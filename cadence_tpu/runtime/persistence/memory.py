"""In-memory persistence backend.

The default store for tests and the onebox cluster (the reference's
equivalent role is its TestBase-managed store). Implements the full
five-manager contract including LWT-style conditional writes — the
concurrency semantics are real even though the medium is a dict.
"""

from __future__ import annotations

import bisect
import copy
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from cadence_tpu.core.events import HistoryEvent, decode_batch, encode_batch
from cadence_tpu.core.tasks import ReplicationTask, TimerTask, TransferTask
from cadence_tpu.utils.locks import make_guarded, make_rlock

from . import interfaces as I
from .errors import (
    ConditionFailedError,
    DomainAlreadyExistsError,
    EntityNotExistsError,
    ShardAlreadyExistsError,
    ShardOwnershipLostError,
    TaskListLeaseLostError,
    WorkflowAlreadyStartedError,
)
from .records import (
    BranchAncestor,
    BranchToken,
    CreateWorkflowMode,
    CurrentExecution,
    DomainRecord,
    GetWorkflowResponse,
    ShardInfo,
    TaskInfo,
    TaskListInfo,
    VisibilityRecord,
    WorkflowSnapshot,
)

_COMPLETED = 2  # WorkflowState.Completed
_ZOMBIE = 3  # WorkflowState.Zombie


class MemoryShardManager(I.ShardManager):
    def __init__(self) -> None:
        self._lock = make_rlock("MemoryShardManager._lock")
        self._shards: Dict[int, ShardInfo] = make_guarded(
            {}, "MemoryShardManager._shards", self._lock
        )
        # singleton routing-epoch row: (epoch, blob) or None
        self._reshard_state: Optional[Tuple[int, str]] = None
        # (shard_id, cluster) -> (version, blob): the consumer-side
        # replication cursor/mode rows (adaptive geo-replication)
        self._replication_progress: Dict[Tuple[int, str], Tuple[int, str]] = {}

    def create_shard(self, info: ShardInfo) -> None:
        with self._lock:
            if info.shard_id in self._shards:
                raise ShardAlreadyExistsError(str(info.shard_id))
            self._shards[info.shard_id] = copy.deepcopy(info)

    def get_shard(self, shard_id: int) -> ShardInfo:
        with self._lock:
            info = self._shards.get(shard_id)
            if info is None:
                raise EntityNotExistsError(f"shard {shard_id}")
            return copy.deepcopy(info)

    def update_shard(self, info: ShardInfo, previous_range_id: int) -> None:
        with self._lock:
            stored = self._shards.get(info.shard_id)
            if stored is None:
                raise EntityNotExistsError(f"shard {info.shard_id}")
            if stored.range_id != previous_range_id:
                raise ShardOwnershipLostError(info.shard_id)
            self._shards[info.shard_id] = copy.deepcopy(info)

    # -- elastic resharding -------------------------------------------

    def get_reshard_state(self) -> Optional[Tuple[int, str]]:
        with self._lock:
            return self._reshard_state

    def set_reshard_state(
        self, epoch: int, blob: str, previous_epoch: int
    ) -> None:
        with self._lock:
            stored = self._reshard_state[0] if self._reshard_state else 0
            if stored != previous_epoch:
                raise ConditionFailedError(
                    f"reshard epoch {stored} != expected {previous_epoch}"
                )
            self._reshard_state = (epoch, blob)

    # -- adaptive geo-replication --------------------------------------

    def get_replication_progress(
        self, shard_id: int, cluster: str
    ) -> Optional[Tuple[int, str]]:
        with self._lock:
            return self._replication_progress.get((shard_id, cluster))

    def set_replication_progress(
        self, shard_id: int, cluster: str, blob: str,
        previous_version: int,
    ) -> None:
        with self._lock:
            key = (shard_id, cluster)
            row = self._replication_progress.get(key)
            stored = row[0] if row else 0
            if stored != previous_version:
                raise ConditionFailedError(
                    f"replication progress version {stored} != "
                    f"expected {previous_version}"
                )
            self._replication_progress[key] = (previous_version + 1, blob)


class MemoryExecutionManager(I.ExecutionManager):
    def __init__(self, shard_manager: MemoryShardManager) -> None:
        self._shard_manager = shard_manager
        self._lock = make_rlock("MemoryExecutionManager._lock")
        # (shard, domain, wf, run) -> (snapshot dict, next_event_id, last_write_version)
        self._executions: Dict[Tuple, Tuple[Dict[str, Any], int, int]] = {}
        # (shard, domain, wf) -> CurrentExecution
        self._current: Dict[Tuple, CurrentExecution] = {}
        # shard -> {task_id -> TransferTask}
        self._transfer: Dict[int, Dict[int, TransferTask]] = {}
        # shard -> {(vis_ts, task_id) -> TimerTask}
        self._timers: Dict[int, Dict[Tuple[int, int], TimerTask]] = {}
        self._replication: Dict[int, Dict[int, ReplicationTask]] = {}

    # -- fencing ------------------------------------------------------

    def _check_range(self, shard_id: int, range_id: int) -> None:
        stored = self._shard_manager.get_shard(shard_id)
        if stored.range_id > range_id:
            raise ShardOwnershipLostError(shard_id)

    # -- helpers ------------------------------------------------------

    def _put_tasks(self, shard_id: int, snap: WorkflowSnapshot) -> None:
        tq = self._transfer.setdefault(shard_id, {})
        for t in snap.transfer_tasks:
            tq[t.task_id] = copy.deepcopy(t)
        mq = self._timers.setdefault(shard_id, {})
        for t in snap.timer_tasks:
            mq[(t.visibility_timestamp, t.task_id)] = copy.deepcopy(t)
        rq = self._replication.setdefault(shard_id, {})
        for t in snap.replication_tasks:
            rq[t.task_id] = copy.deepcopy(t)

    def _store(self, shard_id: int, snap: WorkflowSnapshot) -> None:
        key = (shard_id, snap.domain_id, snap.workflow_id, snap.run_id)
        self._executions[key] = (
            copy.deepcopy(snap.snapshot),
            snap.next_event_id,
            snap.last_write_version,
        )
        self._put_tasks(shard_id, snap)

    def _exec_state(self, snapshot: Dict[str, Any]) -> Tuple[int, int]:
        ex = snapshot.get("execution_info") or snapshot.get("exec") or snapshot
        return int(ex.get("state", 0)), int(ex.get("close_status", 0))

    @staticmethod
    def _request_id(snapshot: Dict[str, Any]) -> str:
        ex = snapshot.get("execution_info") or {}
        return ex.get("create_request_id") or snapshot.get("request_id", "")

    # -- executions ---------------------------------------------------

    def create_workflow_execution(
        self,
        shard_id: int,
        range_id: int,
        mode: int,
        snapshot: WorkflowSnapshot,
        prev_run_id: str = "",
        prev_last_write_version: int = 0,
    ) -> None:
        with self._lock:
            self._check_range(shard_id, range_id)
            cur_key = (shard_id, snapshot.domain_id, snapshot.workflow_id)
            cur = self._current.get(cur_key)
            if mode == CreateWorkflowMode.BRAND_NEW:
                if cur is not None:
                    raise WorkflowAlreadyStartedError(
                        f"workflow {snapshot.workflow_id} already started",
                        cur.create_request_id,
                        cur.run_id,
                        cur.state,
                        cur.close_status,
                        cur.last_write_version,
                    )
            elif mode == CreateWorkflowMode.WORKFLOW_ID_REUSE:
                if cur is None:
                    raise ConditionFailedError("no current execution to reuse")
                if cur.state != _COMPLETED:
                    raise WorkflowAlreadyStartedError(
                        f"workflow {snapshot.workflow_id} still running",
                        cur.create_request_id, cur.run_id, cur.state,
                        cur.close_status, cur.last_write_version,
                    )
                if cur.run_id != prev_run_id:
                    raise ConditionFailedError(
                        f"current run {cur.run_id} != expected {prev_run_id}"
                    )
            elif mode == CreateWorkflowMode.CONTINUE_AS_NEW:
                if cur is None or cur.run_id != prev_run_id:
                    raise ConditionFailedError("continue-as-new current mismatch")
            elif mode == CreateWorkflowMode.ZOMBIE:
                pass
            elif mode == CreateWorkflowMode.SUPPRESS_CURRENT:
                if cur is None or cur.run_id != prev_run_id:
                    raise ConditionFailedError(
                        "suppress-current run mismatch: "
                        f"{cur.run_id if cur else None} != {prev_run_id}"
                    )
                # zombify the stale run's stored record so nothing that
                # reloads it treats it as a live current run
                old_key = (
                    shard_id, snapshot.domain_id, snapshot.workflow_id,
                    cur.run_id,
                )
                old = self._executions.get(old_key)
                if old is not None:
                    snap, next_eid, lwv = old
                    ex = snap.get("execution_info")
                    if isinstance(ex, dict):
                        ex["state"] = _ZOMBIE
                    self._executions[old_key] = (snap, next_eid, lwv)
            else:
                raise ValueError(f"unknown create mode {mode}")

            state, close_status = self._exec_state(snapshot.snapshot)
            if mode != CreateWorkflowMode.ZOMBIE:
                self._current[cur_key] = CurrentExecution(
                    run_id=snapshot.run_id,
                    create_request_id=self._request_id(snapshot.snapshot),
                    state=state,
                    close_status=close_status,
                    last_write_version=snapshot.last_write_version,
                )
            self._store(shard_id, snapshot)

    def get_workflow_execution(
        self, shard_id: int, domain_id: str, workflow_id: str, run_id: str
    ) -> GetWorkflowResponse:
        with self._lock:
            key = (shard_id, domain_id, workflow_id, run_id)
            stored = self._executions.get(key)
            if stored is None:
                raise EntityNotExistsError(f"execution {workflow_id}/{run_id}")
            snap, next_event_id, _ = stored
            return GetWorkflowResponse(
                snapshot=copy.deepcopy(snap), next_event_id=next_event_id
            )

    def update_workflow_execution(
        self,
        shard_id: int,
        range_id: int,
        condition: int,
        mutation: WorkflowSnapshot,
        new_snapshot: Optional[WorkflowSnapshot] = None,
        new_mode: int = CreateWorkflowMode.CONTINUE_AS_NEW,
    ) -> None:
        with self._lock:
            self._check_range(shard_id, range_id)
            key = (
                shard_id, mutation.domain_id, mutation.workflow_id,
                mutation.run_id,
            )
            stored = self._executions.get(key)
            if stored is None:
                raise EntityNotExistsError(
                    f"execution {mutation.workflow_id}/{mutation.run_id}"
                )
            if stored[1] != condition:
                raise ConditionFailedError(
                    f"next_event_id {stored[1]} != condition {condition}"
                )
            self._store(shard_id, mutation)
            cur_key = (shard_id, mutation.domain_id, mutation.workflow_id)
            cur = self._current.get(cur_key)
            state, close_status = self._exec_state(mutation.snapshot)
            if cur is not None and cur.run_id == mutation.run_id:
                cur.state = state
                cur.close_status = close_status
                cur.last_write_version = mutation.last_write_version
            if new_snapshot is not None:
                self.create_workflow_execution(
                    shard_id, range_id, new_mode, new_snapshot,
                    prev_run_id=mutation.run_id,
                )

    def conflict_resolve_workflow_execution(
        self,
        shard_id: int,
        range_id: int,
        condition: int,
        reset_snapshot: WorkflowSnapshot,
    ) -> None:
        with self._lock:
            self._check_range(shard_id, range_id)
            key = (
                shard_id, reset_snapshot.domain_id,
                reset_snapshot.workflow_id, reset_snapshot.run_id,
            )
            stored = self._executions.get(key)
            if stored is not None and stored[1] != condition:
                raise ConditionFailedError(
                    f"next_event_id {stored[1]} != condition {condition}"
                )
            self._store(shard_id, reset_snapshot)
            cur_key = (
                shard_id, reset_snapshot.domain_id, reset_snapshot.workflow_id
            )
            cur = self._current.get(cur_key)
            state, close_status = self._exec_state(reset_snapshot.snapshot)
            if cur is not None and cur.run_id == reset_snapshot.run_id:
                cur.state = state
                cur.close_status = close_status

    def delete_workflow_execution(
        self, shard_id: int, domain_id: str, workflow_id: str, run_id: str
    ) -> None:
        with self._lock:
            self._executions.pop((shard_id, domain_id, workflow_id, run_id), None)

    def delete_current_workflow_execution(
        self, shard_id: int, domain_id: str, workflow_id: str, run_id: str
    ) -> None:
        with self._lock:
            cur_key = (shard_id, domain_id, workflow_id)
            cur = self._current.get(cur_key)
            if cur is not None and cur.run_id == run_id:
                del self._current[cur_key]

    def get_current_execution(
        self, shard_id: int, domain_id: str, workflow_id: str
    ) -> CurrentExecution:
        with self._lock:
            cur = self._current.get((shard_id, domain_id, workflow_id))
            if cur is None:
                raise EntityNotExistsError(f"no current execution {workflow_id}")
            return copy.deepcopy(cur)

    def list_concrete_executions(
        self, shard_id: int
    ) -> List[Tuple[str, str, str]]:
        with self._lock:
            return [
                (d, w, r)
                for (s, d, w, r) in self._executions
                if s == shard_id
            ]

    # -- elastic resharding -------------------------------------------

    def reshard_extract(
        self, shard_id, workflow_ids, transfer_watermark, timer_watermark,
        delete=False,
    ):
        wids = set(workflow_ids)
        out = {"executions": [], "currents": [], "transfer": [],
               "timers": [], "replication": []}
        with self._lock:
            for key in [k for k in self._executions
                        if k[0] == shard_id and k[2] in wids]:
                snap, next_eid, lwv = (
                    self._executions.pop(key) if delete
                    else self._executions[key]
                )
                out["executions"].append({
                    "domain_id": key[1], "workflow_id": key[2],
                    "run_id": key[3], "next_event_id": next_eid,
                    "last_write_version": lwv,
                    "snapshot": copy.deepcopy(snap),
                })
            for key in [k for k in self._current
                        if k[0] == shard_id and k[2] in wids]:
                cur = (
                    self._current.pop(key) if delete else self._current[key]
                )
                out["currents"].append({
                    "domain_id": key[1], "workflow_id": key[2],
                    "run_id": cur.run_id,
                    "create_request_id": cur.create_request_id,
                    "state": cur.state, "close_status": cur.close_status,
                    "last_write_version": cur.last_write_version,
                })
            tq = self._transfer.get(shard_id, {})
            for tid in [tid for tid, t in tq.items()
                        if t.workflow_id in wids
                        and tid > transfer_watermark]:
                out["transfer"].append(
                    tq.pop(tid) if delete else copy.deepcopy(tq[tid])
                )
            mq = self._timers.get(shard_id, {})
            for key in [k for k, t in mq.items()
                        if t.workflow_id in wids
                        and k > tuple(timer_watermark)]:
                out["timers"].append(
                    mq.pop(key) if delete else copy.deepcopy(mq[key])
                )
            rq = self._replication.get(shard_id, {})
            for tid in [tid for tid, t in rq.items()
                        if t.workflow_id in wids]:
                out["replication"].append(
                    rq.pop(tid) if delete else copy.deepcopy(rq[tid])
                )
        for name in out:
            key_fn = {
                "executions": lambda e: (e["workflow_id"], e["run_id"]),
                "currents": lambda e: e["workflow_id"],
                "timers": lambda t: (t.visibility_timestamp, t.task_id),
            }.get(name, lambda t: t.task_id)
            out[name].sort(key=key_fn)
        return out

    def reshard_purge(self, shard_id, extracted):
        with self._lock:
            for e in extracted["executions"]:
                self._executions.pop(
                    (shard_id, e["domain_id"], e["workflow_id"],
                     e["run_id"]), None,
                )
            for c in extracted["currents"]:
                self._current.pop(
                    (shard_id, c["domain_id"], c["workflow_id"]), None
                )
            tq = self._transfer.get(shard_id, {})
            for t in extracted["transfer"]:
                tq.pop(t.task_id, None)
            mq = self._timers.get(shard_id, {})
            for t in extracted["timers"]:
                mq.pop((t.visibility_timestamp, t.task_id), None)
            rq = self._replication.get(shard_id, {})
            for t in extracted["replication"]:
                rq.pop(t.task_id, None)

    def reshard_install(self, shard_id, range_id, extracted, task_id_fn):
        with self._lock:
            stored = self._shard_manager.get_shard(shard_id)
            if stored.range_id != range_id:
                raise ShardOwnershipLostError(shard_id)
            for e in extracted["executions"]:
                key = (shard_id, e["domain_id"], e["workflow_id"],
                       e["run_id"])
                self._executions[key] = (
                    copy.deepcopy(e["snapshot"]),
                    e["next_event_id"], e["last_write_version"],
                )
            for c in extracted["currents"]:
                self._current[(shard_id, c["domain_id"], c["workflow_id"])] \
                    = CurrentExecution(
                        run_id=c["run_id"],
                        create_request_id=c["create_request_id"],
                        state=c["state"], close_status=c["close_status"],
                        last_write_version=c["last_write_version"],
                    )
            tq = self._transfer.setdefault(shard_id, {})
            for t in extracted["transfer"]:
                t = copy.deepcopy(t)
                t.task_id = task_id_fn()
                tq[t.task_id] = t
            mq = self._timers.setdefault(shard_id, {})
            for t in extracted["timers"]:
                t = copy.deepcopy(t)
                t.task_id = task_id_fn()
                mq[(t.visibility_timestamp, t.task_id)] = t
            rq = self._replication.setdefault(shard_id, {})
            for t in extracted["replication"]:
                t = copy.deepcopy(t)
                t.task_id = task_id_fn()
                rq[t.task_id] = t

    # -- transfer queue -----------------------------------------------

    def get_transfer_tasks(
        self, shard_id: int, read_level: int, max_read_level: int, batch_size: int
    ) -> List[TransferTask]:
        with self._lock:
            tasks = sorted(
                (
                    t
                    for tid, t in self._transfer.get(shard_id, {}).items()
                    if read_level < tid <= max_read_level
                ),
                key=lambda t: t.task_id,
            )
            return copy.deepcopy(tasks[:batch_size])

    def complete_transfer_task(self, shard_id: int, task_id: int) -> None:
        with self._lock:
            self._transfer.get(shard_id, {}).pop(task_id, None)

    def range_complete_transfer_tasks(
        self, shard_id: int, exclusive_begin: int, inclusive_end: int
    ) -> None:
        with self._lock:
            q = self._transfer.get(shard_id, {})
            for tid in [t for t in q if exclusive_begin < t <= inclusive_end]:
                del q[tid]

    # -- timer queue --------------------------------------------------

    def get_timer_tasks(
        self, shard_id: int, min_ts: int, max_ts: int, batch_size: int,
        after_key=None,
    ) -> List[TimerTask]:
        with self._lock:
            tasks = sorted(
                (
                    t
                    for (ts, _), t in self._timers.get(shard_id, {}).items()
                    if min_ts <= ts < max_ts
                    and (
                        after_key is None
                        or (ts, t.task_id) > tuple(after_key)
                    )
                ),
                key=lambda t: (t.visibility_timestamp, t.task_id),
            )
            return copy.deepcopy(tasks[:batch_size])

    def complete_timer_task(
        self, shard_id: int, visibility_ts: int, task_id: int
    ) -> None:
        with self._lock:
            self._timers.get(shard_id, {}).pop((visibility_ts, task_id), None)

    def range_complete_timer_tasks(
        self, shard_id: int, inclusive_begin_ts: int, exclusive_end_ts: int
    ) -> None:
        with self._lock:
            q = self._timers.get(shard_id, {})
            for key in [
                k for k in q if inclusive_begin_ts <= k[0] < exclusive_end_ts
            ]:
                del q[key]

    # -- replication queue --------------------------------------------

    def get_replication_tasks(
        self, shard_id: int, read_level: int, batch_size: int
    ) -> List[ReplicationTask]:
        with self._lock:
            tasks = sorted(
                (
                    t
                    for tid, t in self._replication.get(shard_id, {}).items()
                    if tid > read_level
                ),
                key=lambda t: t.task_id,
            )
            return copy.deepcopy(tasks[:batch_size])

    def complete_replication_task(self, shard_id: int, task_id: int) -> None:
        with self._lock:
            self._replication.get(shard_id, {}).pop(task_id, None)


class MemoryHistoryManager(I.HistoryManager):
    def __init__(self) -> None:
        self._lock = make_rlock("MemoryHistoryManager._lock")
        # (tree_id, branch_id) -> {node_id -> (transaction_id, blob)}
        self._nodes: Dict[Tuple[str, str], Dict[int, Tuple[int, bytes]]] = {}
        # tree_id -> {branch_id -> BranchToken}
        self._branches: Dict[str, Dict[str, BranchToken]] = {}

    def new_history_branch(self, tree_id: str) -> BranchToken:
        with self._lock:
            token = BranchToken(tree_id=tree_id, branch_id=str(uuid.uuid4()))
            self._branches.setdefault(tree_id, {})[token.branch_id] = token
            self._nodes.setdefault((tree_id, token.branch_id), {})
            return copy.deepcopy(token)

    def append_history_nodes(
        self,
        branch: BranchToken,
        events: List[HistoryEvent],
        transaction_id: int,
    ) -> int:
        if not events:
            raise ValueError("empty event batch")
        node_id = events[0].event_id
        blob = encode_batch(events)
        with self._lock:
            nodes = self._nodes.setdefault(
                (branch.tree_id, branch.branch_id), {}
            )
            self._branches.setdefault(branch.tree_id, {}).setdefault(
                branch.branch_id, copy.deepcopy(branch)
            )
            existing = nodes.get(node_id)
            if existing is None or existing[0] < transaction_id:
                nodes[node_id] = (transaction_id, blob)
            return len(blob)

    def _branch_node_ranges(
        self, branch: BranchToken
    ) -> List[Tuple[str, int, int]]:
        """(branch_id, begin, end) segments composing this branch's view."""
        segments = [
            (a.branch_id, a.begin_node_id, a.end_node_id)
            for a in branch.ancestors
        ]
        segments.append((branch.branch_id, 1 if not branch.ancestors else
                         branch.ancestors[-1].end_node_id, 2**62))
        return segments

    def read_history_branch(
        self,
        branch: BranchToken,
        min_event_id: int,
        max_event_id: int,
        page_size: int = 0,
        next_token: int = 0,
    ) -> Tuple[List[List[HistoryEvent]], int]:
        with self._lock:
            collected: List[Tuple[int, bytes]] = []
            for branch_id, begin, end in self._branch_node_ranges(branch):
                nodes = self._nodes.get((branch.tree_id, branch_id), {})
                for node_id, (_, blob) in nodes.items():
                    if begin <= node_id < end and (
                        min_event_id <= node_id < max_event_id
                    ) and node_id >= next_token:
                        collected.append((node_id, blob))
            collected.sort(key=lambda x: x[0])
            if page_size and len(collected) > page_size:
                page = collected[:page_size]
                token = collected[page_size][0]
            else:
                page, token = collected, 0
            return [decode_batch(blob) for _, blob in page], token

    def fork_history_branch(
        self, branch: BranchToken, fork_node_id: int
    ) -> BranchToken:
        with self._lock:
            ancestors: List[BranchAncestor] = []
            for a in branch.ancestors:
                if a.end_node_id <= fork_node_id:
                    ancestors.append(copy.deepcopy(a))
                else:
                    ancestors.append(
                        BranchAncestor(
                            a.branch_id, a.begin_node_id, fork_node_id
                        )
                    )
                    break
            else:
                begin = (
                    branch.ancestors[-1].end_node_id if branch.ancestors else 1
                )
                ancestors.append(
                    BranchAncestor(branch.branch_id, begin, fork_node_id)
                )
            token = BranchToken(
                tree_id=branch.tree_id,
                branch_id=str(uuid.uuid4()),
                ancestors=ancestors,
            )
            self._branches.setdefault(branch.tree_id, {})[
                token.branch_id
            ] = token
            self._nodes.setdefault((branch.tree_id, token.branch_id), {})
            return copy.deepcopy(token)

    def delete_history_branch(self, branch: BranchToken) -> None:
        with self._lock:
            tree = self._branches.get(branch.tree_id) or {}
            tree.pop(branch.branch_id, None)
            if branch.tree_id in self._branches and not tree:
                del self._branches[branch.tree_id]
            # Sweep every node range in the tree no surviving branch
            # owns or references as an ancestor segment (shared fork
            # prefix — reference historyV2 deleteBranch keeps shared
            # ranges). Whole-tree sweep also reclaims ranges a
            # previously-deleted ancestor left behind, orphaned exactly
            # when its last descendant goes (ADVICE r4).
            live: dict = {}  # branch_id -> protected end (0 = whole)
            for bid, token in tree.items():
                live[bid] = 0
                for anc in token.ancestors:
                    if live.get(anc.branch_id, 1) != 0:
                        live[anc.branch_id] = max(
                            live.get(anc.branch_id, 0), anc.end_node_id
                        )
            # candidate ranges only (not a store-wide key scan): the
            # deleted branch, its full ancestor chain, and every live
            # branch id cover all ranges this delete can orphan —
            # an orphan outside this set would have been swept when ITS
            # last descendant was deleted (induction)
            candidates = {branch.branch_id}
            candidates.update(a.branch_id for a in branch.ancestors)
            candidates.update(live)
            for bid in candidates:
                key = (branch.tree_id, bid)
                if key not in self._nodes:
                    continue
                end = live.get(bid)
                if end == 0:
                    continue  # a live branch owns the whole range
                if end is None:
                    self._nodes.pop(key, None)
                else:
                    nodes = self._nodes[key]
                    for nid in [n for n in nodes if n >= end]:
                        del nodes[nid]

    def get_history_tree(self, tree_id: str) -> List[BranchToken]:
        with self._lock:
            return [
                copy.deepcopy(t)
                for t in self._branches.get(tree_id, {}).values()
            ]

    def list_history_trees(self):
        """All (tree_id, branches) pairs — the history scavenger's scan
        surface (reference: GetAllHistoryTreeBranches)."""
        with self._lock:
            return [
                (tree_id, [copy.deepcopy(t) for t in branches.values()])
                for tree_id, branches in self._branches.items()
            ]


class MemoryTaskManager(I.TaskManager):
    def __init__(self) -> None:
        self._lock = make_rlock("MemoryTaskManager._lock")
        self._lists: Dict[Tuple[str, str, int], TaskListInfo] = {}
        self._tasks: Dict[Tuple[str, str, int], Dict[int, TaskInfo]] = {}

    def lease_task_list(
        self, domain_id: str, name: str, task_type: int
    ) -> TaskListInfo:
        with self._lock:
            key = (domain_id, name, task_type)
            info = self._lists.get(key)
            if info is None:
                info = TaskListInfo(
                    domain_id=domain_id, name=name, task_type=task_type
                )
            info = copy.deepcopy(info)
            info.range_id += 1
            info.last_updated = time.time_ns()
            self._lists[key] = copy.deepcopy(info)
            return info

    def update_task_list(self, info: TaskListInfo) -> None:
        with self._lock:
            key = (info.domain_id, info.name, info.task_type)
            stored = self._lists.get(key)
            if stored is None or stored.range_id != info.range_id:
                raise TaskListLeaseLostError(info.name)
            info.last_updated = time.time_ns()
            self._lists[key] = copy.deepcopy(info)

    def create_tasks(
        self, info: TaskListInfo, tasks: List[TaskInfo]
    ) -> None:
        with self._lock:
            key = (info.domain_id, info.name, info.task_type)
            stored = self._lists.get(key)
            if stored is None or stored.range_id != info.range_id:
                raise TaskListLeaseLostError(info.name)
            bucket = self._tasks.setdefault(key, {})
            for t in tasks:
                bucket[t.task_id] = copy.deepcopy(t)

    def get_tasks(
        self,
        domain_id: str,
        name: str,
        task_type: int,
        read_level: int,
        max_read_level: int,
        batch_size: int,
    ) -> List[TaskInfo]:
        with self._lock:
            bucket = self._tasks.get((domain_id, name, task_type), {})
            tasks = sorted(
                (
                    t
                    for tid, t in bucket.items()
                    if read_level < tid <= max_read_level
                ),
                key=lambda t: t.task_id,
            )
            return copy.deepcopy(tasks[:batch_size])

    def complete_task(
        self, domain_id: str, name: str, task_type: int, task_id: int
    ) -> None:
        with self._lock:
            self._tasks.get((domain_id, name, task_type), {}).pop(task_id, None)

    def complete_tasks_less_than(
        self, domain_id: str, name: str, task_type: int, task_id: int
    ) -> int:
        with self._lock:
            bucket = self._tasks.get((domain_id, name, task_type), {})
            victims = [tid for tid in bucket if tid < task_id]
            for tid in victims:
                del bucket[tid]
            return len(victims)

    def list_task_lists(self) -> List[TaskListInfo]:
        with self._lock:
            return [copy.deepcopy(i) for i in self._lists.values()]

    def delete_task_list(
        self, domain_id: str, name: str, task_type: int, range_id: int
    ) -> None:
        with self._lock:
            key = (domain_id, name, task_type)
            stored = self._lists.get(key)
            if stored is None:
                return
            if stored.range_id != range_id:
                raise TaskListLeaseLostError(name)
            del self._lists[key]
            self._tasks.pop(key, None)


class MemoryMetadataManager(I.MetadataManager):
    def __init__(self) -> None:
        self._lock = make_rlock("MemoryMetadataManager._lock")
        self._by_id: Dict[str, DomainRecord] = {}
        self._name_to_id: Dict[str, str] = {}
        self._notification_version = 0

    def create_domain(self, record: DomainRecord) -> str:
        with self._lock:
            if record.info.name in self._name_to_id:
                raise DomainAlreadyExistsError(record.info.name)
            record = copy.deepcopy(record)
            if not record.info.id:
                record.info.id = str(uuid.uuid4())
            record.notification_version = self._notification_version
            self._notification_version += 1
            self._by_id[record.info.id] = record
            self._name_to_id[record.info.name] = record.info.id
            return record.info.id

    def _resolve(self, id: str, name: str) -> DomainRecord:
        if id:
            rec = self._by_id.get(id)
        elif name:
            rec = self._by_id.get(self._name_to_id.get(name, ""))
        else:
            raise ValueError("id or name required")
        if rec is None:
            raise EntityNotExistsError(f"domain {id or name}")
        return rec

    def get_domain(self, id: str = "", name: str = "") -> DomainRecord:
        with self._lock:
            return copy.deepcopy(self._resolve(id, name))

    def update_domain(self, record: DomainRecord) -> None:
        with self._lock:
            stored = self._by_id.get(record.info.id)
            if stored is None:
                raise EntityNotExistsError(f"domain {record.info.id}")
            record = copy.deepcopy(record)
            record.notification_version = self._notification_version
            self._notification_version += 1
            if stored.info.name != record.info.name:
                del self._name_to_id[stored.info.name]
                self._name_to_id[record.info.name] = record.info.id
            self._by_id[record.info.id] = record

    def delete_domain(self, id: str = "", name: str = "") -> None:
        with self._lock:
            try:
                rec = self._resolve(id, name)
            except EntityNotExistsError:
                return
            del self._by_id[rec.info.id]
            del self._name_to_id[rec.info.name]

    def list_domains(self) -> List[DomainRecord]:
        with self._lock:
            return [copy.deepcopy(r) for r in self._by_id.values()]

    def get_metadata_version(self) -> int:
        with self._lock:
            return self._notification_version


class MemoryVisibilityManager(I.VisibilityManager):
    def __init__(self) -> None:
        self._lock = make_rlock("MemoryVisibilityManager._lock")
        # domain -> {(wf, run) -> record}
        self._open: Dict[str, Dict[Tuple[str, str], VisibilityRecord]] = {}
        self._closed: Dict[str, Dict[Tuple[str, str], VisibilityRecord]] = {}

    def record_workflow_execution_started(self, rec: VisibilityRecord) -> None:
        with self._lock:
            self._open.setdefault(rec.domain_id, {})[
                (rec.workflow_id, rec.run_id)
            ] = copy.deepcopy(rec)

    def record_workflow_execution_closed(self, rec: VisibilityRecord) -> None:
        with self._lock:
            self._open.get(rec.domain_id, {}).pop(
                (rec.workflow_id, rec.run_id), None
            )
            self._closed.setdefault(rec.domain_id, {})[
                (rec.workflow_id, rec.run_id)
            ] = copy.deepcopy(rec)

    def upsert_workflow_execution(self, rec: VisibilityRecord) -> None:
        with self._lock:
            bucket = self._open.setdefault(rec.domain_id, {})
            key = (rec.workflow_id, rec.run_id)
            if key in bucket:
                bucket[key] = copy.deepcopy(rec)
            else:
                self._closed.setdefault(rec.domain_id, {})[key] = copy.deepcopy(rec)

    def _list(
        self,
        store: Dict[str, Dict[Tuple[str, str], VisibilityRecord]],
        domain_id: str,
        earliest_start: int,
        latest_start: int,
        workflow_type: str,
        workflow_id: str,
        close_status: int,
        page_size: int,
        next_token: int,
    ) -> Tuple[List[VisibilityRecord], int]:
        records = [
            r
            for r in store.get(domain_id, {}).values()
            if earliest_start <= r.start_time <= latest_start
            and (not workflow_type or r.workflow_type == workflow_type)
            and (not workflow_id or r.workflow_id == workflow_id)
            and (close_status < 0 or r.close_status == close_status)
        ]
        records.sort(key=lambda r: (-r.start_time, r.workflow_id, r.run_id))
        page = records[next_token : next_token + page_size]
        token = next_token + page_size if next_token + page_size < len(records) else 0
        return copy.deepcopy(page), token

    def list_open_workflow_executions(
        self, domain_id, earliest_start=0, latest_start=2**63 - 1,
        workflow_type="", workflow_id="", page_size=100, next_token=0,
    ):
        with self._lock:
            return self._list(
                self._open, domain_id, earliest_start, latest_start,
                workflow_type, workflow_id, -1, page_size, next_token,
            )

    def list_closed_workflow_executions(
        self, domain_id, earliest_start=0, latest_start=2**63 - 1,
        workflow_type="", workflow_id="", close_status=-1,
        page_size=100, next_token=0,
    ):
        with self._lock:
            return self._list(
                self._closed, domain_id, earliest_start, latest_start,
                workflow_type, workflow_id, close_status, page_size, next_token,
            )

    def get_closed_workflow_execution(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> VisibilityRecord:
        with self._lock:
            if run_id:
                rec = self._closed.get(domain_id, {}).get((workflow_id, run_id))
            else:
                matches = [
                    r
                    for (w, _), r in self._closed.get(domain_id, {}).items()
                    if w == workflow_id
                ]
                rec = max(matches, key=lambda r: r.close_time) if matches else None
            if rec is None:
                raise EntityNotExistsError(f"closed {workflow_id}/{run_id}")
            return copy.deepcopy(rec)

    def count_workflow_executions(
        self, domain_id: str, open_only: bool = False
    ) -> int:
        with self._lock:
            n = len(self._open.get(domain_id, {}))
            if not open_only:
                n += len(self._closed.get(domain_id, {}))
            return n

    def delete_workflow_execution(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> None:
        with self._lock:
            self._open.get(domain_id, {}).pop((workflow_id, run_id), None)
            self._closed.get(domain_id, {}).pop((workflow_id, run_id), None)


def create_memory_bundle() -> I.PersistenceBundle:
    from cadence_tpu.checkpoint.store import MemoryCheckpointStore

    shard = MemoryShardManager()
    return I.PersistenceBundle(
        shard=shard,
        execution=MemoryExecutionManager(shard),
        history=MemoryHistoryManager(),
        task=MemoryTaskManager(),
        metadata=MemoryMetadataManager(),
        visibility=MemoryVisibilityManager(),
        checkpoint=MemoryCheckpointStore(),
    )
