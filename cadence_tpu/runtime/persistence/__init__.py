"""Persistence: the five-manager storage contract + backends.

Reference model: /root/reference/common/persistence/dataInterfaces.go
(manager interfaces at :1470-1596) with Cassandra and SQL plugins; here a
memory backend (tests, onebox) and a SQLite backend (durable single
node) implement the identical contract, exercised by one conformance
suite (tests/test_persistence.py) — the reference's persistence-tests
pattern."""

from .errors import (
    ConditionFailedError,
    DomainAlreadyExistsError,
    EntityNotExistsError,
    PersistenceError,
    ShardAlreadyExistsError,
    ShardOwnershipLostError,
    TaskListLeaseLostError,
    WorkflowAlreadyStartedError,
)
from .interfaces import (
    ExecutionManager,
    HistoryManager,
    MetadataManager,
    PersistenceBundle,
    ShardManager,
    TaskManager,
    VisibilityManager,
)
from .memory import create_memory_bundle
from .records import (
    BranchAncestor,
    BranchToken,
    CreateWorkflowMode,
    CurrentExecution,
    DomainConfig,
    DomainInfo,
    DomainRecord,
    DomainReplicationConfig,
    GetWorkflowResponse,
    ShardInfo,
    TaskInfo,
    TaskListInfo,
    TaskType,
    VisibilityRecord,
    WorkflowSnapshot,
)
from .sqlite import create_sqlite_bundle
