"""Versioned sqlite schema migrations + boot-time compat check.

Reference: tools/cassandra/handler.go (setup-schema / update-schema
over the versioned dirs in schema/cassandra/cadence/versioned/) and the
server's boot compat check (cmd/server/cadence.go:66 — refuse to start
against a store whose schema the binary doesn't understand).
"""

from __future__ import annotations

import time
from typing import List, Tuple

_V1_BASE = """
CREATE TABLE IF NOT EXISTS shards (
  shard_id INTEGER PRIMARY KEY, range_id INTEGER NOT NULL, blob TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS executions (
  shard_id INTEGER, domain_id TEXT, workflow_id TEXT, run_id TEXT,
  next_event_id INTEGER NOT NULL, last_write_version INTEGER NOT NULL,
  snapshot TEXT NOT NULL,
  PRIMARY KEY (shard_id, domain_id, workflow_id, run_id));
CREATE TABLE IF NOT EXISTS current_executions (
  shard_id INTEGER, domain_id TEXT, workflow_id TEXT,
  run_id TEXT NOT NULL, create_request_id TEXT, state INTEGER,
  close_status INTEGER, last_write_version INTEGER,
  PRIMARY KEY (shard_id, domain_id, workflow_id));
CREATE TABLE IF NOT EXISTS transfer_tasks (
  shard_id INTEGER, task_id INTEGER, blob TEXT NOT NULL,
  PRIMARY KEY (shard_id, task_id));
CREATE TABLE IF NOT EXISTS timer_tasks (
  shard_id INTEGER, visibility_ts INTEGER, task_id INTEGER, blob TEXT NOT NULL,
  PRIMARY KEY (shard_id, visibility_ts, task_id));
CREATE TABLE IF NOT EXISTS replication_tasks (
  shard_id INTEGER, task_id INTEGER, blob TEXT NOT NULL,
  PRIMARY KEY (shard_id, task_id));
CREATE TABLE IF NOT EXISTS history_nodes (
  tree_id TEXT, branch_id TEXT, node_id INTEGER, txn_id INTEGER, blob BLOB,
  PRIMARY KEY (tree_id, branch_id, node_id));
CREATE TABLE IF NOT EXISTS history_branches (
  tree_id TEXT, branch_id TEXT, token TEXT NOT NULL,
  PRIMARY KEY (tree_id, branch_id));
CREATE TABLE IF NOT EXISTS task_lists (
  domain_id TEXT, name TEXT, task_type INTEGER,
  range_id INTEGER NOT NULL, ack_level INTEGER NOT NULL, kind INTEGER,
  last_updated INTEGER,
  PRIMARY KEY (domain_id, name, task_type));
CREATE TABLE IF NOT EXISTS tasks (
  domain_id TEXT, name TEXT, task_type INTEGER, task_id INTEGER,
  blob TEXT NOT NULL,
  PRIMARY KEY (domain_id, name, task_type, task_id));
CREATE TABLE IF NOT EXISTS domains (
  id TEXT PRIMARY KEY, name TEXT UNIQUE NOT NULL, blob TEXT NOT NULL,
  notification_version INTEGER NOT NULL);
CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v INTEGER NOT NULL);
CREATE TABLE IF NOT EXISTS visibility (
  domain_id TEXT, workflow_id TEXT, run_id TEXT, is_open INTEGER,
  start_time INTEGER, close_time INTEGER, close_status INTEGER,
  workflow_type TEXT, blob TEXT NOT NULL,
  PRIMARY KEY (domain_id, workflow_id, run_id));
"""

_V2_QUERY_INDEXES = """
CREATE INDEX IF NOT EXISTS idx_visibility_open
  ON visibility (domain_id, is_open, start_time);
CREATE INDEX IF NOT EXISTS idx_visibility_close
  ON visibility (domain_id, close_time);
CREATE INDEX IF NOT EXISTS idx_timer_due
  ON timer_tasks (shard_id, visibility_ts);
CREATE INDEX IF NOT EXISTS idx_current_by_domain
  ON current_executions (shard_id, domain_id);
"""

_V3_REPLAY_CHECKPOINTS = """
CREATE TABLE IF NOT EXISTS replay_checkpoints (
  branch_key TEXT, event_id INTEGER, tree_id TEXT, fingerprint TEXT,
  created_at INTEGER, blob TEXT NOT NULL,
  PRIMARY KEY (branch_key, event_id));
CREATE INDEX IF NOT EXISTS idx_ckpt_tree
  ON replay_checkpoints (tree_id, event_id);
"""

_V4_RESHARD_STATE = """
CREATE TABLE IF NOT EXISTS reshard_state (
  id INTEGER PRIMARY KEY CHECK (id = 0),
  epoch INTEGER NOT NULL, blob TEXT NOT NULL);
"""

_V5_REPLICATION_PROGRESS = """
CREATE TABLE IF NOT EXISTS replication_progress (
  shard_id INTEGER, cluster TEXT,
  version INTEGER NOT NULL, blob TEXT NOT NULL,
  PRIMARY KEY (shard_id, cluster));
"""

# (version, name, script) — append-only, like the reference's
# schema/cassandra/cadence/versioned/ dirs
MIGRATIONS: List[Tuple[int, str, str]] = [
    (1, "base", _V1_BASE),
    (2, "query indexes", _V2_QUERY_INDEXES),
    (3, "replay checkpoints", _V3_REPLAY_CHECKPOINTS),
    (4, "reshard state", _V4_RESHARD_STATE),
    (5, "replication progress", _V5_REPLICATION_PROGRESS),
]

CURRENT_SCHEMA_VERSION = MIGRATIONS[-1][0]


class SchemaVersionError(RuntimeError):
    pass


def _ensure_version_table(conn) -> None:
    conn.execute(
        "CREATE TABLE IF NOT EXISTS schema_version "
        "(version INTEGER PRIMARY KEY, name TEXT NOT NULL, "
        "applied_at INTEGER NOT NULL)"
    )


def get_schema_version(conn) -> int:
    """0 = empty database; pre-versioning databases (tables but no
    version table) read as 1 (the baseline they were created from)."""
    has_version_table = conn.execute(
        "SELECT 1 FROM sqlite_master WHERE type='table' "
        "AND name='schema_version'"
    ).fetchone()
    if has_version_table:
        row = conn.execute(
            "SELECT MAX(version) FROM schema_version"
        ).fetchone()
        return int(row[0] or 0)
    has_base = conn.execute(
        "SELECT 1 FROM sqlite_master WHERE type='table' "
        "AND name='executions'"
    ).fetchone()
    return 1 if has_base else 0


def update_schema(conn) -> List[Tuple[int, str]]:
    """Apply every pending migration; returns [(version, name)]
    applied. Idempotent (ref tools/cassandra update-schema)."""
    # read BEFORE creating the version table: a pre-versioning database
    # (tables, no stamps) must read as its baseline, not as empty
    current = get_schema_version(conn)
    _ensure_version_table(conn)
    applied: List[Tuple[int, str]] = []
    for version, name, script in MIGRATIONS:
        if version <= current:
            # stamp pre-versioning baselines so the table is complete
            conn.execute(
                "INSERT OR IGNORE INTO schema_version VALUES (?,?,?)",
                (version, name, int(time.time())),
            )
            continue
        conn.executescript(script)
        conn.execute(
            "INSERT OR IGNORE INTO schema_version VALUES (?,?,?)",
            (version, name, int(time.time())),
        )
        applied.append((version, name))
    conn.commit()
    return applied


def setup_schema(conn) -> List[Tuple[int, str]]:
    return update_schema(conn)


def check_compat(conn) -> None:
    """Boot-time gate (ref cmd/server/cadence.go:66): refuse to run
    against a database the code doesn't match."""
    version = get_schema_version(conn)
    if version > CURRENT_SCHEMA_VERSION:
        raise SchemaVersionError(
            f"database schema v{version} is NEWER than this build "
            f"(v{CURRENT_SCHEMA_VERSION}); refusing to start"
        )
    if version < CURRENT_SCHEMA_VERSION:
        raise SchemaVersionError(
            f"database schema v{version} is behind this build "
            f"(v{CURRENT_SCHEMA_VERSION}); run "
            f"`cadence-tpu schema update` first"
        )
