"""The five-manager persistence contract.

Abstract base classes mirroring the reference's manager interfaces
(/root/reference/common/persistence/dataInterfaces.go:1470-1596 and
visibilityInterfaces.go:167). Every backend (memory, sqlite) implements
all of them; the conformance suite in tests/test_persistence.py runs
identically against each — the reference's persistence-tests pattern.

Concurrency contract (identical to the reference):
  * every execution write carries the shard's ``range_id``; a stored
    range_id greater than the caller's fences the write with
    ShardOwnershipLostError (Cassandra LWT ``IF range_id = ?``,
    reference cassandraPersistence.go:397-406);
  * update_workflow_execution additionally carries ``condition`` — the
    next_event_id read at load; mismatch raises ConditionFailedError and
    the caller re-loads and retries (Update_History_Loop);
  * task-list writes carry the lease range_id the same way.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.tasks import ReplicationTask, TimerTask, TransferTask

from .records import (
    BranchToken,
    CurrentExecution,
    DomainRecord,
    GetWorkflowResponse,
    ShardInfo,
    TaskInfo,
    TaskListInfo,
    VisibilityRecord,
    WorkflowSnapshot,
)


class ShardManager:
    def create_shard(self, info: ShardInfo) -> None:
        raise NotImplementedError

    def get_shard(self, shard_id: int) -> ShardInfo:
        raise NotImplementedError

    def update_shard(self, info: ShardInfo, previous_range_id: int) -> None:
        """Conditioned on the stored range_id == previous_range_id."""
        raise NotImplementedError

    # -- elastic resharding (runtime/resharding.py) -------------------

    def get_reshard_state(self) -> Optional[Tuple[int, str]]:
        """The singleton routing-epoch row: ``(epoch, blob)`` where the
        blob carries the committed ShardMap + the in-flight/last
        ReshardPlan (the reconfiguration write-ahead record), or None
        when no reshard was ever attempted."""
        raise NotImplementedError

    def set_reshard_state(
        self, epoch: int, blob: str, previous_epoch: int
    ) -> None:
        """LWT on the stored epoch (an absent row reads as epoch 0):
        raises ConditionFailedError when ``previous_epoch`` doesn't
        match — two coordinators can never both commit an epoch."""
        raise NotImplementedError

    # -- adaptive geo-replication (runtime/replication/) ---------------

    def get_replication_progress(
        self, shard_id: int, cluster: str
    ) -> Optional[Tuple[int, str]]:
        """The consumer-side replication progress row for one
        (shard, remote cluster) link: ``(version, blob)`` where the
        blob carries the durably applied cursor + transport mode
        (processor._progress_blob), or None when the link has never
        persisted progress."""
        raise NotImplementedError

    def set_replication_progress(
        self, shard_id: int, cluster: str, blob: str,
        previous_version: int,
    ) -> None:
        """LWT on the stored version (an absent row reads as version
        0); the stored version becomes ``previous_version + 1``. Raises
        ConditionFailedError on mismatch — same torn-write-retry
        discipline as ``set_reshard_state``: a retry that re-reads the
        blob it meant to write treats the torn write as landed."""
        raise NotImplementedError


class ExecutionManager:
    """Per-shard workflow-execution store + transfer/timer/replication
    queues (the queues live here because they commit atomically with the
    execution write, as in the reference's batched LWT)."""

    # -- executions ---------------------------------------------------

    def create_workflow_execution(
        self,
        shard_id: int,
        range_id: int,
        mode: int,
        snapshot: WorkflowSnapshot,
        prev_run_id: str = "",
        prev_last_write_version: int = 0,
    ) -> None:
        raise NotImplementedError

    def get_workflow_execution(
        self, shard_id: int, domain_id: str, workflow_id: str, run_id: str
    ) -> GetWorkflowResponse:
        raise NotImplementedError

    def update_workflow_execution(
        self,
        shard_id: int,
        range_id: int,
        condition: int,
        mutation: WorkflowSnapshot,
        new_snapshot: Optional[WorkflowSnapshot] = None,
        new_mode: int = 2,  # CreateWorkflowMode.CONTINUE_AS_NEW
    ) -> None:
        """Update current run; optionally create the continue-as-new run
        atomically."""
        raise NotImplementedError

    def conflict_resolve_workflow_execution(
        self,
        shard_id: int,
        range_id: int,
        condition: int,
        reset_snapshot: WorkflowSnapshot,
    ) -> None:
        """Replace mutable state wholesale (reset / NDC conflict resolve)."""
        raise NotImplementedError

    def delete_workflow_execution(
        self, shard_id: int, domain_id: str, workflow_id: str, run_id: str
    ) -> None:
        raise NotImplementedError

    def delete_current_workflow_execution(
        self, shard_id: int, domain_id: str, workflow_id: str, run_id: str
    ) -> None:
        raise NotImplementedError

    def get_current_execution(
        self, shard_id: int, domain_id: str, workflow_id: str
    ) -> CurrentExecution:
        raise NotImplementedError

    def list_concrete_executions(
        self, shard_id: int
    ) -> List[Tuple[str, str, str]]:
        """(domain_id, workflow_id, run_id) triples — scavenger support."""
        raise NotImplementedError

    # -- elastic resharding (runtime/resharding.py) -------------------

    def reshard_extract(
        self,
        shard_id: int,
        workflow_ids: List[str],
        transfer_watermark: int,
        timer_watermark: Tuple[int, int],
        delete: bool = False,
    ) -> Dict[str, list]:
        """Collect everything of ``workflow_ids`` that must move with a
        shard handoff: execution rows, current-execution rows, and the
        pending queue tasks past the drained ack watermarks (tasks
        at/below a watermark are durably complete and stay behind).
        Replication tasks for the moved workflows move wholesale (their
        per-cluster read cursors are shard-local, so moved tasks are
        re-minted above the target's cursor).

        ``delete=False`` is a pure read — the coordinator's
        copy-then-purge move keeps the source rows intact until the
        target copy durably landed (crash-safe in every window);
        ``delete=True`` removes atomically (rollback cleanup).

        Returns ``{"executions", "currents", "transfer", "timers",
        "replication"}`` — the exact payload ``reshard_install``
        accepts, on this or any other backend of the same schema."""
        raise NotImplementedError

    def reshard_install(
        self,
        shard_id: int,
        range_id: int,
        extracted: Dict[str, list],
        task_id_fn,
    ) -> None:
        """Atomically install an extracted payload under ``shard_id``,
        re-minting every queue task id from ``task_id_fn`` (the target
        shard's block sequencer — moved tasks can never regress or
        collide with the target's ids). Conditioned on the target's
        stored range_id == ``range_id`` (all-or-nothing: a fenced or
        partially-failed install leaves the target untouched)."""
        raise NotImplementedError

    def reshard_purge(
        self, shard_id: int, extracted: Dict[str, list]
    ) -> None:
        """Delete exactly the rows named in an extracted payload from
        ``shard_id`` (by ORIGINAL task ids) — the final step of a
        copy-then-purge move. Idempotent."""
        raise NotImplementedError

    # -- transfer queue -----------------------------------------------

    def get_transfer_tasks(
        self, shard_id: int, read_level: int, max_read_level: int, batch_size: int
    ) -> List[TransferTask]:
        raise NotImplementedError

    def complete_transfer_task(self, shard_id: int, task_id: int) -> None:
        raise NotImplementedError

    def range_complete_transfer_tasks(
        self, shard_id: int, exclusive_begin: int, inclusive_end: int
    ) -> None:
        raise NotImplementedError

    # -- timer queue --------------------------------------------------

    def get_timer_tasks(
        self, shard_id: int, min_ts: int, max_ts: int, batch_size: int,
        after_key: Optional[Tuple[int, int]] = None,
    ) -> List[TimerTask]:
        """Tasks with min_ts <= visibility_timestamp < max_ts, ordered
        by (visibility_timestamp, task_id). ``after_key`` is an
        EXCLUSIVE (ts, task_id) resume cursor: pumps page past held
        (deferred) tasks with it, so a span of waiting standby tasks
        cannot starve everything behind them."""
        raise NotImplementedError

    def complete_timer_task(
        self, shard_id: int, visibility_ts: int, task_id: int
    ) -> None:
        raise NotImplementedError

    def range_complete_timer_tasks(
        self, shard_id: int, inclusive_begin_ts: int, exclusive_end_ts: int
    ) -> None:
        raise NotImplementedError

    # -- replication queue --------------------------------------------

    def get_replication_tasks(
        self, shard_id: int, read_level: int, batch_size: int
    ) -> List[ReplicationTask]:
        raise NotImplementedError

    def complete_replication_task(self, shard_id: int, task_id: int) -> None:
        raise NotImplementedError


class HistoryManager:
    """History-as-tree: append-only branches of event-batch nodes
    (reference: historyV2Store.go; node_id == first event id of batch)."""

    def new_history_branch(self, tree_id: str) -> BranchToken:
        raise NotImplementedError

    def append_history_nodes(
        self,
        branch: BranchToken,
        events: List[HistoryEvent],
        transaction_id: int,
    ) -> int:
        """Returns stored size in bytes. Highest transaction_id wins on
        node-id collision (reference's fork/conflict discipline)."""
        raise NotImplementedError

    def read_history_branch(
        self,
        branch: BranchToken,
        min_event_id: int,
        max_event_id: int,
        page_size: int = 0,
        next_token: int = 0,
    ) -> Tuple[List[List[HistoryEvent]], int]:
        """Batches with min_event_id <= first event id < max_event_id.
        Returns (batches, next_token); next_token 0 == done."""
        raise NotImplementedError

    def fork_history_branch(
        self, branch: BranchToken, fork_node_id: int
    ) -> BranchToken:
        """New branch whose ancestor chain covers [..., fork_node_id)."""
        raise NotImplementedError

    def delete_history_branch(self, branch: BranchToken) -> None:
        raise NotImplementedError

    def get_history_tree(self, tree_id: str) -> List[BranchToken]:
        raise NotImplementedError


class TaskManager:
    """Matching task storage (reference: TaskManager,
    dataInterfaces.go:1520-1540 + taskListManager lease semantics)."""

    def lease_task_list(
        self, domain_id: str, name: str, task_type: int
    ) -> TaskListInfo:
        """Creates if absent; bumps range_id (a new lease)."""
        raise NotImplementedError

    def update_task_list(self, info: TaskListInfo) -> None:
        """Conditioned on stored range_id == info.range_id."""
        raise NotImplementedError

    def create_tasks(
        self, info: TaskListInfo, tasks: List[TaskInfo]
    ) -> None:
        raise NotImplementedError

    def get_tasks(
        self,
        domain_id: str,
        name: str,
        task_type: int,
        read_level: int,
        max_read_level: int,
        batch_size: int,
    ) -> List[TaskInfo]:
        raise NotImplementedError

    def complete_task(
        self, domain_id: str, name: str, task_type: int, task_id: int
    ) -> None:
        raise NotImplementedError

    def complete_tasks_less_than(
        self, domain_id: str, name: str, task_type: int, task_id: int
    ) -> int:
        raise NotImplementedError

    def list_task_lists(self) -> List[TaskListInfo]:
        raise NotImplementedError

    def delete_task_list(
        self, domain_id: str, name: str, task_type: int, range_id: int
    ) -> None:
        raise NotImplementedError


class MetadataManager:
    """Domain CRUD (reference: MetadataManager + domain notification
    versions driving cache refresh)."""

    def create_domain(self, record: DomainRecord) -> str:
        raise NotImplementedError

    def get_domain(
        self, id: str = "", name: str = ""
    ) -> DomainRecord:
        raise NotImplementedError

    def update_domain(self, record: DomainRecord) -> None:
        raise NotImplementedError

    def delete_domain(self, id: str = "", name: str = "") -> None:
        raise NotImplementedError

    def list_domains(self) -> List[DomainRecord]:
        raise NotImplementedError

    def get_metadata_version(self) -> int:
        raise NotImplementedError


class VisibilityManager:
    def record_workflow_execution_started(self, rec: VisibilityRecord) -> None:
        raise NotImplementedError

    def record_workflow_execution_closed(self, rec: VisibilityRecord) -> None:
        raise NotImplementedError

    def upsert_workflow_execution(self, rec: VisibilityRecord) -> None:
        raise NotImplementedError

    def list_open_workflow_executions(
        self,
        domain_id: str,
        earliest_start: int = 0,
        latest_start: int = 2**63 - 1,
        workflow_type: str = "",
        workflow_id: str = "",
        page_size: int = 100,
        next_token: int = 0,
    ) -> Tuple[List[VisibilityRecord], int]:
        raise NotImplementedError

    def list_closed_workflow_executions(
        self,
        domain_id: str,
        earliest_start: int = 0,
        latest_start: int = 2**63 - 1,
        workflow_type: str = "",
        workflow_id: str = "",
        close_status: int = -1,
        page_size: int = 100,
        next_token: int = 0,
    ) -> Tuple[List[VisibilityRecord], int]:
        raise NotImplementedError

    def get_closed_workflow_execution(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> VisibilityRecord:
        raise NotImplementedError

    def count_workflow_executions(
        self, domain_id: str, open_only: bool = False
    ) -> int:
        raise NotImplementedError

    def delete_workflow_execution(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> None:
        raise NotImplementedError


class PersistenceBundle:
    """All managers for one datastore — what a backend factory returns.

    ``checkpoint`` (a cadence_tpu.checkpoint.store.CheckpointStore) is
    optional: it rides in the bundle so the decorator factory
    (``wrap_bundle``) stacks metrics/fault-injection over checkpoint
    I/O exactly like the five core managers, but nothing in the
    runtime requires it — a None store simply disables checkpointed
    incremental replay."""

    def __init__(
        self,
        shard: ShardManager,
        execution: ExecutionManager,
        history: HistoryManager,
        task: TaskManager,
        metadata: MetadataManager,
        visibility: VisibilityManager,
        checkpoint=None,
    ) -> None:
        self.shard = shard
        self.execution = execution
        self.history = history
        self.task = task
        self.metadata = metadata
        self.visibility = visibility
        self.checkpoint = checkpoint

    def close(self) -> None:
        pass
