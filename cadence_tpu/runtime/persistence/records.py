"""Storage record types shared by every persistence backend.

The manager-facing model (reference: common/persistence/dataInterfaces.go).
One deliberate simplification vs the reference: workflow executions are
persisted as the full MutableState snapshot dict (core MutableState
.snapshot()/.from_snapshot()) conditioned on next_event_id, instead of the
reference's snapshot+per-map-mutation split — same optimistic-concurrency
contract, far less surface. Histories remain the source of truth; the
snapshot is the replay-avoidance cache, exactly as in the reference.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

from cadence_tpu.core.tasks import ReplicationTask, TimerTask, TransferTask

# -- shard ----------------------------------------------------------------


@dataclasses.dataclass
class ShardInfo:
    shard_id: int
    owner: str = ""
    range_id: int = 0
    transfer_ack_level: int = 0
    timer_ack_level: int = 0            # ns timestamp
    replication_ack_level: int = 0
    # per remote cluster ack levels (NDC)
    cluster_transfer_ack_level: Dict[str, int] = dataclasses.field(default_factory=dict)
    cluster_timer_ack_level: Dict[str, int] = dataclasses.field(default_factory=dict)
    domain_notification_version: int = 0
    stolen_since_renew: int = 0
    update_time: int = 0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "ShardInfo":
        return cls(**json.loads(s))


# -- executions -----------------------------------------------------------


class CreateWorkflowMode:
    BRAND_NEW = 0
    WORKFLOW_ID_REUSE = 1
    CONTINUE_AS_NEW = 2
    ZOMBIE = 3  # replication-created, not the current run
    # replication-created with a NEWER version than a still-running
    # current run: the stale run is zombified and the incoming run takes
    # the current record (ref nDCTransactionPolicySuppressCurrentAndCreateAsCurrent,
    # nDCTransactionMgrForNewWorkflow.go)
    SUPPRESS_CURRENT = 4


@dataclasses.dataclass
class WorkflowSnapshot:
    """A durable workflow execution: MutableState snapshot + queue tasks
    to enqueue atomically with it."""

    domain_id: str
    workflow_id: str
    run_id: str
    snapshot: Dict[str, Any]            # MutableState.snapshot()
    next_event_id: int                  # the write's condition value
    last_write_version: int = 0
    transfer_tasks: List[TransferTask] = dataclasses.field(default_factory=list)
    timer_tasks: List[TimerTask] = dataclasses.field(default_factory=list)
    replication_tasks: List[ReplicationTask] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class CurrentExecution:
    run_id: str
    create_request_id: str
    state: int
    close_status: int
    last_write_version: int


@dataclasses.dataclass
class GetWorkflowResponse:
    snapshot: Dict[str, Any]
    next_event_id: int                  # condition for the next update


# -- history tree ---------------------------------------------------------


@dataclasses.dataclass
class BranchAncestor:
    branch_id: str
    begin_node_id: int                  # inclusive
    end_node_id: int                    # exclusive


@dataclasses.dataclass
class BranchToken:
    """Identifies a branch in a workflow's history tree
    (reference: historyV2Store.go branch token + ancestors)."""

    tree_id: str
    branch_id: str
    ancestors: List[BranchAncestor] = dataclasses.field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            {
                "tree_id": self.tree_id,
                "branch_id": self.branch_id,
                "ancestors": [dataclasses.asdict(a) for a in self.ancestors],
            }
        )

    @classmethod
    def from_json(cls, s: str) -> "BranchToken":
        d = json.loads(s)
        return cls(
            tree_id=d["tree_id"],
            branch_id=d["branch_id"],
            ancestors=[BranchAncestor(**a) for a in d.get("ancestors", [])],
        )


# -- matching tasks -------------------------------------------------------


class TaskType:
    DECISION = 0
    ACTIVITY = 1


@dataclasses.dataclass
class TaskListInfo:
    domain_id: str
    name: str
    task_type: int
    range_id: int = 0
    ack_level: int = 0
    kind: int = 0                       # 0 normal, 1 sticky
    last_updated: int = 0


@dataclasses.dataclass
class TaskInfo:
    domain_id: str
    workflow_id: str
    run_id: str
    task_id: int                        # assigned from the task list's block
    schedule_id: int
    schedule_to_start_timeout_seconds: int = 0
    created_time: int = 0
    expiry_time: int = 0


# -- domains --------------------------------------------------------------


@dataclasses.dataclass
class DomainInfo:
    id: str
    name: str
    status: int = 0                     # 0 registered, 1 deprecated
    description: str = ""
    owner_email: str = ""
    data: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DomainConfig:
    retention_days: int = 7
    emit_metric: bool = True
    archival_bucket: str = ""
    archival_status: int = 0
    history_archival_status: int = 0
    history_archival_uri: str = ""
    visibility_archival_status: int = 0
    visibility_archival_uri: str = ""
    bad_binaries: Dict[str, Dict[str, str]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DomainReplicationConfig:
    active_cluster_name: str = "active"
    clusters: List[str] = dataclasses.field(default_factory=lambda: ["active"])


@dataclasses.dataclass
class DomainRecord:
    info: DomainInfo
    config: DomainConfig
    replication_config: DomainReplicationConfig
    is_global: bool = False
    config_version: int = 0
    failover_version: int = 0
    failover_notification_version: int = 0
    notification_version: int = 0


# -- visibility -----------------------------------------------------------


@dataclasses.dataclass
class VisibilityRecord:
    domain_id: str
    workflow_id: str
    run_id: str
    workflow_type: str
    start_time: int = 0                 # ns
    execution_time: int = 0             # ns (start + backoff)
    close_time: int = 0                 # ns, 0 while open
    close_status: int = -1              # -1 while open
    history_length: int = 0
    memo: Dict[str, Any] = dataclasses.field(default_factory=dict)
    search_attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)


# -- stored-snapshot helpers ----------------------------------------------


def current_version_history(snapshot: Dict[str, Any]):
    """Extract the CURRENT version history from a stored mutable-state
    snapshot dict: ``(branch_token_str, [(event_id, version), ...])``,
    with the execution_info branch token as the fallback when the
    history carries none. One place owns the fiddly current_index /
    bytes-vs-str / fallback dance (the raw-history read path, the
    replication snapshot server) — returns ("", []) when the snapshot
    has no version histories."""
    snap = snapshot or {}
    vh = snap.get("version_histories") or {}
    histories = vh.get("histories", [])
    if not histories:
        return "", []
    current = histories[vh.get("current_index", 0)]
    token = current.get("branch_token") or snap.get(
        "execution_info", {}
    ).get("branch_token", "")
    if isinstance(token, bytes):
        token = token.decode()
    items = [(int(e), int(v)) for e, v in current.get("items", [])]
    return token, items
