"""JSON serialization for queue-task records (shared by durable backends)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict

from cadence_tpu.core.enums import TimerTaskType, TransferTaskType
from cadence_tpu.core.tasks import ReplicationTask, TimerTask, TransferTask


def transfer_to_json(t: TransferTask) -> str:
    return json.dumps(dataclasses.asdict(t))


def transfer_from_json(s: str) -> TransferTask:
    d = json.loads(s)
    d["task_type"] = TransferTaskType(d["task_type"])
    return TransferTask(**d)


def timer_to_json(t: TimerTask) -> str:
    return json.dumps(dataclasses.asdict(t))


def timer_from_json(s: str) -> TimerTask:
    d = json.loads(s)
    d["task_type"] = TimerTaskType(d["task_type"])
    return TimerTask(**d)


def replication_to_json(t: ReplicationTask) -> str:
    d = dataclasses.asdict(t)
    d["branch_token"] = t.branch_token.decode("latin-1")
    d["new_run_branch_token"] = t.new_run_branch_token.decode("latin-1")
    return json.dumps(d)


def replication_from_json(s: str) -> ReplicationTask:
    d = json.loads(s)
    d["branch_token"] = d["branch_token"].encode("latin-1")
    d["new_run_branch_token"] = d["new_run_branch_token"].encode("latin-1")
    return ReplicationTask(**d)
