"""JSON serialization for queue-task records (shared by durable backends)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict

from cadence_tpu.core.enums import TimerTaskType, TransferTaskType
from cadence_tpu.core.tasks import ReplicationTask, TimerTask, TransferTask


def _enc(obj: Any) -> Any:
    """Bytes-tolerant JSON projection (mutable-state snapshots carry
    branch tokens / payload bytes; sets become sorted lists, which
    MutableState.from_snapshot rebuilds)."""
    if isinstance(obj, bytes):
        import base64

        return {"__b": base64.b64encode(obj).decode()}
    if isinstance(obj, dict):
        enc = {str(k): _enc(v) for k, v in obj.items()}
        if "__b" in enc or "__esc" in enc:
            # a user dict that happens to carry a marker key must not be
            # mistaken for an encoded value on the way back
            return {"__esc": enc}
        return enc
    if isinstance(obj, (list, tuple)):
        return [_enc(v) for v in obj]
    if isinstance(obj, (set, frozenset)):
        return [_enc(v) for v in sorted(obj)]
    return obj


def _dec(obj: Any) -> Any:
    if isinstance(obj, dict):
        if "__b" in obj and len(obj) == 1:
            import base64

            return base64.b64decode(obj["__b"])
        if "__esc" in obj and len(obj) == 1:
            return {k: _dec(v) for k, v in obj["__esc"].items()}
        return {k: _dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dec(v) for v in obj]
    return obj


def snapshot_to_json(snapshot: Dict[str, Any]) -> str:
    return json.dumps(_enc(snapshot))


def snapshot_from_json(s: str) -> Dict[str, Any]:
    return _dec(json.loads(s))


def transfer_to_json(t: TransferTask) -> str:
    return json.dumps(dataclasses.asdict(t))


def transfer_from_json(s: str) -> TransferTask:
    d = json.loads(s)
    d["task_type"] = TransferTaskType(d["task_type"])
    return TransferTask(**d)


def timer_to_json(t: TimerTask) -> str:
    return json.dumps(dataclasses.asdict(t))


def timer_from_json(s: str) -> TimerTask:
    d = json.loads(s)
    d["task_type"] = TimerTaskType(d["task_type"])
    return TimerTask(**d)


def replication_to_json(t: ReplicationTask) -> str:
    d = dataclasses.asdict(t)
    d["branch_token"] = t.branch_token.decode("latin-1")
    d["new_run_branch_token"] = t.new_run_branch_token.decode("latin-1")
    return json.dumps(d)


def replication_from_json(s: str) -> ReplicationTask:
    d = json.loads(s)
    d["branch_token"] = d["branch_token"].encode("latin-1")
    d["new_run_branch_token"] = d["new_run_branch_token"].encode("latin-1")
    return ReplicationTask(**d)
