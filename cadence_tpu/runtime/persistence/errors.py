"""Persistence error taxonomy (reference: common/persistence/dataInterfaces.go
error types + workflow service errors the managers surface)."""

from __future__ import annotations


class PersistenceError(Exception):
    pass


class EntityNotExistsError(PersistenceError):
    pass


class ConditionFailedError(PersistenceError):
    """Optimistic-concurrency condition (next_event_id / range_id block)
    failed — caller reloads and retries (the Update_History_Loop,
    reference decisionHandler.go:291)."""


class ShardAlreadyExistsError(PersistenceError):
    pass


class ShardOwnershipLostError(PersistenceError):
    """Write fenced by a newer range_id: another host stole the shard
    (reference: ShardOwnershipLostError, handled by shardController)."""

    def __init__(self, shard_id: int, msg: str = "") -> None:
        super().__init__(msg or f"shard {shard_id} ownership lost")
        self.shard_id = shard_id


class WorkflowAlreadyStartedError(PersistenceError):
    def __init__(
        self, msg: str, start_request_id: str, run_id: str,
        state: int = 0, close_status: int = 0, last_write_version: int = 0,
    ) -> None:
        super().__init__(msg)
        self.start_request_id = start_request_id
        self.run_id = run_id
        self.state = state
        self.close_status = close_status
        self.last_write_version = last_write_version


class DomainAlreadyExistsError(PersistenceError):
    pass


class TaskListLeaseLostError(ConditionFailedError):
    """Task-list range_id condition failed — another matching host owns
    it. A ConditionFailedError so lease-fencing recovery paths (the
    task writer's re-lease-and-retry, taskGC's ack-level suppression)
    catch it with the rest of the optimistic-concurrency family."""
