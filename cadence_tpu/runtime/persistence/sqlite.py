"""SQLite persistence backend — the durable single-node store.

Plays the role the reference's SQL plugin plays
(/root/reference/common/persistence/sql/): the same five-manager
contract as the memory backend, with every conditional write executed
inside a transaction so the LWT semantics hold across processes.
MutableState snapshots, events, and tasks are JSON blobs; condition
columns (range_id, next_event_id) are real columns checked in SQL.
"""

from __future__ import annotations

import json
import sqlite3
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from cadence_tpu.core.events import HistoryEvent, decode_batch, encode_batch
from cadence_tpu.core.tasks import ReplicationTask, TimerTask, TransferTask
from cadence_tpu.utils.locks import make_rlock

from . import interfaces as I
from . import serde
from .errors import (
    ConditionFailedError,
    DomainAlreadyExistsError,
    EntityNotExistsError,
    ShardAlreadyExistsError,
    ShardOwnershipLostError,
    TaskListLeaseLostError,
    WorkflowAlreadyStartedError,
)
from .records import (
    BranchAncestor,
    BranchToken,
    CreateWorkflowMode,
    CurrentExecution,
    DomainConfig,
    DomainInfo,
    DomainRecord,
    DomainReplicationConfig,
    GetWorkflowResponse,
    ShardInfo,
    TaskInfo,
    TaskListInfo,
    VisibilityRecord,
    WorkflowSnapshot,
)

# schema DDL lives in schema.py (versioned migrations)


class _Db:
    """One shared connection guarded by a lock; transactions via context."""

    def __init__(self, path: str, auto_setup: bool = True) -> None:
        from .schema import check_compat, update_schema

        self.conn = sqlite3.connect(path, check_same_thread=False)
        self.conn.execute("PRAGMA journal_mode=WAL")
        # writers from other PROCESSES (two-process service plane) wait
        # instead of failing immediately with SQLITE_BUSY
        self.conn.execute("PRAGMA busy_timeout=5000")
        if auto_setup:
            # embedded/onebox convenience: bring the schema to current
            update_schema(self.conn)
        else:
            # production boot: the operator runs `schema update`
            # explicitly (ref cmd/server/cadence.go:66 compat gate)
            check_compat(self.conn)
        # manual transaction control: txn() issues BEGIN IMMEDIATE
        # itself; the driver must not inject its own deferred BEGINs
        self.conn.isolation_level = None
        self.lock = make_rlock("_Db.lock")

    @contextmanager
    def txn(self):
        with self.lock:
            try:
                # BEGIN IMMEDIATE: python-sqlite3's legacy mode starts
                # the transaction only at the first DML, so a
                # check-then-write (the LWT pattern: current-execution
                # probe, next_event_id condition, lease bump) would run
                # its SELECT in autocommit and race a second PROCESS.
                # Taking the reserved lock up front makes the whole
                # block atomic across processes
                self.conn.execute("BEGIN IMMEDIATE")
                yield self.conn
                self.conn.commit()
            except BaseException:
                self.conn.rollback()
                raise


def _vis_to_json(r: VisibilityRecord) -> str:
    import dataclasses

    return json.dumps(dataclasses.asdict(r))


def _vis_from_json(s: str) -> VisibilityRecord:
    return VisibilityRecord(**json.loads(s))


class SqliteShardManager(I.ShardManager):
    def __init__(self, db: _Db) -> None:
        self.db = db

    def create_shard(self, info: ShardInfo) -> None:
        with self.db.txn() as c:
            row = c.execute(
                "SELECT 1 FROM shards WHERE shard_id=?", (info.shard_id,)
            ).fetchone()
            if row:
                raise ShardAlreadyExistsError(str(info.shard_id))
            c.execute(
                "INSERT INTO shards VALUES (?,?,?)",
                (info.shard_id, info.range_id, info.to_json()),
            )

    def get_shard(self, shard_id: int) -> ShardInfo:
        with self.db.txn() as c:
            row = c.execute(
                "SELECT blob FROM shards WHERE shard_id=?", (shard_id,)
            ).fetchone()
        if not row:
            raise EntityNotExistsError(f"shard {shard_id}")
        return ShardInfo.from_json(row[0])

    def update_shard(self, info: ShardInfo, previous_range_id: int) -> None:
        with self.db.txn() as c:
            cur = c.execute(
                "UPDATE shards SET range_id=?, blob=? "
                "WHERE shard_id=? AND range_id=?",
                (info.range_id, info.to_json(), info.shard_id, previous_range_id),
            )
            if cur.rowcount == 0:
                row = c.execute(
                    "SELECT 1 FROM shards WHERE shard_id=?", (info.shard_id,)
                ).fetchone()
                if not row:
                    raise EntityNotExistsError(f"shard {info.shard_id}")
                raise ShardOwnershipLostError(info.shard_id)

    # -- elastic resharding -------------------------------------------

    def get_reshard_state(self):
        with self.db.txn() as c:
            row = c.execute(
                "SELECT epoch, blob FROM reshard_state WHERE id=0"
            ).fetchone()
        return (int(row[0]), row[1]) if row else None

    def set_reshard_state(self, epoch, blob, previous_epoch):
        with self.db.txn() as c:
            row = c.execute(
                "SELECT epoch FROM reshard_state WHERE id=0"
            ).fetchone()
            stored = int(row[0]) if row else 0
            if stored != previous_epoch:
                raise ConditionFailedError(
                    f"reshard epoch {stored} != expected {previous_epoch}"
                )
            c.execute(
                "INSERT OR REPLACE INTO reshard_state VALUES (0,?,?)",
                (epoch, blob),
            )

    # -- adaptive geo-replication --------------------------------------

    def get_replication_progress(self, shard_id, cluster):
        with self.db.txn() as c:
            row = c.execute(
                "SELECT version, blob FROM replication_progress "
                "WHERE shard_id=? AND cluster=?",
                (shard_id, cluster),
            ).fetchone()
        return (int(row[0]), row[1]) if row else None

    def set_replication_progress(
        self, shard_id, cluster, blob, previous_version
    ):
        with self.db.txn() as c:
            row = c.execute(
                "SELECT version FROM replication_progress "
                "WHERE shard_id=? AND cluster=?",
                (shard_id, cluster),
            ).fetchone()
            stored = int(row[0]) if row else 0
            if stored != previous_version:
                raise ConditionFailedError(
                    f"replication progress version {stored} != "
                    f"expected {previous_version}"
                )
            c.execute(
                "INSERT OR REPLACE INTO replication_progress "
                "VALUES (?,?,?,?)",
                (shard_id, cluster, previous_version + 1, blob),
            )


class SqliteExecutionManager(I.ExecutionManager):
    def __init__(self, db: _Db) -> None:
        self.db = db

    def _check_range(self, c, shard_id: int, range_id: int) -> None:
        row = c.execute(
            "SELECT range_id FROM shards WHERE shard_id=?", (shard_id,)
        ).fetchone()
        if row is None:
            # a missing shard row must FENCE, not bypass fencing (the
            # memory backend raises here too — conformance)
            raise EntityNotExistsError(f"shard {shard_id}")
        if row[0] > range_id:
            raise ShardOwnershipLostError(shard_id)

    def _put_tasks(self, c, shard_id: int, snap: WorkflowSnapshot) -> None:
        for t in snap.transfer_tasks:
            c.execute(
                "INSERT OR REPLACE INTO transfer_tasks VALUES (?,?,?)",
                (shard_id, t.task_id, serde.transfer_to_json(t)),
            )
        for t in snap.timer_tasks:
            c.execute(
                "INSERT OR REPLACE INTO timer_tasks VALUES (?,?,?,?)",
                (
                    shard_id, t.visibility_timestamp, t.task_id,
                    serde.timer_to_json(t),
                ),
            )
        for t in snap.replication_tasks:
            c.execute(
                "INSERT OR REPLACE INTO replication_tasks VALUES (?,?,?)",
                (shard_id, t.task_id, serde.replication_to_json(t)),
            )

    def _store(self, c, shard_id: int, snap: WorkflowSnapshot) -> None:
        c.execute(
            "INSERT OR REPLACE INTO executions VALUES (?,?,?,?,?,?,?)",
            (
                shard_id, snap.domain_id, snap.workflow_id, snap.run_id,
                snap.next_event_id, snap.last_write_version,
                serde.snapshot_to_json(snap.snapshot),
            ),
        )
        self._put_tasks(c, shard_id, snap)

    @staticmethod
    def _exec_state(snapshot: Dict[str, Any]) -> Tuple[int, int]:
        ex = snapshot.get("execution_info") or snapshot.get("exec") or snapshot
        return int(ex.get("state", 0)), int(ex.get("close_status", 0))

    @staticmethod
    def _request_id(snapshot: Dict[str, Any]) -> str:
        ex = snapshot.get("execution_info") or {}
        return ex.get("create_request_id") or snapshot.get("request_id", "")

    def _create_locked(
        self, c, shard_id, range_id, mode, snapshot, prev_run_id,
        prev_last_write_version,
    ) -> None:
        self._check_range(c, shard_id, range_id)
        cur_row = c.execute(
            "SELECT run_id, create_request_id, state, close_status, "
            "last_write_version FROM current_executions "
            "WHERE shard_id=? AND domain_id=? AND workflow_id=?",
            (shard_id, snapshot.domain_id, snapshot.workflow_id),
        ).fetchone()
        if mode == CreateWorkflowMode.BRAND_NEW:
            if cur_row:
                raise WorkflowAlreadyStartedError(
                    f"workflow {snapshot.workflow_id} already started",
                    cur_row[1], cur_row[0], cur_row[2], cur_row[3], cur_row[4],
                )
        elif mode == CreateWorkflowMode.WORKFLOW_ID_REUSE:
            if not cur_row:
                raise ConditionFailedError("no current execution to reuse")
            if cur_row[2] != 2:  # WorkflowState.Completed
                raise WorkflowAlreadyStartedError(
                    f"workflow {snapshot.workflow_id} still running",
                    cur_row[1], cur_row[0], cur_row[2], cur_row[3], cur_row[4],
                )
            if cur_row[0] != prev_run_id:
                raise ConditionFailedError(
                    f"current run {cur_row[0]} != expected {prev_run_id}"
                )
        elif mode == CreateWorkflowMode.CONTINUE_AS_NEW:
            if not cur_row or cur_row[0] != prev_run_id:
                raise ConditionFailedError("continue-as-new current mismatch")
        elif mode == CreateWorkflowMode.ZOMBIE:
            pass
        elif mode == CreateWorkflowMode.SUPPRESS_CURRENT:
            if not cur_row or cur_row[0] != prev_run_id:
                raise ConditionFailedError(
                    "suppress-current run mismatch: "
                    f"{cur_row[0] if cur_row else None} != {prev_run_id}"
                )
            # zombify the stale run's stored record (WorkflowState.Zombie=3)
            old = c.execute(
                "SELECT snapshot FROM executions WHERE shard_id=? AND "
                "domain_id=? AND workflow_id=? AND run_id=?",
                (shard_id, snapshot.domain_id, snapshot.workflow_id,
                 cur_row[0]),
            ).fetchone()
            if old:
                snap = serde.snapshot_from_json(old[0])
                ex = snap.get("execution_info")
                if isinstance(ex, dict):
                    ex["state"] = 3
                c.execute(
                    "UPDATE executions SET snapshot=? WHERE shard_id=? AND "
                    "domain_id=? AND workflow_id=? AND run_id=?",
                    (serde.snapshot_to_json(snap), shard_id,
                     snapshot.domain_id, snapshot.workflow_id, cur_row[0]),
                )
        else:
            raise ValueError(f"unknown create mode {mode}")
        state, close_status = self._exec_state(snapshot.snapshot)
        if mode != CreateWorkflowMode.ZOMBIE:
            c.execute(
                "INSERT OR REPLACE INTO current_executions VALUES "
                "(?,?,?,?,?,?,?,?)",
                (
                    shard_id, snapshot.domain_id, snapshot.workflow_id,
                    snapshot.run_id,
                    self._request_id(snapshot.snapshot),
                    state, close_status, snapshot.last_write_version,
                ),
            )
        self._store(c, shard_id, snapshot)

    def create_workflow_execution(
        self, shard_id, range_id, mode, snapshot,
        prev_run_id="", prev_last_write_version=0,
    ) -> None:
        with self.db.txn() as c:
            self._create_locked(
                c, shard_id, range_id, mode, snapshot, prev_run_id,
                prev_last_write_version,
            )

    def get_workflow_execution(
        self, shard_id, domain_id, workflow_id, run_id
    ) -> GetWorkflowResponse:
        with self.db.txn() as c:
            row = c.execute(
                "SELECT snapshot, next_event_id FROM executions WHERE "
                "shard_id=? AND domain_id=? AND workflow_id=? AND run_id=?",
                (shard_id, domain_id, workflow_id, run_id),
            ).fetchone()
        if not row:
            raise EntityNotExistsError(f"execution {workflow_id}/{run_id}")
        return GetWorkflowResponse(
            snapshot=serde.snapshot_from_json(row[0]), next_event_id=row[1]
        )

    def update_workflow_execution(
        self, shard_id, range_id, condition, mutation,
        new_snapshot=None, new_mode=CreateWorkflowMode.CONTINUE_AS_NEW,
    ) -> None:
        with self.db.txn() as c:
            self._check_range(c, shard_id, range_id)
            row = c.execute(
                "SELECT next_event_id FROM executions WHERE "
                "shard_id=? AND domain_id=? AND workflow_id=? AND run_id=?",
                (
                    shard_id, mutation.domain_id, mutation.workflow_id,
                    mutation.run_id,
                ),
            ).fetchone()
            if not row:
                raise EntityNotExistsError(
                    f"execution {mutation.workflow_id}/{mutation.run_id}"
                )
            if row[0] != condition:
                raise ConditionFailedError(
                    f"next_event_id {row[0]} != condition {condition}"
                )
            self._store(c, shard_id, mutation)
            state, close_status = self._exec_state(mutation.snapshot)
            c.execute(
                "UPDATE current_executions SET state=?, close_status=?, "
                "last_write_version=? WHERE shard_id=? AND domain_id=? AND "
                "workflow_id=? AND run_id=?",
                (
                    state, close_status, mutation.last_write_version,
                    shard_id, mutation.domain_id, mutation.workflow_id,
                    mutation.run_id,
                ),
            )
            if new_snapshot is not None:
                self._create_locked(
                    c, shard_id, range_id, new_mode, new_snapshot,
                    mutation.run_id, 0,
                )

    def conflict_resolve_workflow_execution(
        self, shard_id, range_id, condition, reset_snapshot
    ) -> None:
        with self.db.txn() as c:
            self._check_range(c, shard_id, range_id)
            row = c.execute(
                "SELECT next_event_id FROM executions WHERE "
                "shard_id=? AND domain_id=? AND workflow_id=? AND run_id=?",
                (
                    shard_id, reset_snapshot.domain_id,
                    reset_snapshot.workflow_id, reset_snapshot.run_id,
                ),
            ).fetchone()
            if row and row[0] != condition:
                raise ConditionFailedError(
                    f"next_event_id {row[0]} != condition {condition}"
                )
            self._store(c, shard_id, reset_snapshot)
            state, close_status = self._exec_state(reset_snapshot.snapshot)
            c.execute(
                "UPDATE current_executions SET state=?, close_status=? "
                "WHERE shard_id=? AND domain_id=? AND workflow_id=? AND run_id=?",
                (
                    state, close_status, shard_id, reset_snapshot.domain_id,
                    reset_snapshot.workflow_id, reset_snapshot.run_id,
                ),
            )

    def delete_workflow_execution(
        self, shard_id, domain_id, workflow_id, run_id
    ) -> None:
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM executions WHERE shard_id=? AND domain_id=? "
                "AND workflow_id=? AND run_id=?",
                (shard_id, domain_id, workflow_id, run_id),
            )

    def delete_current_workflow_execution(
        self, shard_id, domain_id, workflow_id, run_id
    ) -> None:
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM current_executions WHERE shard_id=? AND "
                "domain_id=? AND workflow_id=? AND run_id=?",
                (shard_id, domain_id, workflow_id, run_id),
            )

    def get_current_execution(
        self, shard_id, domain_id, workflow_id
    ) -> CurrentExecution:
        with self.db.txn() as c:
            row = c.execute(
                "SELECT run_id, create_request_id, state, close_status, "
                "last_write_version FROM current_executions WHERE "
                "shard_id=? AND domain_id=? AND workflow_id=?",
                (shard_id, domain_id, workflow_id),
            ).fetchone()
        if not row:
            raise EntityNotExistsError(f"no current execution {workflow_id}")
        return CurrentExecution(*row)

    def list_concrete_executions(self, shard_id):
        with self.db.txn() as c:
            rows = c.execute(
                "SELECT domain_id, workflow_id, run_id FROM executions "
                "WHERE shard_id=?",
                (shard_id,),
            ).fetchall()
        return [tuple(r) for r in rows]

    # -- elastic resharding -------------------------------------------

    def reshard_extract(
        self, shard_id, workflow_ids, transfer_watermark, timer_watermark,
        delete=False,
    ):
        out = {"executions": [], "currents": [], "transfer": [],
               "timers": [], "replication": []}
        wids = sorted(set(workflow_ids))
        if not wids:
            return out
        marks = ",".join("?" * len(wids))
        with self.db.txn() as c:
            for row in c.execute(
                "SELECT domain_id, workflow_id, run_id, next_event_id, "
                f"last_write_version, snapshot FROM executions "
                f"WHERE shard_id=? AND workflow_id IN ({marks}) "
                "ORDER BY workflow_id, run_id",
                [shard_id] + wids,
            ).fetchall():
                out["executions"].append({
                    "domain_id": row[0], "workflow_id": row[1],
                    "run_id": row[2], "next_event_id": row[3],
                    "last_write_version": row[4],
                    "snapshot": serde.snapshot_from_json(row[5]),
                })
            for row in c.execute(
                "SELECT domain_id, workflow_id, run_id, create_request_id,"
                f" state, close_status, last_write_version "
                f"FROM current_executions "
                f"WHERE shard_id=? AND workflow_id IN ({marks}) "
                "ORDER BY workflow_id",
                [shard_id] + wids,
            ).fetchall():
                out["currents"].append({
                    "domain_id": row[0], "workflow_id": row[1],
                    "run_id": row[2], "create_request_id": row[3],
                    "state": row[4], "close_status": row[5],
                    "last_write_version": row[6],
                })
            tasks = [
                serde.transfer_from_json(r[0]) for r in c.execute(
                    "SELECT blob FROM transfer_tasks WHERE shard_id=? "
                    "AND task_id>? ORDER BY task_id",
                    (shard_id, transfer_watermark),
                ).fetchall()
            ]
            out["transfer"] = [t for t in tasks if t.workflow_id in wids]
            tasks = [
                serde.timer_from_json(r[0]) for r in c.execute(
                    "SELECT blob FROM timer_tasks WHERE shard_id=? "
                    "AND (visibility_ts>? OR (visibility_ts=? AND "
                    "task_id>?)) ORDER BY visibility_ts, task_id",
                    (shard_id, timer_watermark[0], timer_watermark[0],
                     timer_watermark[1]),
                ).fetchall()
            ]
            out["timers"] = [t for t in tasks if t.workflow_id in wids]
            tasks = [
                serde.replication_from_json(r[0]) for r in c.execute(
                    "SELECT blob FROM replication_tasks WHERE shard_id=? "
                    "ORDER BY task_id", (shard_id,),
                ).fetchall()
            ]
            out["replication"] = [
                t for t in tasks if t.workflow_id in wids
            ]
            if delete:
                self._purge_locked(c, shard_id, out)
        return out

    @staticmethod
    def _purge_locked(c, shard_id, extracted) -> None:
        for e in extracted["executions"]:
            c.execute(
                "DELETE FROM executions WHERE shard_id=? AND domain_id=? "
                "AND workflow_id=? AND run_id=?",
                (shard_id, e["domain_id"], e["workflow_id"], e["run_id"]),
            )
        for cur in extracted["currents"]:
            c.execute(
                "DELETE FROM current_executions WHERE shard_id=? AND "
                "domain_id=? AND workflow_id=?",
                (shard_id, cur["domain_id"], cur["workflow_id"]),
            )
        for t in extracted["transfer"]:
            c.execute(
                "DELETE FROM transfer_tasks WHERE shard_id=? AND "
                "task_id=?", (shard_id, t.task_id),
            )
        for t in extracted["timers"]:
            c.execute(
                "DELETE FROM timer_tasks WHERE shard_id=? AND "
                "visibility_ts=? AND task_id=?",
                (shard_id, t.visibility_timestamp, t.task_id),
            )
        for t in extracted["replication"]:
            c.execute(
                "DELETE FROM replication_tasks WHERE shard_id=? AND "
                "task_id=?", (shard_id, t.task_id),
            )

    def reshard_purge(self, shard_id, extracted):
        with self.db.txn() as c:
            self._purge_locked(c, shard_id, extracted)

    def reshard_install(self, shard_id, range_id, extracted, task_id_fn):
        import copy as _copy

        with self.db.txn() as c:
            row = c.execute(
                "SELECT range_id FROM shards WHERE shard_id=?", (shard_id,)
            ).fetchone()
            if row is None:
                raise EntityNotExistsError(f"shard {shard_id}")
            if row[0] != range_id:
                raise ShardOwnershipLostError(shard_id)
            for e in extracted["executions"]:
                c.execute(
                    "INSERT OR REPLACE INTO executions VALUES "
                    "(?,?,?,?,?,?,?)",
                    (shard_id, e["domain_id"], e["workflow_id"],
                     e["run_id"], e["next_event_id"],
                     e["last_write_version"],
                     serde.snapshot_to_json(e["snapshot"])),
                )
            for cur in extracted["currents"]:
                c.execute(
                    "INSERT OR REPLACE INTO current_executions VALUES "
                    "(?,?,?,?,?,?,?,?)",
                    (shard_id, cur["domain_id"], cur["workflow_id"],
                     cur["run_id"], cur["create_request_id"],
                     cur["state"], cur["close_status"],
                     cur["last_write_version"]),
                )
            for t in extracted["transfer"]:
                t = _copy.deepcopy(t)
                t.task_id = task_id_fn()
                c.execute(
                    "INSERT OR REPLACE INTO transfer_tasks VALUES (?,?,?)",
                    (shard_id, t.task_id, serde.transfer_to_json(t)),
                )
            for t in extracted["timers"]:
                t = _copy.deepcopy(t)
                t.task_id = task_id_fn()
                c.execute(
                    "INSERT OR REPLACE INTO timer_tasks VALUES (?,?,?,?)",
                    (shard_id, t.visibility_timestamp, t.task_id,
                     serde.timer_to_json(t)),
                )
            for t in extracted["replication"]:
                t = _copy.deepcopy(t)
                t.task_id = task_id_fn()
                c.execute(
                    "INSERT OR REPLACE INTO replication_tasks VALUES "
                    "(?,?,?)",
                    (shard_id, t.task_id, serde.replication_to_json(t)),
                )

    # -- queues -------------------------------------------------------

    def get_transfer_tasks(self, shard_id, read_level, max_read_level, batch_size):
        with self.db.txn() as c:
            rows = c.execute(
                "SELECT blob FROM transfer_tasks WHERE shard_id=? AND "
                "task_id>? AND task_id<=? ORDER BY task_id LIMIT ?",
                (shard_id, read_level, max_read_level, batch_size),
            ).fetchall()
        return [serde.transfer_from_json(r[0]) for r in rows]

    def complete_transfer_task(self, shard_id, task_id):
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM transfer_tasks WHERE shard_id=? AND task_id=?",
                (shard_id, task_id),
            )

    def range_complete_transfer_tasks(self, shard_id, exclusive_begin, inclusive_end):
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM transfer_tasks WHERE shard_id=? AND task_id>? "
                "AND task_id<=?",
                (shard_id, exclusive_begin, inclusive_end),
            )

    def get_timer_tasks(self, shard_id, min_ts, max_ts, batch_size,
                        after_key=None):
        sql = (
            "SELECT blob FROM timer_tasks WHERE shard_id=? AND "
            "visibility_ts>=? AND visibility_ts<? "
        )
        params = [shard_id, min_ts, max_ts]
        if after_key is not None:
            sql += (
                "AND (visibility_ts>? OR (visibility_ts=? AND task_id>?)) "
            )
            params += [after_key[0], after_key[0], after_key[1]]
        sql += "ORDER BY visibility_ts, task_id LIMIT ?"
        params.append(batch_size)
        with self.db.txn() as c:
            rows = c.execute(sql, params).fetchall()
        return [serde.timer_from_json(r[0]) for r in rows]

    def complete_timer_task(self, shard_id, visibility_ts, task_id):
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM timer_tasks WHERE shard_id=? AND "
                "visibility_ts=? AND task_id=?",
                (shard_id, visibility_ts, task_id),
            )

    def range_complete_timer_tasks(self, shard_id, inclusive_begin_ts, exclusive_end_ts):
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM timer_tasks WHERE shard_id=? AND "
                "visibility_ts>=? AND visibility_ts<?",
                (shard_id, inclusive_begin_ts, exclusive_end_ts),
            )

    def get_replication_tasks(self, shard_id, read_level, batch_size):
        with self.db.txn() as c:
            rows = c.execute(
                "SELECT blob FROM replication_tasks WHERE shard_id=? AND "
                "task_id>? ORDER BY task_id LIMIT ?",
                (shard_id, read_level, batch_size),
            ).fetchall()
        return [serde.replication_from_json(r[0]) for r in rows]

    def complete_replication_task(self, shard_id, task_id):
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM replication_tasks WHERE shard_id=? AND task_id=?",
                (shard_id, task_id),
            )


class SqliteHistoryManager(I.HistoryManager):
    def __init__(self, db: _Db) -> None:
        self.db = db

    def new_history_branch(self, tree_id: str) -> BranchToken:
        token = BranchToken(tree_id=tree_id, branch_id=str(uuid.uuid4()))
        with self.db.txn() as c:
            c.execute(
                "INSERT INTO history_branches VALUES (?,?,?)",
                (tree_id, token.branch_id, token.to_json()),
            )
        return token

    def append_history_nodes(self, branch, events, transaction_id) -> int:
        if not events:
            raise ValueError("empty event batch")
        node_id = events[0].event_id
        blob = encode_batch(events)
        with self.db.txn() as c:
            c.execute(
                "INSERT OR IGNORE INTO history_branches VALUES (?,?,?)",
                (branch.tree_id, branch.branch_id, branch.to_json()),
            )
            row = c.execute(
                "SELECT txn_id FROM history_nodes WHERE tree_id=? AND "
                "branch_id=? AND node_id=?",
                (branch.tree_id, branch.branch_id, node_id),
            ).fetchone()
            if row is None or row[0] < transaction_id:
                c.execute(
                    "INSERT OR REPLACE INTO history_nodes VALUES (?,?,?,?,?)",
                    (
                        branch.tree_id, branch.branch_id, node_id,
                        transaction_id, blob,
                    ),
                )
        return len(blob)

    def _segments(self, branch: BranchToken):
        segs = [
            (a.branch_id, a.begin_node_id, a.end_node_id)
            for a in branch.ancestors
        ]
        begin = branch.ancestors[-1].end_node_id if branch.ancestors else 1
        segs.append((branch.branch_id, begin, 2**62))
        return segs

    def read_history_branch(
        self, branch, min_event_id, max_event_id, page_size=0, next_token=0
    ):
        collected: List[Tuple[int, bytes]] = []
        with self.db.txn() as c:
            for branch_id, begin, end in self._segments(branch):
                rows = c.execute(
                    "SELECT node_id, blob FROM history_nodes WHERE tree_id=? "
                    "AND branch_id=? AND node_id>=? AND node_id<? "
                    "AND node_id>=? AND node_id<? AND node_id>=?",
                    (
                        branch.tree_id, branch_id, begin, end,
                        min_event_id, max_event_id, next_token,
                    ),
                ).fetchall()
                collected.extend((r[0], r[1]) for r in rows)
        collected.sort(key=lambda x: x[0])
        if page_size and len(collected) > page_size:
            page = collected[:page_size]
            token = collected[page_size][0]
        else:
            page, token = collected, 0
        return [decode_batch(blob) for _, blob in page], token

    def fork_history_branch(self, branch, fork_node_id) -> BranchToken:
        ancestors: List[BranchAncestor] = []
        for a in branch.ancestors:
            if a.end_node_id <= fork_node_id:
                ancestors.append(a)
            else:
                ancestors.append(
                    BranchAncestor(a.branch_id, a.begin_node_id, fork_node_id)
                )
                break
        else:
            begin = branch.ancestors[-1].end_node_id if branch.ancestors else 1
            ancestors.append(
                BranchAncestor(branch.branch_id, begin, fork_node_id)
            )
        token = BranchToken(
            tree_id=branch.tree_id, branch_id=str(uuid.uuid4()),
            ancestors=ancestors,
        )
        with self.db.txn() as c:
            c.execute(
                "INSERT INTO history_branches VALUES (?,?,?)",
                (branch.tree_id, token.branch_id, token.to_json()),
            )
        return token

    def delete_history_branch(self, branch) -> None:
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM history_branches WHERE tree_id=? AND branch_id=?",
                (branch.tree_id, branch.branch_id),
            )
            # Sweep every node range in the tree that no surviving
            # branch owns or references as an ancestor segment (shared
            # fork prefix — reference historyV2 deleteBranch keeps
            # shared ranges). Sweeping the whole tree rather than just
            # the target also reclaims ranges a *previously deleted*
            # ancestor left behind, which become orphaned exactly when
            # their last descendant goes (ADVICE r4).
            live: dict = {}  # branch_id -> protected end (0 = whole)
            for (token,) in c.execute(
                "SELECT token FROM history_branches WHERE tree_id=?",
                (branch.tree_id,),
            ).fetchall():
                bt = BranchToken.from_json(token)
                live[bt.branch_id] = 0
                for anc in bt.ancestors:
                    if live.get(anc.branch_id, 1) != 0:
                        live[anc.branch_id] = max(
                            live.get(anc.branch_id, 0), anc.end_node_id
                        )
            node_bids = [r[0] for r in c.execute(
                "SELECT DISTINCT branch_id FROM history_nodes "
                "WHERE tree_id=?",
                (branch.tree_id,),
            ).fetchall()]
            for bid in node_bids:
                end = live.get(bid)
                if end == 0:
                    continue  # a live branch owns the whole range
                if end is None:
                    c.execute(
                        "DELETE FROM history_nodes WHERE tree_id=? AND "
                        "branch_id=?",
                        (branch.tree_id, bid),
                    )
                else:
                    c.execute(
                        "DELETE FROM history_nodes WHERE tree_id=? AND "
                        "branch_id=? AND node_id>=?",
                        (branch.tree_id, bid, end),
                    )

    def list_history_trees(self):
        """All (tree_id, branch tokens) pairs — the history scavenger's
        scan surface (reference GetAllHistoryTreeBranches); without it
        the scavenger silently skips the durable backend."""
        with self.db.txn() as c:
            rows = c.execute(
                "SELECT tree_id, token FROM history_branches "
                "ORDER BY tree_id"
            ).fetchall()
        out = {}
        for tree_id, blob in rows:
            out.setdefault(tree_id, []).append(BranchToken.from_json(blob))
        return list(out.items())

    def get_history_tree(self, tree_id: str) -> List[BranchToken]:
        with self.db.txn() as c:
            rows = c.execute(
                "SELECT token FROM history_branches WHERE tree_id=?",
                (tree_id,),
            ).fetchall()
        return [BranchToken.from_json(r[0]) for r in rows]


class SqliteTaskManager(I.TaskManager):
    def __init__(self, db: _Db) -> None:
        self.db = db

    def lease_task_list(self, domain_id, name, task_type) -> TaskListInfo:
        with self.db.txn() as c:
            row = c.execute(
                "SELECT range_id, ack_level, kind, last_updated FROM "
                "task_lists WHERE domain_id=? AND name=? AND task_type=?",
                (domain_id, name, task_type),
            ).fetchone()
            now_ns = time.time_ns()
            if row:
                info = TaskListInfo(
                    domain_id, name, task_type, row[0] + 1, row[1], row[2],
                    now_ns,
                )
                c.execute(
                    "UPDATE task_lists SET range_id=?, last_updated=? "
                    "WHERE domain_id=? AND name=? AND task_type=?",
                    (info.range_id, now_ns, domain_id, name, task_type),
                )
            else:
                info = TaskListInfo(
                    domain_id, name, task_type, range_id=1,
                    last_updated=now_ns,
                )
                c.execute(
                    "INSERT INTO task_lists VALUES (?,?,?,?,?,?,?)",
                    (domain_id, name, task_type, 1, 0, 0, now_ns),
                )
        return info

    def update_task_list(self, info: TaskListInfo) -> None:
        with self.db.txn() as c:
            cur = c.execute(
                "UPDATE task_lists SET ack_level=?, kind=?, last_updated=? "
                "WHERE domain_id=? AND name=? AND task_type=? AND range_id=?",
                (
                    info.ack_level, info.kind, time.time_ns(),
                    info.domain_id, info.name, info.task_type, info.range_id,
                ),
            )
            if cur.rowcount == 0:
                raise TaskListLeaseLostError(info.name)

    def create_tasks(self, info: TaskListInfo, tasks: List[TaskInfo]) -> None:
        import dataclasses

        with self.db.txn() as c:
            row = c.execute(
                "SELECT range_id FROM task_lists WHERE domain_id=? AND "
                "name=? AND task_type=?",
                (info.domain_id, info.name, info.task_type),
            ).fetchone()
            if not row or row[0] != info.range_id:
                raise TaskListLeaseLostError(info.name)
            for t in tasks:
                c.execute(
                    "INSERT OR REPLACE INTO tasks VALUES (?,?,?,?,?)",
                    (
                        info.domain_id, info.name, info.task_type, t.task_id,
                        json.dumps(dataclasses.asdict(t)),
                    ),
                )

    def get_tasks(
        self, domain_id, name, task_type, read_level, max_read_level, batch_size
    ):
        with self.db.txn() as c:
            rows = c.execute(
                "SELECT blob FROM tasks WHERE domain_id=? AND name=? AND "
                "task_type=? AND task_id>? AND task_id<=? "
                "ORDER BY task_id LIMIT ?",
                (
                    domain_id, name, task_type, read_level, max_read_level,
                    batch_size,
                ),
            ).fetchall()
        return [TaskInfo(**json.loads(r[0])) for r in rows]

    def complete_task(self, domain_id, name, task_type, task_id):
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM tasks WHERE domain_id=? AND name=? AND "
                "task_type=? AND task_id=?",
                (domain_id, name, task_type, task_id),
            )

    def complete_tasks_less_than(self, domain_id, name, task_type, task_id):
        with self.db.txn() as c:
            cur = c.execute(
                "DELETE FROM tasks WHERE domain_id=? AND name=? AND "
                "task_type=? AND task_id<?",
                (domain_id, name, task_type, task_id),
            )
            return cur.rowcount

    def list_task_lists(self):
        with self.db.txn() as c:
            rows = c.execute(
                "SELECT domain_id, name, task_type, range_id, ack_level, "
                "kind, last_updated FROM task_lists"
            ).fetchall()
        return [TaskListInfo(*r) for r in rows]

    def delete_task_list(self, domain_id, name, task_type, range_id):
        with self.db.txn() as c:
            row = c.execute(
                "SELECT range_id FROM task_lists WHERE domain_id=? AND "
                "name=? AND task_type=?",
                (domain_id, name, task_type),
            ).fetchone()
            if not row:
                return
            if row[0] != range_id:
                raise TaskListLeaseLostError(name)
            c.execute(
                "DELETE FROM task_lists WHERE domain_id=? AND name=? AND "
                "task_type=?",
                (domain_id, name, task_type),
            )
            c.execute(
                "DELETE FROM tasks WHERE domain_id=? AND name=? AND task_type=?",
                (domain_id, name, task_type),
            )


class SqliteMetadataManager(I.MetadataManager):
    def __init__(self, db: _Db) -> None:
        self.db = db
        with self.db.txn() as c:
            c.execute(
                "INSERT OR IGNORE INTO meta VALUES ('domain_notification', 0)"
            )

    @staticmethod
    def _to_json(rec: DomainRecord) -> str:
        import dataclasses

        return json.dumps(dataclasses.asdict(rec))

    @staticmethod
    def _from_json(s: str) -> DomainRecord:
        d = json.loads(s)
        return DomainRecord(
            info=DomainInfo(**d["info"]),
            config=DomainConfig(**d["config"]),
            replication_config=DomainReplicationConfig(**d["replication_config"]),
            is_global=d["is_global"],
            config_version=d["config_version"],
            failover_version=d["failover_version"],
            failover_notification_version=d["failover_notification_version"],
            notification_version=d["notification_version"],
        )

    def _bump_version(self, c) -> int:
        c.execute("UPDATE meta SET v=v+1 WHERE k='domain_notification'")
        return c.execute(
            "SELECT v FROM meta WHERE k='domain_notification'"
        ).fetchone()[0] - 1

    def create_domain(self, record: DomainRecord) -> str:
        import copy

        record = copy.deepcopy(record)
        if not record.info.id:
            record.info.id = str(uuid.uuid4())
        with self.db.txn() as c:
            row = c.execute(
                "SELECT 1 FROM domains WHERE name=?", (record.info.name,)
            ).fetchone()
            if row:
                raise DomainAlreadyExistsError(record.info.name)
            record.notification_version = self._bump_version(c)
            c.execute(
                "INSERT INTO domains VALUES (?,?,?,?)",
                (
                    record.info.id, record.info.name, self._to_json(record),
                    record.notification_version,
                ),
            )
        return record.info.id

    def get_domain(self, id: str = "", name: str = "") -> DomainRecord:
        with self.db.txn() as c:
            if id:
                row = c.execute(
                    "SELECT blob FROM domains WHERE id=?", (id,)
                ).fetchone()
            elif name:
                row = c.execute(
                    "SELECT blob FROM domains WHERE name=?", (name,)
                ).fetchone()
            else:
                raise ValueError("id or name required")
        if not row:
            raise EntityNotExistsError(f"domain {id or name}")
        return self._from_json(row[0])

    def update_domain(self, record: DomainRecord) -> None:
        import copy

        record = copy.deepcopy(record)
        with self.db.txn() as c:
            row = c.execute(
                "SELECT 1 FROM domains WHERE id=?", (record.info.id,)
            ).fetchone()
            if not row:
                raise EntityNotExistsError(f"domain {record.info.id}")
            record.notification_version = self._bump_version(c)
            c.execute(
                "UPDATE domains SET name=?, blob=?, notification_version=? "
                "WHERE id=?",
                (
                    record.info.name, self._to_json(record),
                    record.notification_version, record.info.id,
                ),
            )

    def delete_domain(self, id: str = "", name: str = "") -> None:
        with self.db.txn() as c:
            if id:
                c.execute("DELETE FROM domains WHERE id=?", (id,))
            elif name:
                c.execute("DELETE FROM domains WHERE name=?", (name,))

    def list_domains(self) -> List[DomainRecord]:
        with self.db.txn() as c:
            rows = c.execute("SELECT blob FROM domains").fetchall()
        return [self._from_json(r[0]) for r in rows]

    def get_metadata_version(self) -> int:
        with self.db.txn() as c:
            return c.execute(
                "SELECT v FROM meta WHERE k='domain_notification'"
            ).fetchone()[0]


class SqliteVisibilityManager(I.VisibilityManager):
    def __init__(self, db: _Db) -> None:
        self.db = db

    def record_workflow_execution_started(self, rec: VisibilityRecord) -> None:
        with self.db.txn() as c:
            c.execute(
                "INSERT OR REPLACE INTO visibility VALUES (?,?,?,?,?,?,?,?,?)",
                (
                    rec.domain_id, rec.workflow_id, rec.run_id, 1,
                    rec.start_time, 0, -1, rec.workflow_type,
                    _vis_to_json(rec),
                ),
            )

    def record_workflow_execution_closed(self, rec: VisibilityRecord) -> None:
        with self.db.txn() as c:
            c.execute(
                "INSERT OR REPLACE INTO visibility VALUES (?,?,?,?,?,?,?,?,?)",
                (
                    rec.domain_id, rec.workflow_id, rec.run_id, 0,
                    rec.start_time, rec.close_time, rec.close_status,
                    rec.workflow_type, _vis_to_json(rec),
                ),
            )

    def upsert_workflow_execution(self, rec: VisibilityRecord) -> None:
        with self.db.txn() as c:
            row = c.execute(
                "SELECT is_open FROM visibility WHERE domain_id=? AND "
                "workflow_id=? AND run_id=?",
                (rec.domain_id, rec.workflow_id, rec.run_id),
            ).fetchone()
            is_open = row[0] if row else 0
            c.execute(
                "INSERT OR REPLACE INTO visibility VALUES (?,?,?,?,?,?,?,?,?)",
                (
                    rec.domain_id, rec.workflow_id, rec.run_id, is_open,
                    rec.start_time, rec.close_time, rec.close_status,
                    rec.workflow_type, _vis_to_json(rec),
                ),
            )

    def _list(
        self, is_open, domain_id, earliest_start, latest_start,
        workflow_type, workflow_id, close_status, page_size, next_token,
    ):
        q = (
            "SELECT blob FROM visibility WHERE domain_id=? AND is_open=? "
            "AND start_time>=? AND start_time<=?"
        )
        args: List[Any] = [domain_id, is_open, earliest_start, latest_start]
        if workflow_type:
            q += " AND workflow_type=?"
            args.append(workflow_type)
        if workflow_id:
            q += " AND workflow_id=?"
            args.append(workflow_id)
        if close_status >= 0:
            q += " AND close_status=?"
            args.append(close_status)
        q += " ORDER BY start_time DESC, workflow_id, run_id LIMIT ? OFFSET ?"
        args.extend([page_size + 1, next_token])
        with self.db.txn() as c:
            rows = c.execute(q, args).fetchall()
        records = [_vis_from_json(r[0]) for r in rows[:page_size]]
        token = next_token + page_size if len(rows) > page_size else 0
        return records, token

    def list_open_workflow_executions(
        self, domain_id, earliest_start=0, latest_start=2**63 - 1,
        workflow_type="", workflow_id="", page_size=100, next_token=0,
    ):
        return self._list(
            1, domain_id, earliest_start, latest_start, workflow_type,
            workflow_id, -1, page_size, next_token,
        )

    def list_closed_workflow_executions(
        self, domain_id, earliest_start=0, latest_start=2**63 - 1,
        workflow_type="", workflow_id="", close_status=-1,
        page_size=100, next_token=0,
    ):
        return self._list(
            0, domain_id, earliest_start, latest_start, workflow_type,
            workflow_id, close_status, page_size, next_token,
        )

    def get_closed_workflow_execution(self, domain_id, workflow_id, run_id):
        with self.db.txn() as c:
            if run_id:
                row = c.execute(
                    "SELECT blob FROM visibility WHERE domain_id=? AND "
                    "workflow_id=? AND run_id=? AND is_open=0",
                    (domain_id, workflow_id, run_id),
                ).fetchone()
            else:
                row = c.execute(
                    "SELECT blob FROM visibility WHERE domain_id=? AND "
                    "workflow_id=? AND is_open=0 ORDER BY close_time DESC "
                    "LIMIT 1",
                    (domain_id, workflow_id),
                ).fetchone()
        if not row:
            raise EntityNotExistsError(f"closed {workflow_id}/{run_id}")
        return _vis_from_json(row[0])

    def count_workflow_executions(self, domain_id, open_only=False):
        q = "SELECT COUNT(*) FROM visibility WHERE domain_id=?"
        if open_only:
            q += " AND is_open=1"
        with self.db.txn() as c:
            return c.execute(q, (domain_id,)).fetchone()[0]

    def delete_workflow_execution(self, domain_id, workflow_id, run_id):
        with self.db.txn() as c:
            c.execute(
                "DELETE FROM visibility WHERE domain_id=? AND workflow_id=? "
                "AND run_id=?",
                (domain_id, workflow_id, run_id),
            )


class SqliteBundle(I.PersistenceBundle):
    def __init__(self, path: str = ":memory:", auto_setup: bool = True) -> None:
        from cadence_tpu.checkpoint.store import SqliteCheckpointStore

        self._db = _Db(path, auto_setup=auto_setup)
        super().__init__(
            shard=SqliteShardManager(self._db),
            execution=SqliteExecutionManager(self._db),
            history=SqliteHistoryManager(self._db),
            task=SqliteTaskManager(self._db),
            metadata=SqliteMetadataManager(self._db),
            visibility=SqliteVisibilityManager(self._db),
            checkpoint=SqliteCheckpointStore(self._db),
        )

    def close(self) -> None:
        self._db.conn.close()


def create_sqlite_bundle(
    path: str = ":memory:", auto_setup: bool = True
) -> I.PersistenceBundle:
    return SqliteBundle(path, auto_setup=auto_setup)
