"""Persistence decorator clients: metrics + rate limiting.

Reference: common/persistence/persistenceMetricClients.go (per-API
latency/error counters around every manager) and
persistenceRateLimitedClients.go (token-bucket QPS guards returning
ServiceBusyError when saturated). Decorators are generic: they wrap any
manager object and intercept its public methods, so one implementation
covers all five managers — the factory stacks them the same way the
reference's persistence-factory does.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from cadence_tpu.utils.metrics import NOOP, Scope
from cadence_tpu.utils.quotas import TokenBucket


class PersistenceBusyError(Exception):
    """QPS limit hit (reference: ServiceBusyError from rate-limited
    persistence clients)."""


class _Wrapped:
    """Base proxy: public methods pass through hooks."""

    def __init__(self, base: Any) -> None:
        self._base = base

    def _invoke(self, name: str, method, args, kwargs):
        return method(*args, **kwargs)

    def __getattr__(self, name: str):
        attr = getattr(self._base, name)
        if not callable(attr) or name.startswith("_"):
            return attr

        def call(*args, **kwargs):
            return self._invoke(name, attr, args, kwargs)

        return call


class MetricsClient(_Wrapped):
    """Latency + error counters per persistence API, plus a trace span
    per call when the calling thread carries a sampled trace
    (utils/tracing.py) — the store hop of the end-to-end request trace.
    The untraced path adds one current-span check; the span name and
    the span machinery are only built when a trace is live."""

    def __init__(self, base: Any, metrics: Scope = NOOP,
                 manager: str = "") -> None:
        super().__init__(base)
        self._manager = manager or type(base).__name__
        self._metrics = metrics.tagged(
            layer="persistence", manager=self._manager
        )

    def _invoke(self, name, method, args, kwargs):
        from cadence_tpu.utils.tracing import NOOP_SPAN, TRACER

        span = (
            NOOP_SPAN if TRACER.current() is None
            else TRACER.span(
                f"{self._manager}.{name}", service="persistence"
            )
        )
        start = time.monotonic()
        try:
            with span:
                out = method(*args, **kwargs)
        except Exception as e:
            self._metrics.inc(f"{name}.errors")
            self._metrics.inc(f"{name}.errors.{type(e).__name__}")
            raise
        finally:
            self._metrics.record(
                f"{name}.latency", time.monotonic() - start
            )
        self._metrics.inc(f"{name}.calls")
        return out


class RateLimitedClient(_Wrapped):
    """Token-bucket QPS guard in front of a manager."""

    def __init__(self, base: Any, max_qps: float = 2000.0,
                 bucket: Optional[TokenBucket] = None) -> None:
        super().__init__(base)
        self._bucket = bucket or TokenBucket(max_qps)

    def _invoke(self, name, method, args, kwargs):
        if not self._bucket.allow():
            raise PersistenceBusyError(
                f"persistence QPS limit hit on {name}"
            )
        return method(*args, **kwargs)


def wrap_bundle(bundle, metrics: Scope = NOOP,
                max_qps: Optional[float] = None,
                faults=None, effects=False, sanitize=False):
    """Layer metrics (and optionally rate limits) over every manager in
    a PersistenceBundle, mirroring persistence-factory/factory.go.

    ``faults`` (a testing.faults.FaultSchedule) installs the fault-
    injection client INNERMOST — under the metrics client, so injected
    errors/latency are counted like real backend misbehavior, and under
    the rate limiter, so an injected PersistenceBusyError surfaces to
    the caller untranslated. Nothing is installed when it is None: the
    default factory stack pays zero overhead for the chaos machinery.

    ``effects=True`` installs the effect-witness recording client
    (testing/effect_witness.py) BELOW the fault client — the witness
    must see the real store calls, so an injected error that never
    reached the backend is not recorded while a torn write that landed
    is. Testing-only, like ``faults``.

    ``sanitize=True`` installs the concurrency sanitizer's store probe
    (testing/race_witness.SanitizerProbeClient) OUTERMOST — every
    attempted store call made while the caller holds a tracked lock is
    a RUNTIME-LOCK-BLOCKING observation, injected faults included (a
    fault that stalls the caller under a lock is as real a stall as a
    slow backend). Testing-only, like ``faults``/``effects``.
    """
    from .interfaces import PersistenceBundle

    fault_client = None
    if faults is not None:
        # lazy import: the runtime layer must not depend on the testing
        # package unless fault injection is actually configured
        from cadence_tpu.testing.faults import FaultInjectionClient

        fault_client = FaultInjectionClient
    effect_client = None
    if effects:
        from cadence_tpu.testing.effect_witness import (
            EffectRecordingClient,
        )

        effect_client = EffectRecordingClient
    sanitize_client = None
    if sanitize:
        from cadence_tpu.testing.race_witness import SanitizerProbeClient

        sanitize_client = SanitizerProbeClient

    def deco(mgr, name):
        if mgr is None:
            return None
        out = mgr
        if effect_client is not None:
            out = effect_client(out, manager=name)
        if fault_client is not None:
            out = fault_client(out, faults, manager=name)
        out = MetricsClient(out, metrics, manager=name)
        if max_qps is not None:
            out = RateLimitedClient(out, max_qps)
        if sanitize_client is not None:
            out = sanitize_client(out, manager=name)
        return out

    return PersistenceBundle(
        shard=deco(bundle.shard, "shard"),
        execution=deco(bundle.execution, "execution"),
        history=deco(bundle.history, "history"),
        task=deco(bundle.task, "task"),
        metadata=deco(bundle.metadata, "metadata"),
        visibility=deco(bundle.visibility, "visibility"),
        # chaos rules on persistence.checkpoint exercise the replay
        # plane's degrade-to-full-replay fallback
        checkpoint=deco(getattr(bundle, "checkpoint", None), "checkpoint"),
    )
