"""Domain cache + registry operations.

Reference: common/cache/domainCache.go (notification-version-driven LRU)
+ common/domain/handler.go (CRUD/failover). The cache refreshes entries
when the metadata notification version moves — same contract, simpler
machinery."""

from __future__ import annotations

import logging
import uuid
from typing import Dict, List, Optional

from cadence_tpu.utils.locks import make_guarded, make_rlock

from .persistence.errors import EntityNotExistsError
from .persistence.interfaces import MetadataManager
from .persistence.records import (
    DomainConfig,
    DomainInfo,
    DomainRecord,
    DomainReplicationConfig,
)


class DomainCache:
    def __init__(self, metadata: MetadataManager) -> None:
        self.metadata = metadata
        self._lock = make_rlock("DomainCache._lock")
        self._by_id: Dict[str, DomainRecord] = make_guarded(
            {}, "DomainCache._by_id", self._lock
        )
        self._by_name: Dict[str, DomainRecord] = make_guarded(
            {}, "DomainCache._by_name", self._lock
        )
        self._version = -1
        self._failover_listeners: List = []
        # active-cluster snapshot per domain, taken at refresh time —
        # records can be mutated in place by callers, so the comparison
        # baseline must be the immutable string captured at insert
        self._active_cluster: Dict[str, str] = make_guarded(
            {}, "DomainCache._active_cluster", self._lock
        )

    def add_failover_listener(self, fn) -> None:
        """fn(domain_id, old_active_cluster, new_active_cluster) — fired
        when a refresh observes a domain's active cluster change (ref
        domainCache.go RegisterDomainChangeCallback driving the queue
        processors' failover handling)."""
        with self._lock:
            self._failover_listeners.append(fn)

    def _refresh_if_stale(self) -> None:
        v = self.metadata.get_metadata_version()
        with self._lock:
            if v <= self._version:
                return
        # read the store OUTSIDE the lock: every domain lookup funnels
        # through this cache, and a slow metadata scan under the lock
        # would stall all of them (queue workers, allocators, frontend)
        # behind one refresher. The version recheck below makes a
        # concurrent refresh benign: whoever applies last wins only if
        # its snapshot is newer.
        records = self.metadata.list_domains()
        failovers = []
        with self._lock:
            if v <= self._version:
                return
            # copy-then-clear instead of rebinding: the guarded proxy
            # (sanitizer mode) must stay the canonical container
            old_active = dict(self._active_cluster)
            self._active_cluster.clear()
            self._by_id.clear()
            self._by_name.clear()
            for rec in records:
                self._by_id[rec.info.id] = rec
                self._by_name[rec.info.name] = rec
                new_cluster = rec.replication_config.active_cluster_name
                self._active_cluster[rec.info.id] = new_cluster
                old_cluster = old_active.get(rec.info.id)
                if old_cluster is not None and old_cluster != new_cluster:
                    failovers.append((rec.info.id, old_cluster, new_cluster))
            self._version = v
            listeners = list(self._failover_listeners)
        for domain_id, old_cluster, new_cluster in failovers:
            for fn in listeners:
                try:
                    fn(domain_id, old_cluster, new_cluster)
                except Exception:
                    # the version transition is one-shot; a lost rewind
                    # must at least be visible
                    logging.getLogger("cadence_tpu.domains").exception(
                        "failover listener failed for domain %s (%s->%s)",
                        domain_id, old_cluster, new_cluster,
                    )

    def get_by_id(self, domain_id: str) -> DomainRecord:
        self._refresh_if_stale()
        with self._lock:
            rec = self._by_id.get(domain_id)
        if rec is None:
            raise EntityNotExistsError(f"domain {domain_id}")
        return rec

    def get_by_name(self, name: str) -> DomainRecord:
        self._refresh_if_stale()
        with self._lock:
            rec = self._by_name.get(name)
        if rec is None:
            raise EntityNotExistsError(f"domain {name}")
        return rec

    def get_domain_id(self, name: str) -> str:
        return self.get_by_name(name).info.id

    def resolve(self, name_or_id: str) -> DomainRecord:
        self._refresh_if_stale()
        with self._lock:
            rec = self._by_name.get(name_or_id) or self._by_id.get(name_or_id)
        if rec is None:
            raise EntityNotExistsError(f"domain {name_or_id}")
        return rec


def register_domain(
    metadata: MetadataManager,
    name: str,
    retention_days: int = 7,
    description: str = "",
    is_global: bool = False,
    clusters: Optional[List[str]] = None,
    active_cluster: str = "active",
    domain_id: Optional[str] = None,
    failover_version: int = 0,
) -> str:
    """Domain registration (reference: domain/handler.go RegisterDomain).

    ``domain_id``/``failover_version`` are set explicitly when the domain
    record is replicated from another cluster — the ID must be identical
    cluster-wide (domainReplicationTaskHandler.go)."""
    rec = DomainRecord(
        info=DomainInfo(
            id=domain_id or str(uuid.uuid4()), name=name,
            description=description,
        ),
        config=DomainConfig(retention_days=retention_days),
        replication_config=DomainReplicationConfig(
            active_cluster_name=active_cluster,
            clusters=list(clusters or [active_cluster]),
        ),
        is_global=is_global,
        failover_version=failover_version,
    )
    return metadata.create_domain(rec)
