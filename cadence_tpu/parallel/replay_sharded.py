"""Mesh-sharded batched replay + NDC snapshot exchange.

Batch (shard-axis) sharding is plain SPMD: the replay scan is elementwise
over B, so `jit` with NamedSharding on the batch axis compiles to fully
local compute — zero collectives, matching the reference's
shared-nothing shard design (each history shard is single-writer,
/root/reference/service/history/shardContext.go:44).

The one genuinely cross-device step is the NDC replication storm
(BASELINE config 5): after a batched rebuild, every participant needs the
others' rebuilt snapshot digests — the reference ships these via
cross-cluster RPC/Kafka (/root/reference/service/history/
replicatorQueueProcessor.go, replicationTaskFetcher.go:167); here they
ride ICI as one `all_gather` + `psum` inside `shard_map`.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cadence_tpu.ops import schema as S
from cadence_tpu.ops.pack import PackedHistories
from cadence_tpu.ops.refresh import RefreshedTasks, refresh_tasks_device
from cadence_tpu.ops.replay import replay_scan

from .mesh import SHARD_AXIS, events_spec, shard_map, shard_spec


def _state_specs(sharding: NamedSharding) -> S.StateTensors:
    return jax.tree_util.tree_map(lambda _: sharding, S.empty_state(1, S.Capacities()))


@functools.lru_cache(maxsize=8)
def replay_sharded_fn(mesh: Mesh, scan_mode: str = "scan"):
    """jit(replay+refresh) with batch-axis shardings over ``mesh``.

    ``scan_mode="scan"`` consumes time-major [T, B, EV_N] events through
    the sequential scan; ``"assoc"`` consumes field-major [EV_N, B, T]
    events through the parallel-in-time associative kernel
    (cadence_tpu/ops/assoc.py), wrapped in ``shard_map`` so the
    per-history provenance reductions stay shard-local — the assoc path
    is elementwise over B like the scan, so batch sharding adds zero
    collectives either way.

    Returns fn(state, events) -> (final_state, refreshed_tasks); both
    outputs stay sharded on device.
    """
    st_spec = shard_spec(mesh)

    if scan_mode == "assoc":
        from cadence_tpu.ops.assoc import _assoc_core

        def step_local(state: S.StateTensors, events_fm: jnp.ndarray):
            final = _assoc_core(events_fm, state)
            return final, refresh_tasks_device(final)

        sharded = shard_map(
            step_local,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS, None)),
            out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0,))

    ev_spec = events_spec(mesh)

    def step(state: S.StateTensors, events_tm: jnp.ndarray):
        final = replay_scan(state, events_tm)
        tasks = refresh_tasks_device(final)
        return final, tasks

    return jax.jit(
        step,
        in_shardings=(_state_specs(st_spec), ev_spec),
        # pytree-prefix: one sharding covers every leaf of each output
        out_shardings=(st_spec, st_spec),
        donate_argnums=(0,),
    )


def replay_packed_sharded(
    packed: PackedHistories,
    mesh: Mesh,
    initial: Optional[S.StateTensors] = None,
    scan_mode: str = "scan",
) -> Tuple[S.StateTensors, RefreshedTasks]:
    """Replay a packed batch across the mesh; returns numpy pytrees.

    The batch must be padded to a multiple of the shard-axis size
    (``pack_histories(pad_batch_to=...)``). ``scan_mode="assoc"`` rides
    the parallel-in-time kernel (O(log T) depth per shard) —
    bit-identical to the scan (tests/test_parallel.py).
    """
    from cadence_tpu.ops.replay import check_scan_mode

    # no "auto" here: the sharded facade is an explicit two-kernel API
    check_scan_mode(scan_mode, allowed=("scan", "assoc"))
    n_shard = mesh.shape[SHARD_AXIS]
    if packed.batch % n_shard != 0:
        raise ValueError(
            f"batch {packed.batch} not divisible by shard axis {n_shard}; "
            "pack with pad_batch_to"
        )
    state = initial if initial is not None else S.empty_state(packed.batch, packed.caps)
    if scan_mode == "assoc":
        from cadence_tpu.ops.assoc import events_fm_of

        ev = events_fm_of(packed.events)
        ev_sharding = NamedSharding(mesh, P(None, SHARD_AXIS, None))
    else:
        ev = packed.time_major()
        ev_sharding = events_spec(mesh)
    fn = replay_sharded_fn(mesh, scan_mode)
    final, tasks = fn(
        jax.device_put(state, shard_spec(mesh))
        if initial is not None
        else jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.asarray(x), shard_spec(mesh)), state
        ),
        jax.device_put(jnp.asarray(ev), ev_sharding),
    )
    to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)
    return to_np(final), to_np(tasks)


# Snapshot digest columns gathered in the NDC exchange: enough for the
# receiving side's version-check + conflict detection (the fields
# nDCHistoryReplicator.ApplyEvents consults before accepting events:
# last event id/version, state/close status —
# /root/reference/service/history/nDCHistoryReplicator.go:259-340).
_DIGEST_COLS = (
    S.X_STATE,
    S.X_CLOSE_STATUS,
    S.X_NEXT_EVENT_ID,
    S.X_LAST_EVENT_TASK_ID,
    S.X_CUR_VERSION,
    S.X_DEC_VERSION,
)


@functools.lru_cache(maxsize=8)
def _ndc_exchange_fn(mesh: Mesh):
    spec_in = P(SHARD_AXIS)

    def exchange(exec_info: jnp.ndarray, vh_items: jnp.ndarray, vh_len: jnp.ndarray):
        digest = jnp.stack([exec_info[:, c] for c in _DIGEST_COLS], axis=-1)
        # every device sees every shard's digest + version histories
        all_digest = jax.lax.all_gather(digest, SHARD_AXIS, tiled=True)
        all_vh = jax.lax.all_gather(vh_items, SHARD_AXIS, tiled=True)
        all_vh_len = jax.lax.all_gather(vh_len, SHARD_AXIS, tiled=True)
        # global counters: replayed workflows + max failover version — the
        # cluster-metadata aggregate the replication storm needs. A row
        # is REPLAYED iff its history actually started (start_ts set):
        # X_STATE >= 0 is true for zero-initialized padding rows too
        replayed = jax.lax.psum(
            jnp.sum(exec_info[:, S.X_START_TS] > 0), SHARD_AXIS
        )
        max_version = jax.lax.pmax(
            jnp.max(exec_info[:, S.X_CUR_VERSION]), SHARD_AXIS
        )
        return all_digest, all_vh, all_vh_len, replayed, max_version

    return jax.jit(
        shard_map(
            exchange,
            mesh=mesh,
            in_specs=(spec_in, spec_in, spec_in),
            out_specs=(P(), P(), P(), P(), P()),
            check_vma=False,
        )
    )


def ndc_snapshot_exchange(state: S.StateTensors, mesh: Mesh):
    """All-gather rebuilt snapshot digests + psum storm counters over ICI.

    Returns (digests [B, len(_DIGEST_COLS)], vh_items [B, V, 2],
    vh_len [B], replayed_count, max_version) replicated on every device.
    """
    fn = _ndc_exchange_fn(mesh)
    return fn(state.exec_info, state.vh_items, state.vh_len)
