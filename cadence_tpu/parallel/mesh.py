"""Mesh construction for the replay fabric.

Two logical axes:

  * ``shard`` — the batch axis. Cadence shards (workflowID % numShards,
    /root/reference/common/util.go:249-251) are rows of the event tensor;
    sharding them over devices is the data-parallel dimension.
  * ``seq``   — the time axis for pipelined long-history replay
    (cadence_tpu/parallel/pipeline.py). The reference's analog is the
    paginated history-branch read + strictly sequential per-workflow
    replay (/root/reference/service/history/nDCStateRebuilder.go:103-137).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"
SEQ_AXIS = "seq"


# shard_map moved out of jax.experimental, and its replication-check
# kwarg was renamed check_rep -> check_vma, across jax releases; this
# shim presents the new-style surface on either.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_legacy(*args, **kwargs)


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    seq: int = 1,
) -> Mesh:
    """Build a ("shard", "seq") mesh over ``devices``.

    ``seq`` devices are dedicated to the time-pipeline; the rest to the
    batch axis. seq=1 (default) is pure batch sharding.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % seq != 0:
        raise ValueError(f"{n} devices not divisible by seq={seq}")
    arr = np.array(devices).reshape(n // seq, seq)
    return Mesh(arr, (SHARD_AXIS, SEQ_AXIS))


def shard_spec(mesh: Mesh) -> NamedSharding:
    """Sharding for batch-leading state arrays: [B, ...] split on shard."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def events_spec(mesh: Mesh) -> NamedSharding:
    """Sharding for time-major event tensors: [T, B, EV_N], B split."""
    return NamedSharding(mesh, P(None, SHARD_AXIS))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
