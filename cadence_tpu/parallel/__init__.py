"""Device-mesh parallelism for batched history replay.

The reference scales horizontally by hashing workflowID -> shard and
spreading shards over hosts via a ringpop consistent-hash ring
(/root/reference/service/history/shardController.go:96,
/root/reference/common/util.go:249-251). Here the same dimension is a
tensor axis: each shard's replay requests are rows of the [B, T] event
tensor, and shards map onto TPU devices through a `jax.sharding.Mesh`
("shard" axis = Cadence's horizontal sharding; "seq" axis = the time-
pipelined long-history path, SURVEY.md §2.8).

ICI collectives (all_gather / psum / ppermute) replace the reference's
cross-host RPC fan-out for the NDC replication-storm snapshot exchange
(BASELINE config 5).
"""

from cadence_tpu.parallel.mesh import make_mesh, shard_spec
from cadence_tpu.parallel.replay_sharded import (
    ndc_snapshot_exchange,
    replay_packed_sharded,
    replay_sharded_fn,
)
from cadence_tpu.parallel.pipeline import replay_pipelined

__all__ = [
    "make_mesh",
    "shard_spec",
    "replay_sharded_fn",
    "replay_packed_sharded",
    "ndc_snapshot_exchange",
    "replay_pipelined",
]
