"""Time-pipelined replay for deep histories (sequence parallelism).

A workflow-history replay is an inherently sequential scan over time
(the reference replays strictly per-workflow, batch after batch:
/root/reference/service/history/nDCStateRebuilder.go:128-137). The FSM
transition is not associative, so the time axis cannot be parallelized
by a prefix-scan — but it CAN be pipelined: split T into contiguous
chunks over the ``seq`` mesh axis, split the batch into micro-batches,
and hand each micro-batch's carry state from device i to device i+1 over
ICI (`ppermute`) as soon as chunk i is done. With M micro-batches and S
seq devices, utilization is M/(M+S-1) — the classic GPipe schedule,
applied to FSM simulation instead of layers.

This is the TPU answer to the reference's paginated long-history reads
(ReadHistoryBranchByBatch, /root/reference/common/persistence/
dataInterfaces.go:1552-1556): a 64k-event history that would blow one
device's scan-depth/HBM budget streams through S devices at 1/S of the
per-device depth.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map

from cadence_tpu.ops import schema as S
from cadence_tpu.ops.replay import replay_scan

from .mesh import SEQ_AXIS, SHARD_AXIS


@functools.lru_cache(maxsize=8)
def _pipelined_fn(mesh: Mesh, n_micro: int):
    n_seq = mesh.shape[SEQ_AXIS]

    def pipe(events_local: jnp.ndarray, init_local: S.StateTensors):
        # events_local: [T/n_seq, B_local, EV_N]; init_local: [B_local, ...]
        b_local = events_local.shape[1]
        if b_local % n_micro != 0:
            raise ValueError(
                f"local batch {b_local} not divisible by n_micro={n_micro}"
            )
        mb = b_local // n_micro
        idx = lax.axis_index(SEQ_AXIS)
        is_first = idx == 0
        is_last = idx == n_seq - 1

        to_micro = lambda x: x.reshape((n_micro, mb) + x.shape[1:])
        init_mb = jax.tree_util.tree_map(to_micro, init_local)
        out0 = jax.tree_util.tree_map(jnp.zeros_like, init_mb)
        recv0 = jax.tree_util.tree_map(lambda x: x[0], init_mb)
        # forward ring, no wraparound: the last stage's output exits the
        # pipeline instead of feeding stage 0
        perm = tuple((p, p + 1) for p in range(n_seq - 1))

        def body(carry, k):
            recv, out = carry
            j = k - idx                      # micro-batch this stage works on
            active = (j >= 0) & (j < n_micro)
            jc = jnp.clip(j, 0, n_micro - 1)
            st_in = jax.tree_util.tree_map(
                lambda a, r: jnp.where(is_first, a[jc], r), init_mb, recv
            )
            ev = lax.dynamic_slice_in_dim(events_local, jc * mb, mb, axis=1)
            st_out = replay_scan(st_in, ev)
            recv_next = jax.tree_util.tree_map(
                lambda x: lax.ppermute(x, SEQ_AXIS, perm), st_out
            )
            out = jax.tree_util.tree_map(
                lambda o, s: o.at[jc].set(jnp.where(active & is_last, s, o[jc])),
                out,
                st_out,
            )
            return (recv_next, out), None

        n_steps = n_micro + n_seq - 1
        (_, out), _ = lax.scan(body, (recv0, out0), jnp.arange(n_steps))
        # only the last stage holds real results; psum replicates them
        out = jax.tree_util.tree_map(
            lambda x: lax.psum(jnp.where(is_last, x, jnp.zeros_like(x)), SEQ_AXIS),
            out,
        )
        from_micro = lambda x: x.reshape((b_local,) + x.shape[2:])
        return jax.tree_util.tree_map(from_micro, out)

    state_spec = jax.tree_util.tree_map(
        lambda _: P(SHARD_AXIS), S.empty_state(1, S.Capacities())
    )
    return jax.jit(
        shard_map(
            pipe,
            mesh=mesh,
            in_specs=(P(SEQ_AXIS, SHARD_AXIS), state_spec),
            out_specs=state_spec,
            check_vma=False,
        )
    )


def replay_pipelined(
    state: S.StateTensors,
    events_tm: jnp.ndarray,
    mesh: Mesh,
    n_micro: int = 0,
) -> S.StateTensors:
    """Pipelined replay: T sharded over ``seq``, B over ``shard``.

    Requires T % n_seq == 0 and (B / n_shard) % n_micro == 0.
    ``n_micro`` defaults to the seq-axis size (balanced bubble).
    """
    n_micro = n_micro or mesh.shape[SEQ_AXIS]
    return _pipelined_fn(mesh, n_micro)(events_tm, state)
