"""Archiver: archival as a system workflow.

Reference: service/worker/archiver/ — client_worker.go (the archival
system workflow + activities on a system domain), workflow.go:39 /
pump.go:83 (drain a batch of signaled archival requests, then
continue-as-new), activities.go:52-122 (uploadHistoryActivity /
archiveVisibilityActivity / deleteHistoryActivity). The trigger side is
the history close-execution processor (archivalClient.Archive →
SignalWithStart on the system workflow).
"""

from __future__ import annotations

import json
from typing import Optional

from cadence_tpu.archival import (
    ArchiveHistoryRequest,
    ArchiveVisibilityRequest,
    ArchiverProvider,
    HistoryIterator,
    URI,
)
from cadence_tpu.runtime.api import (
    SignalWithStartRequest,
    StartWorkflowRequest,
)

from .sdk import ActivityError, Worker

SYSTEM_DOMAIN = "cadence-system"
ARCHIVAL_WORKFLOW_TYPE = "cadence-sys-archival-workflow"
ARCHIVAL_WORKFLOW_ID = "cadence-archival"
ARCHIVAL_TASK_LIST = "cadence-archival-tl"
ARCHIVAL_SIGNAL = "archival-request"
_REQUESTS_PER_RUN = 500  # pump.go batch before continue-as-new


class ArchivalClient:
    """Trigger side, called by the transfer close processor."""

    def __init__(self, frontend, domain_cache) -> None:
        self.frontend = frontend
        self.domains = domain_cache

    def maybe_archive(self, task, snap: dict) -> None:
        """Signal the archival workflow when the domain archives."""
        from cadence_tpu.frontend.domain_handler import ArchivalStatus

        rec = self.domains.get_by_id(task.domain_id)
        cfg = rec.config
        want_history = (
            cfg.history_archival_status == ArchivalStatus.ENABLED
            and cfg.history_archival_uri
        )
        want_visibility = (
            cfg.visibility_archival_status == ArchivalStatus.ENABLED
            and cfg.visibility_archival_uri
        )
        if not want_history and not want_visibility:
            return
        branch_token = snap.get("branch_token", b"")
        payload = {
            "domain_id": task.domain_id,
            "domain_name": rec.info.name,
            "workflow_id": task.workflow_id,
            "run_id": task.run_id,
            "branch_token": (
                branch_token.decode()
                if isinstance(branch_token, bytes)
                else branch_token
            ),
            "workflow_type": snap["workflow_type"],
            "start_time": snap["start_time"],
            "close_time": snap["close_time"],
            "close_status": snap["close_status"],
            "history_length": snap["history_length"],
            "history_uri": cfg.history_archival_uri if want_history else "",
            "visibility_uri": (
                cfg.visibility_archival_uri if want_visibility else ""
            ),
        }
        self.frontend.signal_with_start_workflow_execution(
            SignalWithStartRequest(
                start=StartWorkflowRequest(
                    domain=SYSTEM_DOMAIN,
                    workflow_id=ARCHIVAL_WORKFLOW_ID,
                    workflow_type=ARCHIVAL_WORKFLOW_TYPE,
                    task_list=ARCHIVAL_TASK_LIST,
                    execution_start_to_close_timeout_seconds=3600 * 24,
                    task_start_to_close_timeout_seconds=30,
                ),
                signal_name=ARCHIVAL_SIGNAL,
                signal_input=json.dumps(payload).encode(),
            )
        )


# transient store errors must not fail the system run: one poisoned
# upload would kill every other buffered request on it (the reference
# retries archival activities with an unlimited-attempt policy,
# service/worker/archiver/activities.go)
_ARCHIVE_RETRY = {
    "initial_interval_seconds": 2,
    "backoff_coefficient": 2.0,
    "maximum_interval_seconds": 60,
    "maximum_attempts": 10,
}


def _archive_one(ctx, payload):
    try:
        yield ctx.schedule_activity(
            "upload_history", payload,
            start_to_close_timeout_seconds=300,
            retry_policy=_ARCHIVE_RETRY,
        )
        yield ctx.schedule_activity(
            "archive_visibility", payload,
            start_to_close_timeout_seconds=60,
            retry_policy=_ARCHIVE_RETRY,
        )
    except ActivityError:
        # retry budget exhausted for THIS request: drop it, keep the
        # pump alive for the other buffered requests
        pass


def archival_workflow(ctx, input: bytes):
    """Drain archival-request signals; continue-as-new after a batch
    (reference workflow.go + pump.go)."""
    handled = int(input or b"0")
    while handled < _REQUESTS_PER_RUN:
        payload = yield ctx.wait_signal(ARCHIVAL_SIGNAL)
        yield from _archive_one(ctx, payload)
        handled += 1
    # drain signals already recorded but not yet consumed — continuing
    # as new would orphan them (pump.go drains before CAN)
    while True:
        payload = yield ctx.poll_signal(ARCHIVAL_SIGNAL)
        if payload is None:
            break
        yield from _archive_one(ctx, payload)
    yield ctx.continue_as_new(b"0")


class ArchiverActivities:
    def __init__(
        self, history_manager, provider: Optional[ArchiverProvider] = None
    ) -> None:
        self.history = history_manager
        self.provider = provider or ArchiverProvider.default()

    def upload_history(self, payload: bytes) -> bytes:
        req = json.loads(payload)
        if not req.get("history_uri"):
            return b"skipped"
        uri = URI.parse(req["history_uri"])
        archiver = self.provider.get_history_archiver(uri.scheme)
        # resolve the branch token from the run's mutable state
        branch_token = req.get("branch_token", "").encode()
        if not branch_token:
            branch_token = self._branch_token_of(req)
            if branch_token is None:
                return b"no-branch"
        batches = HistoryIterator(self.history, branch_token).all_batches()
        archiver.archive(
            uri,
            ArchiveHistoryRequest(
                domain_id=req["domain_id"],
                domain_name=req.get("domain_name", ""),
                workflow_id=req["workflow_id"],
                run_id=req["run_id"],
            ),
            batches,
        )
        return b"uploaded"

    def _branch_token_of(self, req) -> Optional[bytes]:
        execution = getattr(self, "execution_manager", None)
        shard_resolver = getattr(self, "shard_resolver", None)
        if execution is None or shard_resolver is None:
            return None
        shard_id = shard_resolver(req["workflow_id"])
        try:
            resp = execution.get_workflow_execution(
                shard_id, req["domain_id"], req["workflow_id"], req["run_id"]
            )
        except Exception:
            return None
        raw = resp.snapshot.get("execution_info", {}).get("branch_token", b"")
        return raw if isinstance(raw, bytes) else str(raw).encode()

    def archive_visibility(self, payload: bytes) -> bytes:
        req = json.loads(payload)
        if not req.get("visibility_uri"):
            return b"skipped"
        uri = URI.parse(req["visibility_uri"])
        archiver = self.provider.get_visibility_archiver(uri.scheme)
        archiver.archive(
            uri,
            ArchiveVisibilityRequest(
                domain_id=req["domain_id"],
                domain_name=req.get("domain_name", ""),
                workflow_id=req["workflow_id"],
                run_id=req["run_id"],
                workflow_type=req.get("workflow_type", ""),
                start_time=req.get("start_time", 0),
                close_time=req.get("close_time", 0),
                close_status=req.get("close_status", 0),
                history_length=req.get("history_length", 0),
            ),
        )
        return b"archived"


def build_archiver_worker(
    frontend, history_manager, execution_manager=None,
    shard_resolver=None, provider: Optional[ArchiverProvider] = None,
) -> Worker:
    """Assemble the archival system worker (client_worker.go)."""
    acts = ArchiverActivities(history_manager, provider)
    acts.execution_manager = execution_manager
    acts.shard_resolver = shard_resolver
    w = Worker(frontend, SYSTEM_DOMAIN, ARCHIVAL_TASK_LIST,
               identity="archiver")
    w.register_workflow(ARCHIVAL_WORKFLOW_TYPE, archival_workflow)
    w.register_activity("upload_history", acts.upload_history)
    w.register_activity("archive_visibility", acts.archive_visibility)
    return w
