"""Scanner: scavengers running as system workflows.

Reference: service/worker/scanner/ — scanner.go:101-171 launches
scavenger workflows on the system domain; tasklist/scavenger.go deletes
expired/orphan task lists, history/scavenger.go deletes history
branches whose workflow is gone. Scavenger passes run as activities;
the workflow loops pass → sleep → continue-as-new (a cron shape).
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

from cadence_tpu.runtime.persistence.errors import EntityNotExistsError

from .sdk import ActivityError, Worker
from .archiver import SYSTEM_DOMAIN

SCANNER_WORKFLOW_TYPE = "cadence-sys-scanner-workflow"
SCANNER_WORKFLOW_ID = "cadence-scanner"
SCANNER_TASK_LIST = "cadence-scanner-tl"


_SCAVENGE_RETRY = {
    "initial_interval_seconds": 2,
    "backoff_coefficient": 2.0,
    "maximum_interval_seconds": 60,
    "maximum_attempts": 5,
}


def scanner_workflow(ctx, input: bytes):
    """One pass of every scavenger, then sleep and continue-as-new.

    A pass that still fails after its retry budget is LOGGED-AND-SKIPPED
    (the next cron pass retries): one bad pass must not close the cron
    loop Failed and silently stop scavenging until a process restart."""
    for activity in ("scavenge_task_lists", "scavenge_history"):
        try:
            yield ctx.schedule_activity(
                activity, b"", start_to_close_timeout_seconds=300,
                retry_policy=_SCAVENGE_RETRY,
            )
        except ActivityError:
            pass  # this pass is lost; the loop survives
    interval = int(input or b"60")
    yield ctx.start_timer(interval)
    yield ctx.continue_as_new(input)


class ScannerActivities:
    def __init__(
        self,
        task_manager,
        history_manager=None,
        execution_manager=None,
        num_shards: int = 0,
        idle_task_list_age_s: float = 3600.0,
        now=time.time,
        matching=None,
        shard_ids=None,
    ) -> None:
        self.tasks = task_manager
        self.history = history_manager
        self.execution = execution_manager
        # optional: consulted for live pollers before deleting a list
        self.matching = matching
        self.num_shards = num_shards
        # live shard-id provider (elastic resharding: a split mints ids
        # beyond the boot-time count, and a run moved to the new shard
        # MUST be in the live set or the history scavenger would
        # classify its tree orphaned and destroy it). None = the static
        # range(num_shards) of a never-resharded cluster.
        self._shard_ids = shard_ids
        self.idle_age = idle_task_list_age_s
        self.now = now
        # trees seen orphaned on the previous scavenge pass
        self._orphan_candidates: set = set()

    # -- tasklist scavenger (tasklist/scavenger.go) --------------------

    def scavenge_task_lists(self, _input: bytes = b"") -> bytes:
        """Delete task lists with an expired lease, no backlog and no
        recent pollers."""
        deleted = 0
        scanned = 0
        for info in self.tasks.list_task_lists():
            scanned += 1
            backlog = self.tasks.get_tasks(
                info.domain_id, info.name, info.task_type,
                0, 1 << 62, 1,
            )
            if backlog:
                continue
            if not info.last_updated:
                continue  # age unknown: never delete on a guess
            age = self.now() - info.last_updated / 1e9
            if age < self.idle_age:
                continue
            if self._has_recent_pollers(info):
                continue
            try:
                self.tasks.delete_task_list(
                    info.domain_id, info.name, info.task_type,
                    info.range_id,
                )
                deleted += 1
            except Exception:
                continue  # raced with a new lease: leave it
        return json.dumps({"scanned": scanned, "deleted": deleted}).encode()

    def _has_recent_pollers(self, info) -> bool:
        """Live long-pollers don't bump last_updated; ask matching
        (reference: scavenger consults DescribeTaskList pollers)."""
        if self.matching is None:
            return False
        try:
            desc = self.matching.describe_task_list(
                info.domain_id, info.name, info.task_type
            )
        except Exception:
            return True  # can't tell: keep the list
        pollers = (
            desc.get("pollers", []) if isinstance(desc, dict)
            else getattr(desc, "pollers", [])
        )
        return bool(pollers)

    # -- history scavenger (history/scavenger.go) ----------------------

    def scavenge_history(self, _input: bytes = b"") -> bytes:
        """Delete history trees whose workflow execution is gone.

        Two-phase: a tree is deleted only when it was ALSO orphaned on
        the previous pass — closing the race with workflow creation,
        where the branch is written before the execution record
        (context.create_workflow). The reference uses an age threshold;
        two sightings across the scan interval bounds the same risk."""
        if self.history is None or self.execution is None:
            return json.dumps({"skipped": True}).encode()
        list_trees = getattr(self.history, "list_history_trees", None)
        if list_trees is None:
            return json.dumps({"skipped": True}).encode()
        live = self._live_run_ids()
        deleted = 0
        scanned = 0
        orphans = set()
        for tree_id, branches in list_trees():
            scanned += 1
            if tree_id in live:
                continue
            orphans.add(tree_id)
            if tree_id not in self._orphan_candidates:
                continue  # first sighting: candidate only
            for branch in branches:
                try:
                    self.history.delete_history_branch(branch)
                    deleted += 1
                except Exception:
                    pass
        self._orphan_candidates = orphans
        return json.dumps({"scanned": scanned, "deleted": deleted}).encode()

    def _live_run_ids(self) -> set:
        """Run ids AND history-tree ids of every concrete execution.

        Trees are keyed by the run that CREATED them — a reset forks
        the new run's branch inside the ORIGINAL run's tree, so once
        retention deletes the original execution, the reset run's life
        depends on its branch token's tree_id being counted here; run
        ids alone would let the scavenger destroy a live workflow's
        history."""
        from cadence_tpu.runtime.persistence.records import BranchToken

        live = set()
        shard_ids = (
            self._shard_ids() if self._shard_ids is not None
            else range(self.num_shards)
        )
        for shard_id in shard_ids:
            # fail-SAFE: any read error aborts this scavenge pass. An
            # incomplete live set is indistinguishable from "orphan" —
            # e.g. a reset run whose tree id we failed to read would be
            # classified orphan on two passes and its live history
            # destroyed. The next cron pass retries.
            rows = self.execution.list_concrete_executions(shard_id)
            for domain_id, wf_id, rid in rows:
                live.add(rid)
                try:
                    resp = self.execution.get_workflow_execution(
                        shard_id, domain_id, wf_id, rid
                    )
                except EntityNotExistsError:
                    continue  # deleted between list and read
                token = (resp.snapshot or {}).get(
                    "execution_info", {}
                ).get("branch_token") or b""
                if isinstance(token, bytes):
                    token = token.decode()
                if token:
                    live.add(BranchToken.from_json(token).tree_id)
        return live


def build_scanner_worker(
    frontend, task_manager, history_manager=None, execution_manager=None,
    num_shards: int = 0, shard_ids=None, **kwargs,
) -> Worker:
    acts = ScannerActivities(
        task_manager, history_manager, execution_manager,
        num_shards=num_shards, shard_ids=shard_ids, **kwargs,
    )
    w = Worker(frontend, SYSTEM_DOMAIN, SCANNER_TASK_LIST,
               identity="scanner")
    w.register_workflow(SCANNER_WORKFLOW_TYPE, scanner_workflow)
    w.register_activity("scavenge_task_lists", acts.scavenge_task_lists)
    w.register_activity("scavenge_history", acts.scavenge_history)
    return w
