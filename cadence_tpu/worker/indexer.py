"""Indexer: visibility message consumer.

Reference: service/worker/indexer/ — indexer.go:63 + esProcessor.go:
visibility writes ride a Kafka topic and a bulk processor lands them in
Elasticsearch. Here the topic is the in-proc bus and the sink is the
advanced visibility store; the producer side (BusVisibilityClient) is
the analogue of the history service writing visibility messages to
Kafka instead of the store.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from cadence_tpu.messaging import MessageBus
from cadence_tpu.runtime.persistence.interfaces import VisibilityManager
from cadence_tpu.runtime.persistence.records import VisibilityRecord

VISIBILITY_TOPIC = "visibility"


class BusVisibilityClient(VisibilityManager):
    """Producer side: visibility writes become bus messages (the
    reference's visibilityQueueKafka path); reads are not served here."""

    def __init__(self, bus: MessageBus, topic: str = VISIBILITY_TOPIC) -> None:
        self._producer = bus.new_producer(topic)

    def _publish(self, kind: str, rec: VisibilityRecord) -> None:
        self._producer.publish(
            f"{rec.domain_id}:{rec.workflow_id}:{rec.run_id}",
            {"kind": kind, "record": dataclasses.asdict(rec)},
        )

    def record_workflow_execution_started(self, rec) -> None:
        self._publish("started", rec)

    def record_workflow_execution_closed(self, rec) -> None:
        self._publish("closed", rec)

    def upsert_workflow_execution(self, rec) -> None:
        self._publish("upsert", rec)

    def delete_workflow_execution(self, domain_id, workflow_id, run_id):
        self._producer.publish(
            f"{domain_id}:{workflow_id}:{run_id}",
            {
                "kind": "delete",
                "record": {
                    "domain_id": domain_id,
                    "workflow_id": workflow_id,
                    "run_id": run_id,
                },
            },
        )


class Indexer:
    """Consumer side: bus → visibility store."""

    def __init__(
        self, bus: MessageBus, store: VisibilityManager,
        topic: str = VISIBILITY_TOPIC, group: str = "indexer",
    ) -> None:
        self.consumer = bus.new_consumer(topic, group)
        self.store = store
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _handle(self, msg) -> None:
        kind = msg.value["kind"]
        raw = dict(msg.value["record"])
        if kind == "delete":
            self.store.delete_workflow_execution(
                raw["domain_id"], raw["workflow_id"], raw["run_id"]
            )
            return
        rec = VisibilityRecord(**raw)
        if kind == "started":
            self.store.record_workflow_execution_started(rec)
        elif kind == "closed":
            self.store.record_workflow_execution_closed(rec)
        else:
            self.store.upsert_workflow_execution(rec)

    def process_backlog(self) -> int:
        """Drain everything currently queued (tests/sync callers)."""
        return self.consumer.drain(self._handle)

    def start(self, interval_s: float = 0.05) -> None:
        def pump() -> None:
            while not self._stop.is_set():
                msg = self.consumer.poll(timeout=interval_s)
                if msg is None:
                    continue
                try:
                    self._handle(msg)
                except Exception:
                    self.consumer.nack(msg)
                else:
                    self.consumer.ack(msg)

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
