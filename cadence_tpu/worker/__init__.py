"""Worker service: background daemons + the replay-based worker SDK.

Reference: service/worker/ — replicator, archiver, indexer, scanner,
batcher, parent-close-policy. The reference runs most of these *as
Cadence workflows* against the public frontend API via the Go client
SDK; this package ships a deterministic generator-based mini-SDK
(sdk.py) and implements the daemons as workflows on top of it.
"""

from .sdk import (
    ActivityWorker,
    DecisionWorker,
    Worker,
    WorkflowRegistry,
    activity_method,
)

__all__ = [
    "ActivityWorker",
    "DecisionWorker",
    "Worker",
    "WorkflowRegistry",
    "activity_method",
]
