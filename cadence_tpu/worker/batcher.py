"""Batcher: bulk terminate/cancel/signal as a system workflow.

Reference: service/worker/batcher/ — batcher.go + workflow.go: a batch
request (operation + target query/list) runs as a workflow whose
activity pages through matching executions applying the operation with
a rate cap, heartbeating progress.
"""

from __future__ import annotations

import json
from typing import List, Optional

from cadence_tpu.runtime.api import SignalRequest

from .sdk import Worker
from .archiver import SYSTEM_DOMAIN

BATCHER_WORKFLOW_TYPE = "cadence-sys-batch-workflow"
BATCHER_TASK_LIST = "cadence-batcher-tl"


def batch_workflow(ctx, input: bytes):
    """input: json {operation, domain, query|executions, params}."""
    summary = yield ctx.schedule_activity(
        "run_batch", input, start_to_close_timeout_seconds=3600,
    )
    return summary


class BatcherActivities:
    def __init__(self, frontend) -> None:
        self.frontend = frontend

    def run_batch(self, payload: bytes) -> bytes:
        req = json.loads(payload)
        operation = req["operation"]
        if operation not in ("terminate", "cancel", "signal"):
            raise ValueError(f"unknown operation {operation!r}")
        domain = req["domain"]
        params = req.get("params", {})
        targets = self._targets(req)
        done = 0
        errors: List[str] = []
        for wf_id, run_id in targets:
            try:
                if operation == "terminate":
                    self.frontend.terminate_workflow_execution(
                        domain, wf_id, run_id,
                        reason=params.get("reason", "batch terminate"),
                    )
                elif operation == "cancel":
                    self.frontend.request_cancel_workflow_execution(
                        domain, wf_id, run_id
                    )
                elif operation == "signal":
                    self.frontend.signal_workflow_execution(
                        SignalRequest(
                            domain=domain, workflow_id=wf_id, run_id=run_id,
                            signal_name=params.get("signal_name", ""),
                            input=params.get(
                                "signal_input", ""
                            ).encode(),
                        )
                    )
                done += 1
            except Exception as e:
                errors.append(f"{wf_id}: {e}")
        return json.dumps(
            {"done": done, "failed": len(errors), "errors": errors[:10]}
        ).encode()

    def _targets(self, req) -> List[tuple]:
        if req.get("executions"):
            return [
                (e["workflow_id"], e.get("run_id", ""))
                for e in req["executions"]
            ]
        query = req.get("query", "")
        out = []
        token = 0
        while True:
            recs, token = self.frontend.list_workflow_executions(
                req["domain"], query, page_size=200, next_token=token
            )
            out.extend((r.workflow_id, r.run_id) for r in recs)
            if not token:
                return out


def build_batcher_worker(frontend) -> Worker:
    acts = BatcherActivities(frontend)
    w = Worker(frontend, SYSTEM_DOMAIN, BATCHER_TASK_LIST,
               identity="batcher")
    w.register_workflow(BATCHER_WORKFLOW_TYPE, batch_workflow)
    w.register_activity("run_batch", acts.run_batch)
    return w
