"""Batcher: bulk terminate/cancel/signal as a system workflow.

Reference: service/worker/batcher/ — batcher.go + workflow.go: a batch
request (operation + target query/list) runs as a workflow whose
activity pages through matching executions applying the operation with
a rate cap, heartbeating progress.
"""

from __future__ import annotations

import json
from typing import List, Optional

from cadence_tpu.runtime.api import SignalRequest
from cadence_tpu.utils.quotas import TokenBucket

from .sdk import Worker, activity_heartbeat
from .archiver import SYSTEM_DOMAIN

BATCHER_WORKFLOW_TYPE = "cadence-sys-batch-workflow"
BATCHER_TASK_LIST = "cadence-batcher-tl"


def batch_workflow(ctx, input: bytes):
    """input: json {operation, domain, query|executions, params}."""
    summary = yield ctx.schedule_activity(
        "run_batch", input, start_to_close_timeout_seconds=3600,
        heartbeat_timeout_seconds=120,
    )
    return summary


class BatcherActivities:
    def __init__(self, frontend) -> None:
        self.frontend = frontend

    # per-activity RPS cap (reference batcher DefaultRPS); burst 1 makes
    # the cap a hard pace, not a front-loaded burst
    DEFAULT_RPS = 50.0

    def run_batch(self, payload: bytes) -> bytes:
        import time as _time

        req = json.loads(payload)
        operation = req["operation"]
        if operation not in ("terminate", "cancel", "signal"):
            raise ValueError(f"unknown operation {operation!r}")
        domain = req["domain"]
        params = req.get("params", {})
        bucket = TokenBucket(float(params.get("rps", self.DEFAULT_RPS)),
                             burst=1)
        done = 0
        errors: List[str] = []
        for wf_id, run_id in self._targets(req):
            while not bucket.allow():
                _time.sleep(0.005)
            try:
                if operation == "terminate":
                    self.frontend.terminate_workflow_execution(
                        domain, wf_id, run_id,
                        reason=params.get("reason", "batch terminate"),
                    )
                elif operation == "cancel":
                    self.frontend.request_cancel_workflow_execution(
                        domain, wf_id, run_id
                    )
                elif operation == "signal":
                    self.frontend.signal_workflow_execution(
                        SignalRequest(
                            domain=domain, workflow_id=wf_id, run_id=run_id,
                            signal_name=params.get("signal_name", ""),
                            input=params.get(
                                "signal_input", ""
                            ).encode(),
                        )
                    )
                done += 1
            except Exception as e:
                errors.append(f"{wf_id}: {e}")
        return json.dumps(
            {"done": done, "failed": len(errors), "errors": errors[:10]}
        ).encode()

    def _targets(self, req):
        """Stream targets page-by-page (a 100k-execution query must not
        be materialized in one list), heartbeating once per page so a
        dead worker is detected within the heartbeat window instead of
        the full start-to-close timeout."""
        if req.get("executions"):
            for e in req["executions"]:
                yield (e["workflow_id"], e.get("run_id", ""))
            return
        query = req.get("query", "")
        token = 0
        while True:
            recs, token = self.frontend.list_workflow_executions(
                req["domain"], query, page_size=200, next_token=token
            )
            activity_heartbeat(str(len(recs)).encode())
            for r in recs:
                yield (r.workflow_id, r.run_id)
            if not token:
                return


def build_batcher_worker(frontend) -> Worker:
    acts = BatcherActivities(frontend)
    w = Worker(frontend, SYSTEM_DOMAIN, BATCHER_TASK_LIST,
               identity="batcher")
    w.register_workflow(BATCHER_WORKFLOW_TYPE, batch_workflow)
    w.register_activity("run_batch", acts.run_batch)
    return w
