"""Deterministic replay-based worker SDK.

The reference's worker daemons run as Cadence workflows via the Go
client SDK (uber-go/cadence); this is the equivalent for this framework:
workflow code is a Python GENERATOR that yields commands; on every
decision task the runner replays the full history through the generator
— commands whose outcome is already recorded feed results back in,
the first unresolved command batch becomes this decision's output.
Determinism contract: workflow code must derive everything from
``ctx``/inputs (no wall clock, no I/O) — identical to the reference
SDK's replay rules.

Workflow code shape::

    def greet(ctx, input):
        name = yield ctx.schedule_activity("fetch-name", input)
        yield ctx.start_timer(5)
        sig = yield ctx.wait_signal("go")
        return b"hello " + name

Activities are plain functions registered on the ActivityWorker.
"""

from __future__ import annotations

import dataclasses
import threading
import traceback
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, Generator, List, Optional, Tuple

from cadence_tpu.core.enums import DecisionType, EventType
from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.runtime.api import Decision


def _require_bytes_result(value):
    """Workflow return values must be bytes (or None). Completing with
    b"" for a str/dict return would silently LOSE the result — the same
    loud-failure rule the activity worker applies."""
    if value is None:
        return b""
    if isinstance(value, bytes):
        return value
    raise TypeError(
        f"workflow must return bytes (or None), got {type(value).__name__}"
    )


class ActivityError(Exception):
    """Raised into workflow code when an activity failed/timed out."""

    def __init__(self, reason: str, details: bytes = b"") -> None:
        super().__init__(reason)
        self.reason = reason
        self.details = details


class WorkflowCancelled(Exception):
    """Raise from workflow code to close the run as Canceled.

    The reference SDK's equivalent is returning ctx.Err() after
    ctx.Done() fires (reference canary/cancellation.go); here the
    workflow observes the cancel request via ``ctx.wait_cancel()`` /
    ``ctx.cancel_requested()`` and raises this to emit a
    CancelWorkflowExecution decision.
    """

    def __init__(self, details: bytes = b"") -> None:
        super().__init__(details)
        self.details = details


class _NonDeterminismError(Exception):
    pass


# -- commands yielded by workflow code ------------------------------------


@dataclasses.dataclass
class _ActivityCmd:
    activity_type: str
    input: bytes
    task_list: str
    start_to_close: int
    schedule_to_start: int
    heartbeat: int
    retry_policy: Optional[dict]
    activity_id: str = ""  # assigned by the runner


@dataclasses.dataclass
class _TimerCmd:
    seconds: int
    timer_id: str = ""


@dataclasses.dataclass
class _SignalWaitCmd:
    name: str


@dataclasses.dataclass
class _SignalPollCmd:
    """Non-blocking: next unconsumed signal or None."""

    name: str


@dataclasses.dataclass
class _ChildCmd:
    workflow_type: str
    workflow_id: str
    input: bytes
    task_list: str
    execution_timeout: int
    task_timeout: int
    parent_close_policy: int


@dataclasses.dataclass
class _ContinueAsNewCmd:
    input: bytes
    workflow_type: str = ""
    task_list: str = ""
    execution_timeout: int = 0
    task_timeout: int = 0


@dataclasses.dataclass
class _SignalExternalCmd:
    domain: str
    workflow_id: str
    run_id: str
    signal_name: str
    input: bytes


@dataclasses.dataclass
class _CancelWaitCmd:
    pass


@dataclasses.dataclass
class _CancelExternalCmd:
    domain: str
    workflow_id: str
    run_id: str


@dataclasses.dataclass
class _UpsertSearchAttrsCmd:
    attrs: dict


@dataclasses.dataclass
class _LocalActivityCmd:
    activity_type: str
    input: bytes


@dataclasses.dataclass
class _SideEffectCmd:
    fn: Callable[[], bytes]


@dataclasses.dataclass
class _GetVersionCmd:
    change_id: str
    min_supported: int
    max_supported: int


DEFAULT_VERSION = -1  # reference client.DefaultVersion: pre-change code


class WorkflowContext:
    """Command factory handed to workflow code."""

    def schedule_activity(
        self, activity_type: str, input: bytes = b"",
        task_list: str = "", start_to_close_timeout_seconds: int = 60,
        schedule_to_start_timeout_seconds: int = 60,
        heartbeat_timeout_seconds: int = 0,
        retry_policy: Optional[dict] = None,
    ) -> _ActivityCmd:
        return _ActivityCmd(
            activity_type, input, task_list,
            start_to_close_timeout_seconds,
            schedule_to_start_timeout_seconds,
            heartbeat_timeout_seconds, retry_policy,
        )

    def start_timer(self, seconds: int) -> _TimerCmd:
        return _TimerCmd(seconds)

    def wait_signal(self, name: str) -> _SignalWaitCmd:
        return _SignalWaitCmd(name)

    def poll_signal(self, name: str) -> _SignalPollCmd:
        """Non-blocking signal read: yields the next unconsumed payload
        or None when the recorded history has no more — used to drain
        pending signals before continue-as-new (reference pump.go)."""
        return _SignalPollCmd(name)

    def start_child_workflow(
        self, workflow_type: str, workflow_id: str, input: bytes = b"",
        task_list: str = "", execution_timeout: int = 60,
        task_timeout: int = 10, parent_close_policy: int = 2,
    ) -> _ChildCmd:
        return _ChildCmd(
            workflow_type, workflow_id, input, task_list,
            execution_timeout, task_timeout, parent_close_policy,
        )

    def continue_as_new(self, input: bytes = b"", **kw) -> _ContinueAsNewCmd:
        return _ContinueAsNewCmd(input, **kw)

    def signal_external(
        self, domain: str, workflow_id: str, signal_name: str,
        input: bytes = b"", run_id: str = "",
    ) -> _SignalExternalCmd:
        return _SignalExternalCmd(
            domain, workflow_id, run_id, signal_name, input
        )

    def wait_cancel(self) -> _CancelWaitCmd:
        """Block until this run's cancellation is requested; resumes with
        the request's cause (reference ctx.Done)."""
        return _CancelWaitCmd()

    def request_cancel_external(
        self, domain: str, workflow_id: str, run_id: str = "",
    ) -> _CancelExternalCmd:
        """Request cancellation of another workflow (fire-and-forget,
        reference RequestCancelExternalWorkflowExecution decision)."""
        return _CancelExternalCmd(domain, workflow_id, run_id)

    def upsert_search_attributes(self, attrs: dict) -> _UpsertSearchAttrsCmd:
        """Attach/overwrite advanced-visibility search attributes."""
        return _UpsertSearchAttrsCmd(attrs)

    def local_activity(
        self, activity_type: str, input: bytes = b"",
    ) -> _LocalActivityCmd:
        """Run an activity inline in the decision task; its result is
        recorded as a MarkerRecorded event, so replay never re-executes
        it (reference local activity semantics: no ActivityTaskScheduled
        round-trip through matching)."""
        return _LocalActivityCmd(activity_type, input)

    def side_effect(self, fn: Callable[[], bytes]) -> _SideEffectCmd:
        """Record a non-deterministic value (uuid, random, clock read)
        once; replay returns the recorded bytes without re-running fn
        (reference workflow.SideEffect marker semantics)."""
        return _SideEffectCmd(fn)

    def get_version(
        self, change_id: str, min_supported: int, max_supported: int,
    ) -> _GetVersionCmd:
        """Safe workflow-code versioning (reference workflow.GetVersion):
        the first execution through this point records max_supported in
        a version marker; replays of histories recorded before the
        change see DEFAULT_VERSION (-1); replays of recorded versions
        outside [min_supported, max_supported] fail as non-determinism."""
        return _GetVersionCmd(change_id, min_supported, max_supported)


# -- history → replay state -----------------------------------------------


class _ReplayState:
    def __init__(self, history: List[HistoryEvent]) -> None:
        self.input: bytes = b""
        self.workflow_type = ""
        self.task_list = ""
        # activity_id → ("completed", result) | ("failed", reason, details)
        self.activity_outcome: Dict[str, Tuple] = {}
        self.activities_scheduled: set = set()
        # timer_id → fired?
        self.timers_started: set = set()
        self.timers_fired: set = set()
        # child outcomes by INITIATION ORDER (a workflow may start the
        # same child workflow_id again after it closes)
        self.children_started: set = set()
        self.child_outcome_by_index: Dict[int, Tuple] = {}
        # signals by name (FIFO)
        self.signals: Dict[str, List[bytes]] = {}
        # history-ordered initiation lists: replay matches the Nth yield
        # of a command type to the Nth initiation event, so repeating the
        # same target is not deduped away
        self.signals_external_list: List[tuple] = []
        self.children_list: List[str] = []
        self.cancels_external_list: List[str] = []
        # cancel request on THIS run
        self.cancel_requested = False
        self.cancel_cause: bytes = b""
        # markers by kind, each in record order (replay consumes each
        # stream independently; names disambiguate misuse)
        self.local_markers: List[Tuple[str, bytes]] = []
        self.side_effect_markers: List[bytes] = []
        self.version_markers: Dict[str, int] = {}
        self.upsert_count = 0
        # replay frontier detection for GetVersion/SideEffect: a history
        # with completed decisions is a replay until the driver crosses
        # into new territory (emits a decision, blocks, or has consumed
        # every recorded outcome)
        self.completed_decisions = 0

        sched_to_aid: Dict[int, str] = {}
        init_to_child: Dict[int, str] = {}
        for e in history:
            a = e.attributes
            et = e.event_type
            if et == EventType.WorkflowExecutionStarted:
                self.input = a.get("input", b"") or b""
                self.workflow_type = a.get("workflow_type", "")
                self.task_list = a.get("task_list", "")
            elif et == EventType.ActivityTaskScheduled:
                aid = a.get("activity_id", "")
                self.activities_scheduled.add(aid)
                sched_to_aid[e.event_id] = aid
            elif et == EventType.ActivityTaskCompleted:
                aid = sched_to_aid.get(a.get("scheduled_event_id"))
                if aid:
                    self.activity_outcome[aid] = (
                        "completed", a.get("result", b"")
                    )
            elif et == EventType.ActivityTaskFailed:
                aid = sched_to_aid.get(a.get("scheduled_event_id"))
                if aid:
                    self.activity_outcome[aid] = (
                        "failed", a.get("reason", ""), a.get("details", b"")
                    )
            elif et == EventType.ActivityTaskTimedOut:
                aid = sched_to_aid.get(a.get("scheduled_event_id"))
                if aid:
                    self.activity_outcome[aid] = ("failed", "timeout", b"")
            elif et == EventType.ActivityTaskCanceled:
                aid = sched_to_aid.get(a.get("scheduled_event_id"))
                if aid:
                    self.activity_outcome[aid] = ("failed", "canceled", b"")
            elif et == EventType.TimerStarted:
                self.timers_started.add(a.get("timer_id", ""))
            elif et == EventType.TimerFired:
                self.timers_fired.add(a.get("timer_id", ""))
            elif et == EventType.WorkflowExecutionSignaled:
                self.signals.setdefault(
                    a.get("signal_name", ""), []
                ).append(a.get("input", b"") or b"")
            elif et == EventType.StartChildWorkflowExecutionInitiated:
                wid = a.get("workflow_id", "")
                self.children_started.add(wid)
                init_to_child[e.event_id] = len(self.children_list)
                self.children_list.append(wid)
            elif et == EventType.ChildWorkflowExecutionCompleted:
                idx = init_to_child.get(a.get("initiated_event_id"))
                if idx is not None:
                    self.child_outcome_by_index[idx] = (
                        "completed", a.get("result", b"")
                    )
            elif et in (
                EventType.ChildWorkflowExecutionFailed,
                EventType.ChildWorkflowExecutionTimedOut,
                EventType.ChildWorkflowExecutionCanceled,
                EventType.ChildWorkflowExecutionTerminated,
                EventType.StartChildWorkflowExecutionFailed,
            ):
                idx = init_to_child.get(a.get("initiated_event_id"))
                if idx is None and a.get("workflow_id", "") in (
                    self.children_list
                ):
                    idx = self.children_list.index(a["workflow_id"])
                if idx is not None:
                    self.child_outcome_by_index[idx] = (
                        "failed", a.get("reason", str(et)), b""
                    )
            elif et == EventType.SignalExternalWorkflowExecutionInitiated:
                self.signals_external_list.append(
                    (a.get("workflow_id", ""), a.get("signal_name", ""))
                )
            elif et == (
                EventType.RequestCancelExternalWorkflowExecutionInitiated
            ):
                self.cancels_external_list.append(a.get("workflow_id", ""))
            elif et == EventType.WorkflowExecutionCancelRequested:
                self.cancel_requested = True
                cause = a.get("cause", "") or ""
                self.cancel_cause = (
                    cause.encode() if isinstance(cause, str) else cause
                )
            elif et == EventType.MarkerRecorded:
                name = a.get("marker_name", "")
                details = a.get("details", b"") or b""
                if name.startswith("version:"):
                    try:
                        self.version_markers[name[len("version:"):]] = int(
                            details.decode()
                        )
                    except ValueError:
                        pass
                elif name == "side_effect":
                    self.side_effect_markers.append(details)
                else:
                    self.local_markers.append((name, details))
            elif et == EventType.UpsertWorkflowSearchAttributes:
                self.upsert_count += 1
            elif et == EventType.DecisionTaskCompleted:
                self.completed_decisions += 1

    def total_outcomes(self) -> int:
        """Recorded command outcomes available to replay. The driver is
        'replaying' until they are all consumed — past that point the
        workflow code is executing for the first time.

        Signals and cancel requests are deliberately NOT counted: they
        buffer before the workflow reads them (a delivered-but-unread
        signal is not evidence of code progress), so counting them
        would misclassify genuinely-new code at the frontier as a
        replay. The cost is weaker old-history detection for runs whose
        recorded progress is purely signal-driven — the reference SDK
        resolves this with exact event positions; this build errs
        toward 'executing', which records rather than fails."""
        return (
            len(self.activity_outcome)
            + len(self.timers_fired)
            + len(self.child_outcome_by_index)
            + len(self.local_markers)
            + len(self.side_effect_markers)
            + len(self.version_markers)
        )


# -- the replay runner ----------------------------------------------------


class _Driver:
    def __init__(
        self, fn: Callable, state: _ReplayState,
        local_executor: Optional[Callable] = None,
    ) -> None:
        self.fn = fn
        self.state = state
        self.decisions: List[Decision] = []
        self.seq = {"a": 0, "t": 0, "c": 0, "s": 0, "rc": 0, "m": 0,
                    "se": 0}
        # versions resolved THIS replay that have no history marker yet
        self._version_cache: Dict[str, int] = {}
        self.signal_cursor: Dict[str, int] = {}
        self.closed = False
        # executes local activities inline (activity_type, input) -> bytes
        self.local_executor = local_executor
        # replay frontier: the run is a replay while recorded outcomes
        # remain unconsumed; emitting a decision or blocking also
        # crosses into new execution (matches the reference SDK's
        # isReplaying transition at the last DecisionTaskStarted)
        self._crossed = state.completed_decisions == 0
        self._total_outcomes = state.total_outcomes()
        self._consumed = 0

    @property
    def replaying(self) -> bool:
        return not self._crossed and self._consumed < self._total_outcomes

    def _consume(self) -> None:
        self._consumed += 1

    def _next_id(self, kind: str) -> str:
        self.seq[kind] += 1
        return f"{kind}{self.seq[kind]}"

    def run(self) -> List[Decision]:
        ctx = WorkflowContext()
        gen = self.fn(ctx, self.state.input)
        if not isinstance(gen, Generator):
            # plain function: complete immediately with its return value
            try:
                result = _require_bytes_result(gen)
            except TypeError:
                self.decisions.append(
                    Decision(
                        DecisionType.FailWorkflowExecution,
                        {
                            "reason": "workflow code raised",
                            "details": traceback.format_exc().encode(),
                        },
                    )
                )
                return self.decisions
            self.decisions.append(
                Decision(
                    DecisionType.CompleteWorkflowExecution,
                    {"result": result},
                )
            )
            return self.decisions
        try:
            to_send: Any = None
            to_throw: Optional[BaseException] = None
            while True:
                cmd = (
                    gen.throw(to_throw) if to_throw is not None
                    else gen.send(to_send)
                )
                to_send, to_throw, blocked = self._handle(cmd)
                if blocked:
                    return self.decisions
        except StopIteration as done:
            try:
                result = _require_bytes_result(done.value)
            except TypeError:
                # a wrong-typed return is a workflow-code bug of the
                # same class as raising: fail the RUN loudly (silently
                # completing with b"" would lose the result)
                if not self.closed:
                    self.decisions.append(
                        Decision(
                            DecisionType.FailWorkflowExecution,
                            {
                                "reason": "workflow code raised",
                                "details": traceback.format_exc().encode(),
                            },
                        )
                    )
                return self.decisions
            if not self.closed:
                self.decisions.append(
                    Decision(
                        DecisionType.CompleteWorkflowExecution,
                        {"result": result},
                    )
                )
            return self.decisions
        except _NonDeterminismError:
            raise
        except WorkflowCancelled as wc:
            if not self.closed:
                self.decisions.append(
                    Decision(
                        DecisionType.CancelWorkflowExecution,
                        {"details": wc.details},
                    )
                )
            return self.decisions
        except Exception:
            if not self.closed:
                self.decisions.append(
                    Decision(
                        DecisionType.FailWorkflowExecution,
                        {
                            "reason": "workflow code raised",
                            "details": traceback.format_exc().encode(),
                        },
                    )
                )
            return self.decisions

    def _handle(self, cmd) -> Tuple[Any, Optional[BaseException], bool]:
        """Returns (value_to_send, exc_to_throw, blocked)."""
        before = len(self.decisions)
        out = self._handle_inner(cmd)
        if out[2] or len(self.decisions) > before:
            # crossed the frontier: subsequent code is NEW execution
            self._crossed = True
        return out

    def _handle_inner(self, cmd) -> Tuple[Any, Optional[BaseException], bool]:
        st = self.state
        if isinstance(cmd, _ActivityCmd):
            aid = cmd.activity_id or self._next_id("a")
            outcome = st.activity_outcome.get(aid)
            if outcome is not None:
                self._consume()
                if outcome[0] == "completed":
                    return outcome[1], None, False
                return None, ActivityError(outcome[1], outcome[2]), False
            if aid not in st.activities_scheduled:
                self.decisions.append(
                    Decision(
                        DecisionType.ScheduleActivityTask,
                        {
                            "activity_id": aid,
                            "activity_type": cmd.activity_type,
                            "task_list": cmd.task_list or st.task_list,
                            "input": cmd.input,
                            "schedule_to_start_timeout_seconds": cmd.schedule_to_start,
                            "start_to_close_timeout_seconds": cmd.start_to_close,
                            "heartbeat_timeout_seconds": cmd.heartbeat,
                            "retry_policy": cmd.retry_policy,
                        },
                    )
                )
            return None, None, True  # awaiting the outcome
        if isinstance(cmd, _TimerCmd):
            tid = cmd.timer_id or self._next_id("t")
            if tid in st.timers_fired:
                self._consume()
                return None, None, False
            if tid not in st.timers_started:
                self.decisions.append(
                    Decision(
                        DecisionType.StartTimer,
                        {
                            "timer_id": tid,
                            "start_to_fire_timeout_seconds": cmd.seconds,
                        },
                    )
                )
            return None, None, True
        if isinstance(cmd, _SignalWaitCmd):
            cursor = self.signal_cursor.get(cmd.name, 0)
            queue = st.signals.get(cmd.name, [])
            if cursor < len(queue):
                self.signal_cursor[cmd.name] = cursor + 1
                return queue[cursor], None, False
            return None, None, True  # wait for the signal
        if isinstance(cmd, _SignalPollCmd):
            cursor = self.signal_cursor.get(cmd.name, 0)
            queue = st.signals.get(cmd.name, [])
            if cursor < len(queue):
                self.signal_cursor[cmd.name] = cursor + 1
                return queue[cursor], None, False
            return None, None, False  # nothing recorded: None, no block
        if isinstance(cmd, _ChildCmd):
            wid = cmd.workflow_id
            child_idx = self.seq["c"]
            self.seq["c"] += 1
            if child_idx < len(st.children_list) and (
                st.children_list[child_idx] != wid
            ):
                # the Nth yield must match the Nth recorded initiation;
                # silently crossing outcomes between reordered children
                # corrupts downstream decisions
                raise _NonDeterminismError(
                    f"child #{child_idx} in history is "
                    f"{st.children_list[child_idx]!r}, workflow code "
                    f"started {wid!r}"
                )
            outcome = st.child_outcome_by_index.get(child_idx)
            if outcome is not None:
                self._consume()
                if outcome[0] == "completed":
                    return outcome[1], None, False
                return None, ActivityError(outcome[1]), False
            if child_idx >= len(st.children_list):
                self.decisions.append(
                    Decision(
                        DecisionType.StartChildWorkflowExecution,
                        {
                            "workflow_id": wid,
                            "workflow_type": cmd.workflow_type,
                            "task_list": cmd.task_list or st.task_list,
                            "input": cmd.input,
                            "execution_start_to_close_timeout_seconds": (
                                cmd.execution_timeout
                            ),
                            "task_start_to_close_timeout_seconds": (
                                cmd.task_timeout
                            ),
                            "parent_close_policy": cmd.parent_close_policy,
                        },
                    )
                )
            return None, None, True
        if isinstance(cmd, _SignalExternalCmd):
            sig_idx = self.seq["s"]
            self.seq["s"] += 1
            if sig_idx < len(st.signals_external_list) and (
                st.signals_external_list[sig_idx]
                != (cmd.workflow_id, cmd.signal_name)
            ):
                # same rule as children: the Nth yield must match the
                # Nth recorded initiation, else a code change silently
                # drops one signal and duplicates another
                raise _NonDeterminismError(
                    f"external signal #{sig_idx} in history targets "
                    f"{st.signals_external_list[sig_idx]!r}, workflow "
                    f"code signals "
                    f"{(cmd.workflow_id, cmd.signal_name)!r}"
                )
            if sig_idx >= len(st.signals_external_list):
                self.decisions.append(
                    Decision(
                        DecisionType.SignalExternalWorkflowExecution,
                        {
                            "domain": cmd.domain,
                            "workflow_id": cmd.workflow_id,
                            "run_id": cmd.run_id,
                            "signal_name": cmd.signal_name,
                            "input": cmd.input,
                        },
                    )
                )
            return None, None, False  # fire and forget
        if isinstance(cmd, _CancelWaitCmd):
            if st.cancel_requested:
                return st.cancel_cause, None, False
            return None, None, True  # wait for the cancel request
        if isinstance(cmd, _CancelExternalCmd):
            rc_idx = self.seq["rc"]
            self.seq["rc"] += 1
            if rc_idx < len(st.cancels_external_list) and (
                st.cancels_external_list[rc_idx] != cmd.workflow_id
            ):
                raise _NonDeterminismError(
                    f"external cancel #{rc_idx} in history targets "
                    f"{st.cancels_external_list[rc_idx]!r}, workflow "
                    f"code cancels {cmd.workflow_id!r}"
                )
            if rc_idx >= len(st.cancels_external_list):
                self.decisions.append(
                    Decision(
                        DecisionType.RequestCancelExternalWorkflowExecution,
                        {
                            "domain": cmd.domain,
                            "workflow_id": cmd.workflow_id,
                            "run_id": cmd.run_id,
                        },
                    )
                )
            return None, None, False  # fire and forget
        if isinstance(cmd, _UpsertSearchAttrsCmd):
            if self.seq.setdefault("u", 0) >= st.upsert_count:
                self.decisions.append(
                    Decision(
                        DecisionType.UpsertWorkflowSearchAttributes,
                        {"search_attributes": dict(cmd.attrs)},
                    )
                )
            self.seq["u"] += 1
            return None, None, False
        if isinstance(cmd, _LocalActivityCmd):
            m_idx = self.seq["m"]
            self.seq["m"] += 1
            if m_idx < len(st.local_markers):
                name, details = st.local_markers[m_idx]
                want = f"local_activity:{cmd.activity_type}"
                if name != want:
                    raise _NonDeterminismError(
                        f"marker {m_idx} is {name!r}, workflow code "
                        f"asked for {want!r}"
                    )
                self._consume()
                return details, None, False
            if self.local_executor is None:
                raise _NonDeterminismError(
                    "local activity yielded but no executor is wired "
                    "(replay_decide without a DecisionWorker)"
                )
            result = self.local_executor(cmd.activity_type, cmd.input)
            if not isinstance(result, bytes):
                raise TypeError(
                    f"local activity {cmd.activity_type!r} must return "
                    f"bytes, got {type(result).__name__}"
                )
            self.decisions.append(
                Decision(
                    DecisionType.RecordMarker,
                    {"marker_name": f"local_activity:{cmd.activity_type}",
                     "details": result},
                )
            )
            return result, None, False
        if isinstance(cmd, _SideEffectCmd):
            se_idx = self.seq["se"]
            self.seq["se"] += 1
            if se_idx < len(st.side_effect_markers):
                self._consume()
                return st.side_effect_markers[se_idx], None, False
            if self.replaying:
                raise _NonDeterminismError(
                    "side effect has no recorded marker during replay — "
                    "gate new side effects behind ctx.get_version"
                )
            result = cmd.fn()
            if not isinstance(result, bytes):
                raise TypeError(
                    "side_effect fn must return bytes, got "
                    f"{type(result).__name__}"
                )
            self.decisions.append(
                Decision(
                    DecisionType.RecordMarker,
                    {"marker_name": "side_effect", "details": result},
                )
            )
            return result, None, False
        if isinstance(cmd, _GetVersionCmd):
            recorded = st.version_markers.get(cmd.change_id)
            if recorded is not None and cmd.change_id not in (
                self._version_cache
            ):
                # each recorded change counts once toward the frontier
                self._version_cache[cmd.change_id] = recorded
                self._consume()
            if recorded is None and cmd.change_id in self._version_cache:
                recorded = self._version_cache[cmd.change_id]
            if recorded is not None:
                if not cmd.min_supported <= recorded <= cmd.max_supported:
                    raise _NonDeterminismError(
                        f"history recorded version {recorded} for change "
                        f"{cmd.change_id!r}, workflow code supports "
                        f"[{cmd.min_supported}, {cmd.max_supported}]"
                    )
                return recorded, None, False
            if self.replaying:
                # history predates this GetVersion point: old behavior
                if cmd.min_supported > DEFAULT_VERSION:
                    raise _NonDeterminismError(
                        f"history predates change {cmd.change_id!r} but "
                        f"min_supported={cmd.min_supported} drops the "
                        "pre-change path"
                    )
                self._version_cache[cmd.change_id] = DEFAULT_VERSION
                return DEFAULT_VERSION, None, False
            self._version_cache[cmd.change_id] = cmd.max_supported
            self.decisions.append(
                Decision(
                    DecisionType.RecordMarker,
                    {"marker_name": f"version:{cmd.change_id}",
                     "details": str(cmd.max_supported).encode()},
                )
            )
            return cmd.max_supported, None, False
        if isinstance(cmd, _ContinueAsNewCmd):
            self.decisions.append(
                Decision(
                    DecisionType.ContinueAsNewWorkflowExecution,
                    {
                        "workflow_type": cmd.workflow_type or st.workflow_type,
                        "task_list": cmd.task_list or st.task_list,
                        "input": cmd.input,
                        "execution_start_to_close_timeout_seconds": (
                            cmd.execution_timeout or 60
                        ),
                        "task_start_to_close_timeout_seconds": (
                            cmd.task_timeout or 10
                        ),
                    },
                )
            )
            self.closed = True
            raise StopIteration(b"")
        raise _NonDeterminismError(f"unknown command {cmd!r}")


# -- registries + workers -------------------------------------------------


class WorkflowRegistry:
    def __init__(self) -> None:
        self._workflows: Dict[str, Callable] = {}
        self._query_handlers: Dict[str, Callable] = {}
        self._local_activities: Dict[str, Callable] = {}

    def register_local_activity(
        self, activity_type: str, fn: Callable
    ) -> None:
        """Local activities run inline in the decision task (not via
        matching), so they register with the workflow side."""
        self._local_activities[activity_type] = fn

    def local_activity(self, activity_type: str) -> Callable:
        fn = self._local_activities.get(activity_type)
        if fn is None:
            raise KeyError(
                f"local activity {activity_type!r} not registered"
            )
        return fn

    def register_workflow(self, workflow_type: str, fn: Callable) -> None:
        self._workflows[workflow_type] = fn

    def register_query_handler(
        self, workflow_type: str, fn: Callable[[str, bytes], bytes]
    ) -> None:
        self._query_handlers[workflow_type] = fn

    def workflow(self, workflow_type: str) -> Callable:
        fn = self._workflows.get(workflow_type)
        if fn is None:
            raise KeyError(f"workflow type {workflow_type!r} not registered")
        return fn

    def query_handler(self, workflow_type: str):
        return self._query_handlers.get(workflow_type)


def replay_decide(
    registry: WorkflowRegistry, history: List[HistoryEvent],
    state: Optional[_ReplayState] = None,
) -> List[Decision]:
    """Pure function: full history → this decision's output."""
    if state is None:
        state = _ReplayState(history)
    fn = registry.workflow(state.workflow_type)

    def local_executor(activity_type: str, input: bytes) -> bytes:
        return registry.local_activity(activity_type)(input)

    return _Driver(fn, state, local_executor=local_executor).run()


class DecisionWorker:
    """Decision poller with sticky execution.

    Reference worker semantics: after the first decision the worker
    advertises a host-specific sticky task list; the engine then
    dispatches follow-up decisions there with a PARTIAL history (the
    delta since the worker's previous decision), and the worker merges
    it onto its cached prefix. A schedule-to-start timeout on the
    sticky list falls back to the normal list with full history
    (timer queue clears stickiness), so a dead worker never wedges the
    workflow.
    """

    STICKY_TIMEOUT_S = 5
    CACHE_RUNS = 200

    def __init__(
        self, frontend, domain: str, task_list: str,
        registry: WorkflowRegistry, identity: str = "decision-worker",
        sticky: bool = True,
    ) -> None:
        self.frontend = frontend
        self.domain = domain
        self.task_list = task_list
        self.registry = registry
        self.identity = identity
        self.sticky = sticky
        self.sticky_task_list = (
            f"{identity}:{uuid.uuid4().hex[:8]}:sticky" if sticky else ""
        )
        # (workflow_id, run_id) → contiguous event prefix seen so far
        self._history_cache: "OrderedDict[tuple, List[HistoryEvent]]" = (
            OrderedDict()
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_and_process_one(self, timeout_s: float = 1.0) -> bool:
        task = None
        if self.sticky:
            # drain the sticky list first (short poll), then the
            # normal list — the reference worker multiplexes both
            task = self.frontend.poll_for_decision_task(
                self.domain, self.sticky_task_list,
                identity=self.identity,
                timeout_s=min(0.05, timeout_s),
            )
        if task is None:
            task = self.frontend.poll_for_decision_task(
                self.domain, self.task_list,
                identity=self.identity, timeout_s=timeout_s,
            )
        if task is None:
            return False
        if task.query is not None:
            self._answer_direct_query(task)
            return True
        history = self._full_history(task)
        state = _ReplayState(history)
        try:
            decisions = replay_decide(self.registry, history, state)
        except Exception:
            self._history_cache.pop(
                (task.workflow_id, task.run_id), None
            )
            self.frontend.respond_decision_task_failed(
                task.task_token, identity=self.identity,
                details=traceback.format_exc().encode(),
            )
            return True
        query_results = {}
        for qid, q in (task.queries or {}).items():
            query_results[qid] = self._run_query_handler(
                state, q.get("query_type", ""), q.get("query_args", b"")
            )
        self.frontend.respond_decision_task_completed(
            task.task_token, decisions, identity=self.identity,
            query_results=query_results or None,
            sticky_task_list=self.sticky_task_list,
            sticky_schedule_to_start_timeout_seconds=(
                self.STICKY_TIMEOUT_S if self.sticky else 0
            ),
        )
        return True

    def _full_history(self, task) -> List[HistoryEvent]:
        """Merge a (possibly partial) poll history onto the cached
        prefix; a cache miss or gap re-reads the full history."""
        key = (task.workflow_id, task.run_id)
        events = list(task.history)
        first = events[0].event_id if events else 1
        if first > 1:
            cached = self._history_cache.get(key, [])
            prefix = [e for e in cached if e.event_id < first]
            if not prefix or prefix[-1].event_id != first - 1:
                # the sticky cache is cold (worker restart / eviction):
                # fetch the real prefix instead of failing the decision
                full, _ = self.frontend.get_workflow_execution_history(
                    self.domain, task.workflow_id, task.run_id
                )
                prefix = [e for e in full if e.event_id < first]
            events = prefix + events
        if self.sticky:
            self._history_cache[key] = events
            self._history_cache.move_to_end(key)
            while len(self._history_cache) > self.CACHE_RUNS:
                self._history_cache.popitem(last=False)
        return events

    def _run_query_handler(self, state, query_type: str, args: bytes):
        handler = self.registry.query_handler(state.workflow_type)
        if handler is None:
            return {"error": f"no query handler for {state.workflow_type}"}
        try:
            return {"result": handler(query_type, args)}
        except Exception as e:
            return {"error": str(e)}

    def _answer_direct_query(self, task) -> None:
        q = task.query
        # direct queries carry no history; look the workflow up
        try:
            events, _ = self.frontend.get_workflow_execution_history(
                self.domain, task.workflow_id, task.run_id
            )
            state = _ReplayState(events)
            out = self._run_query_handler(
                state, q.get("query_type", ""), q.get("query_args", b"")
            )
        except Exception as e:
            out = {"error": str(e)}
        self.frontend.respond_query_task_completed(
            self.task_list, q["query_id"],
            result=out.get("result", b"") or b"",
            error=out.get("error", "") or "",
        )

    def run_until_stopped(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_and_process_one(timeout_s=0.2)
            except Exception:
                self._stop.wait(0.1)

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run_until_stopped,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def activity_method(fn: Callable) -> Callable:
    """Marker decorator for activity implementations."""
    fn.__is_activity__ = True
    return fn


# thread-local activity execution context: lets long-running activity
# code heartbeat without threading a token through every signature
# (reference: go client activity.RecordHeartbeat via context.Context)
_activity_ctx = threading.local()


def activity_heartbeat(details: bytes = b"") -> None:
    """Record a heartbeat for the activity running on this thread.
    No-op outside an activity (e.g. unit tests calling the fn
    directly)."""
    ctx = getattr(_activity_ctx, "ctx", None)
    if ctx is None:
        return
    frontend, token, identity = ctx
    frontend.record_activity_task_heartbeat(
        token, details=details, identity=identity
    )


class ActivityWorker:
    def __init__(
        self, frontend, domain: str, task_list: str,
        identity: str = "activity-worker",
    ) -> None:
        self.frontend = frontend
        self.domain = domain
        self.task_list = task_list
        self.identity = identity
        self._activities: Dict[str, Callable] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register_activity(self, activity_type: str, fn: Callable) -> None:
        self._activities[activity_type] = fn

    def register_activities_from(self, obj: Any) -> None:
        for name in dir(obj):
            fn = getattr(obj, name)
            if callable(fn) and getattr(fn, "__is_activity__", False):
                self._activities[name] = fn

    def poll_and_process_one(self, timeout_s: float = 1.0) -> bool:
        task = self.frontend.poll_for_activity_task(
            self.domain, self.task_list,
            identity=self.identity, timeout_s=timeout_s,
        )
        if task is None:
            return False
        fn = self._activities.get(task.activity_type)
        if fn is None:
            self.frontend.respond_activity_task_failed(
                task.task_token,
                reason=f"activity {task.activity_type!r} not registered",
                identity=self.identity,
            )
            return True
        try:
            _activity_ctx.ctx = (self.frontend, task.task_token,
                                 self.identity)
            try:
                result = fn(task.input)
            finally:
                _activity_ctx.ctx = None
            if result is None:
                result = b""
            if not isinstance(result, bytes):
                # fail LOUDLY: silently recording b"" loses the result
                # and surfaces far downstream in workflow code
                raise TypeError(
                    f"activity {task.activity_type!r} must return "
                    f"bytes (or None), got {type(result).__name__}"
                )
        except Exception as e:
            self.frontend.respond_activity_task_failed(
                task.task_token, reason=str(e) or type(e).__name__,
                details=traceback.format_exc().encode(),
                identity=self.identity,
            )
            return True
        self.frontend.respond_activity_task_completed(
            task.task_token, result=result, identity=self.identity,
        )
        return True

    def run_until_stopped(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_and_process_one(timeout_s=0.2)
            except Exception:
                self._stop.wait(0.1)

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run_until_stopped,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class Worker:
    """Decision + activity workers on one (domain, task list)."""

    def __init__(
        self, frontend, domain: str, task_list: str,
        identity: str = "worker", sticky: bool = True,
    ) -> None:
        self.registry = WorkflowRegistry()
        self.decisions = DecisionWorker(
            frontend, domain, task_list, self.registry,
            identity=f"{identity}-decider", sticky=sticky,
        )
        self.activities = ActivityWorker(
            frontend, domain, task_list, identity=f"{identity}-activities"
        )

    def register_workflow(self, workflow_type: str, fn: Callable) -> None:
        self.registry.register_workflow(workflow_type, fn)

    def register_activity(self, activity_type: str, fn: Callable) -> None:
        self.activities.register_activity(activity_type, fn)

    def register_query_handler(self, workflow_type: str, fn) -> None:
        self.registry.register_query_handler(workflow_type, fn)

    def register_local_activity(self, activity_type: str, fn) -> None:
        self.registry.register_local_activity(activity_type, fn)

    def start(self) -> None:
        self.decisions.start()
        self.activities.start()

    def stop(self) -> None:
        self.decisions.stop()
        self.activities.stop()
