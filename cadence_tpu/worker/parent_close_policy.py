"""Parent-close-policy processor as a system workflow.

Reference: service/worker/parentclosepolicy/ — when a closing parent
has many started children, the close processor offloads the
terminate/cancel fan-out to this system workflow instead of doing it
inline (processor.go + workflow.go). The inline path lives in the
transfer queue (_apply_parent_close_policy); this workflow covers the
offloaded shape.
"""

from __future__ import annotations

import json
from typing import List

from cadence_tpu.runtime.api import (
    SignalWithStartRequest,
    StartWorkflowRequest,
)

from .sdk import Worker
from .archiver import SYSTEM_DOMAIN

PCP_WORKFLOW_TYPE = "cadence-sys-parent-close-policy-workflow"
PCP_WORKFLOW_ID = "cadence-parent-close-policy"
PCP_TASK_LIST = "cadence-parent-close-policy-tl"
PCP_SIGNAL = "parent-close-request"
_REQUESTS_PER_RUN = 500


class ParentClosePolicyClient:
    def __init__(self, frontend) -> None:
        self.frontend = frontend

    def send(self, children: List[dict]) -> None:
        """children: [{domain, workflow_id, run_id, policy}] with policy
        'terminate' | 'cancel'."""
        self.frontend.signal_with_start_workflow_execution(
            SignalWithStartRequest(
                start=StartWorkflowRequest(
                    domain=SYSTEM_DOMAIN,
                    workflow_id=PCP_WORKFLOW_ID,
                    workflow_type=PCP_WORKFLOW_TYPE,
                    task_list=PCP_TASK_LIST,
                    execution_start_to_close_timeout_seconds=3600 * 24,
                    task_start_to_close_timeout_seconds=30,
                ),
                signal_name=PCP_SIGNAL,
                signal_input=json.dumps(children).encode(),
            )
        )


def parent_close_policy_workflow(ctx, input: bytes):
    handled = 0
    while handled < _REQUESTS_PER_RUN:
        payload = yield ctx.wait_signal(PCP_SIGNAL)
        yield ctx.schedule_activity(
            "apply_parent_close_policy", payload,
            start_to_close_timeout_seconds=300,
        )
        handled += 1
    # drain signals recorded but unconsumed — continue-as-new would
    # orphan those close requests (same pattern as archival_workflow)
    while True:
        payload = yield ctx.poll_signal(PCP_SIGNAL)
        if payload is None:
            break
        yield ctx.schedule_activity(
            "apply_parent_close_policy", payload,
            start_to_close_timeout_seconds=300,
        )
    yield ctx.continue_as_new(b"")


class ParentClosePolicyActivities:
    def __init__(self, frontend) -> None:
        self.frontend = frontend

    def apply_parent_close_policy(self, payload: bytes) -> bytes:
        from cadence_tpu.runtime.api import (
            CancellationAlreadyRequestedError,
            EntityNotExistsServiceError,
        )

        children = json.loads(payload)
        applied = 0
        for child in children:
            try:
                if child["policy"] == "terminate":
                    self.frontend.terminate_workflow_execution(
                        child["domain"], child["workflow_id"],
                        child.get("run_id", ""),
                        reason="by parent close policy",
                    )
                elif child["policy"] == "cancel":
                    self.frontend.request_cancel_workflow_execution(
                        child["domain"], child["workflow_id"],
                        child.get("run_id", ""),
                    )
                applied += 1
            except (EntityNotExistsServiceError,
                    CancellationAlreadyRequestedError):
                continue  # child already closed/gone: policy satisfied
            # any OTHER failure (transient store/RPC error) must fail
            # the activity so redelivery retries — swallowing it would
            # permanently drop the terminate/cancel (ref
            # service/worker/parentclosepolicy processor retries)
        return str(applied).encode()


def build_parent_close_policy_worker(frontend) -> Worker:
    acts = ParentClosePolicyActivities(frontend)
    w = Worker(frontend, SYSTEM_DOMAIN, PCP_TASK_LIST, identity="pcp")
    w.register_workflow(PCP_WORKFLOW_TYPE, parent_close_policy_workflow)
    w.register_activity(
        "apply_parent_close_policy", acts.apply_parent_close_policy
    )
    return w
