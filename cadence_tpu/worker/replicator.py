"""Worker replicator: push-model replication consumers.

Reference: service/worker/replicator/ — replicator.go:84-213 +
processor.go:85-482: per-remote-cluster Kafka consumers decode
replication tasks and apply them through the history client, with
retry + DLQ; domainReplicationMessageProcessor.go applies domain
metadata changes from the master cluster. The pull model
(runtime/replication/processor.py) is the primary path; this push
consumer covers the reference's Kafka deployment shape on the in-proc
bus.
"""

from __future__ import annotations

import threading
from typing import Optional

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.messaging import MessageBus
from cadence_tpu.runtime.replication.messages import (
    HistoryTaskV2,
    RetryTaskV2Error,
)


def replication_topic(source_cluster: str) -> str:
    return f"replication-{source_cluster}"


class ReplicationPublisher:
    """Active-side pump: hydrate the shard's replication tasks and
    publish them to the cluster topic (replicatorQueueProcessor's Kafka
    emit path)."""

    def __init__(self, history_service, bus: MessageBus,
                 source_cluster: str) -> None:
        self.history = history_service
        self.producer = bus.new_producer(replication_topic(source_cluster))
        self._cursors = {}

    def publish_once(self) -> int:
        published = 0
        for shard_id in self.history.controller.owned_shards():
            last = self._cursors.get(shard_id, 0)
            msgs = self.history.get_replication_messages(
                shard_id, last, cluster="<bus>"
            )
            for task in msgs.tasks:
                self.producer.publish(
                    f"{task.workflow_id}:{task.run_id}",
                    _task_to_dict(task),
                )
                published += 1
            self._cursors[shard_id] = msgs.last_retrieved_id
        return published


class HistoryReplicationConsumer:
    """Passive-side consumer: bus topic → ReplicateEventsV2 with retry,
    re-replication on gaps, and the bus's DLQ on poison messages."""

    def __init__(
        self,
        bus: MessageBus,
        source_cluster: str,
        history_service,
        rereplicator=None,
        group: str = "",
    ) -> None:
        self.consumer = bus.new_consumer(
            replication_topic(source_cluster),
            group or f"replicator-{source_cluster}",
        )
        self.history = history_service
        self.rereplicator = rereplicator
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _apply(self, msg) -> None:
        task = _task_from_dict(msg.value)
        try:
            self.history.replicate_events_v2(task)
        except RetryTaskV2Error as e:
            if self.rereplicator is None:
                raise
            self.rereplicator.rereplicate(e)
            self.history.replicate_events_v2(task)

    def process_backlog(self) -> int:
        return self.consumer.drain(self._apply)

    def start(self, interval_s: float = 0.05) -> None:
        def pump() -> None:
            while not self._stop.is_set():
                msg = self.consumer.poll(timeout=interval_s)
                if msg is None:
                    continue
                try:
                    self._apply(msg)
                except Exception:
                    self.consumer.nack(msg)
                else:
                    self.consumer.ack(msg)

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class DomainReplicationProcessor:
    """Applies domain metadata changes from the master cluster
    (domainReplicationMessageProcessor.go)."""

    def __init__(self, bus: MessageBus, domain_handler,
                 group: str = "domain-replicator") -> None:
        self.consumer = bus.new_consumer("domain-replication", group)
        self.domain_handler = domain_handler
        self._stop = threading.Event()
        self._thread = None

    def process_backlog(self) -> int:
        return self.consumer.drain(
            lambda m: self.domain_handler.apply_replication_record(m.value)
        )

    def start(self, interval_s: float = 0.05) -> None:
        """Continuous pump (the worker service runs this like any other
        consumer — without it, domain registrations/failovers published
        by the master would never apply on this cluster)."""

        def pump() -> None:
            while not self._stop.is_set():
                msg = self.consumer.poll(timeout=interval_s)
                if msg is None:
                    continue
                try:
                    self.domain_handler.apply_replication_record(msg.value)
                except Exception:
                    self.consumer.nack(msg)
                else:
                    self.consumer.ack(msg)

        self._thread = threading.Thread(
            target=pump, name="domain-replication", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def _task_to_dict(task: HistoryTaskV2) -> dict:
    return {
        "task_id": task.task_id,
        "domain_id": task.domain_id,
        "workflow_id": task.workflow_id,
        "run_id": task.run_id,
        "version_history_items": task.version_history_items,
        "events": [e.to_dict() for e in task.events],
        "new_run_events": [e.to_dict() for e in task.new_run_events],
        "new_run_id": task.new_run_id,
    }


def _task_from_dict(d: dict) -> HistoryTaskV2:
    return HistoryTaskV2(
        task_id=d["task_id"],
        domain_id=d["domain_id"],
        workflow_id=d["workflow_id"],
        run_id=d["run_id"],
        version_history_items=d["version_history_items"],
        events=[HistoryEvent.from_dict(e) for e in d["events"]],
        new_run_events=[
            HistoryEvent.from_dict(e) for e in d["new_run_events"]
        ],
        new_run_id=d.get("new_run_id", ""),
    )
