"""Worker service assembly: all background daemons on one host.

Reference: service/worker/service.go — starts the sub-daemons that are
enabled by config: replicator consumers (global-domain clusters),
indexer (advanced visibility), archiver, scanner, batcher,
parent-close-policy, each on the system domain.
"""

from __future__ import annotations

from typing import List, Optional

from cadence_tpu.messaging import MessageBus

from .archiver import SYSTEM_DOMAIN, build_archiver_worker
from .batcher import build_batcher_worker
from .indexer import Indexer
from .parent_close_policy import build_parent_close_policy_worker
from .replicator import DomainReplicationProcessor, HistoryReplicationConsumer
from .scanner import build_scanner_worker


class WorkerService:
    def __init__(
        self,
        frontend,
        persistence,
        num_shards: int,
        bus: Optional[MessageBus] = None,
        domain_handler=None,
        history_service=None,
        visibility_store=None,
        enable_scanner: bool = True,
        enable_batcher: bool = True,
        enable_archiver: bool = True,
        enable_pcp: bool = True,
        enable_indexer: bool = False,
        replication_sources: Optional[List[str]] = None,
    ) -> None:
        self.frontend = frontend
        self._ensure_system_domain(frontend)
        self._scanner_enabled = enable_scanner
        self.workers = []
        self.consumers = []
        if enable_archiver:
            self.workers.append(
                build_archiver_worker(
                    frontend, persistence.history, persistence.execution,
                    shard_resolver=(
                        history_service.controller.shard_for
                        if history_service is not None
                        else None
                    ),
                )
            )
        if enable_scanner:
            self.workers.append(
                build_scanner_worker(
                    frontend, persistence.task, persistence.history,
                    persistence.execution, num_shards=num_shards,
                    # live ids, not the boot-time count: after a shard
                    # split the scavenger must count the new shard's
                    # runs as live or it would destroy their histories
                    shard_ids=(
                        history_service.controller.shard_ids
                        if history_service is not None else None
                    ),
                    matching=frontend.matching if hasattr(
                        frontend, "matching"
                    ) else None,
                )
            )
        if enable_batcher:
            self.workers.append(build_batcher_worker(frontend))
        if enable_pcp:
            self.workers.append(build_parent_close_policy_worker(frontend))
        if enable_indexer and bus is not None and visibility_store is not None:
            self.consumers.append(Indexer(bus, visibility_store))
        if bus is not None and domain_handler is not None:
            self.domain_replication = DomainReplicationProcessor(
                bus, domain_handler
            )
            # pumped like every other consumer — construction alone
            # would leave published domain records unapplied forever
            self.consumers.append(self.domain_replication)
        else:
            self.domain_replication = None
        if bus is not None and history_service is not None:
            for source in replication_sources or []:
                self.consumers.append(
                    HistoryReplicationConsumer(bus, source, history_service)
                )

    @staticmethod
    def _ensure_system_domain(frontend) -> None:
        from cadence_tpu.frontend.domain_handler import DomainAlreadyExistsError

        try:
            frontend.register_domain(SYSTEM_DOMAIN, retention_days=1)
        except DomainAlreadyExistsError:
            pass

    def start(self) -> None:
        for w in self.workers:
            w.start()
        for c in self.consumers:
            c.start()
        self._kick_scanner()

    def _kick_scanner(self) -> None:
        """Launch the scavenger cron workflow (scanner.go starts it at
        service start; AlreadyStarted means a previous run is live)."""
        if not self._scanner_enabled:
            return
        from cadence_tpu.runtime.api import (
            StartWorkflowRequest,
            WorkflowExecutionAlreadyStartedServiceError,
        )

        from .scanner import (
            SCANNER_TASK_LIST,
            SCANNER_WORKFLOW_ID,
            SCANNER_WORKFLOW_TYPE,
        )

        try:
            self.frontend.start_workflow_execution(
                StartWorkflowRequest(
                    domain=SYSTEM_DOMAIN,
                    workflow_id=SCANNER_WORKFLOW_ID,
                    workflow_type=SCANNER_WORKFLOW_TYPE,
                    task_list=SCANNER_TASK_LIST,
                    input=b"60",
                    execution_start_to_close_timeout_seconds=3600 * 24,
                    task_start_to_close_timeout_seconds=30,
                )
            )
        except WorkflowExecutionAlreadyStartedServiceError:
            pass

    def stop(self) -> None:
        for w in self.workers:
            w.stop()
        for c in self.consumers:
            c.stop()
