"""Service clients: ring-routed access to history and matching.

Reference: /root/reference/client/ — per-service clients that resolve
the owning host through the membership ring and dispatch RPCs
(history routes by workflowID → shard → host,
client/history/client.go:844-846; matching routes by task list). In
this build dispatch is an in-process call into the target host's
engine registry; a gRPC transport can replace `_dispatch` without
touching callers.
"""

from .history import HistoryClient
from .matching import MatchingClient

__all__ = ["HistoryClient", "MatchingClient"]
