"""Service clients: ring-routed access to history and matching.

Reference: /root/reference/client/ — per-service clients that resolve
the owning host through the membership ring and dispatch RPCs
(history routes by workflowID → shard → host,
client/history/client.go:844-846; matching routes by task list).
HistoryClient/MatchingClient dispatch in-process into the target host's
engine registry; the Routed* variants add the process boundary — ring
lookup → host address → gRPC stub (rpc/server.py endpoints).
"""

from .history import HistoryClient
from .matching import MatchingClient
from .routed import RoutedHistoryClient, RoutedMatchingClient

__all__ = [
    "HistoryClient",
    "MatchingClient",
    "RoutedHistoryClient",
    "RoutedMatchingClient",
]
