"""History client: workflowID → shard → owning host → engine.

Reference: /root/reference/client/history/client.go (GetClientForKey
routing :844-846) + clientBean. Every call resolves the target shard's
engine at call time, so shard movement between calls is handled by the
receiving controller (ShardOwnershipLostError surfaces to the caller,
which retries after the ring settles — retryableClient.go).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict

from cadence_tpu.runtime.controller import (
    ShardController,
    ShardOwnershipLostError,
)
from cadence_tpu.runtime.persistence.errors import (
    ShardOwnershipLostError as PersistenceShardOwnershipLost,
)

# Bounded ownership-lost retry (reference retryableClient.go): every
# attempt re-resolves through the controllers, so a shard mid-move —
# reshard handoff or plain membership churn — is found at its new
# owner once the routing epoch flips. Jittered exponential backoff
# decorrelates the thundering herd of callers all retrying the same
# moved shard.
_OWNERSHIP_RETRY = 6
_OWNERSHIP_BACKOFF_S = 0.05
_OWNERSHIP_BACKOFF_MAX_S = 1.0


def _ownership_backoff_s(attempt: int, rng=random) -> float:
    base = min(
        _OWNERSHIP_BACKOFF_S * (2 ** (attempt - 1)), _OWNERSHIP_BACKOFF_MAX_S
    )
    return base * rng.uniform(0.5, 1.5)


class HistoryClient:
    """Routes engine calls through one or more in-process controllers.

    ``controllers`` maps host identity → ShardController; the owning
    host for a shard is whichever controller claims it. A single-host
    deployment passes one controller.
    """

    def __init__(self, controllers) -> None:
        if isinstance(controllers, ShardController):
            controllers = {controllers.identity: controllers}
        self._controllers: Dict[str, ShardController] = dict(controllers)

    def add_host(self, controller: ShardController) -> None:
        self._controllers[controller.identity] = controller

    def remove_host(self, identity: str) -> None:
        self._controllers.pop(identity, None)

    def _engine_for(self, workflow_id: str):
        """ONE ring/shard-map pass over the controllers (retry policy
        lives in _call, wrapping the engine invocation too)."""
        last_err = None
        for controller in self._controllers.values():
            try:
                return controller.get_engine(workflow_id)
            except ShardOwnershipLostError as e:
                last_err = e
        raise last_err or ShardOwnershipLostError(-1, "<unknown>")

    def _call(self, workflow_id: str, method: str, *args, **kwargs):
        """Resolve + invoke under a bounded ownership-lost retry: BOTH
        shapes — the controller's (no local handle) and the persistence
        rangeID-fencing sibling raised mid-call by a fenced/stolen
        shard — re-resolve and retry instead of surfacing to callers
        (frontends saw the raw error during any ownership change).
        Retried attempts ride the active trace as ``retry`` spans
        (utils/tracing.py), so a chaos/reshard run's recovery path is
        readable off the flight recorder instead of correlated from
        logs."""
        from cadence_tpu.utils.tracing import TRACER

        last_err = None
        for attempt in range(_OWNERSHIP_RETRY):
            if attempt:
                time.sleep(_ownership_backoff_s(attempt))
            try:
                if attempt == 0:
                    engine = self._engine_for(workflow_id)
                    return getattr(engine, method)(*args, **kwargs)
                with TRACER.span(
                    f"retry.{method}", service="history_client",
                    attempt=attempt,
                ) as span:
                    span.annotate(
                        f"ownership_lost retry attempt={attempt} "
                        f"({type(last_err).__name__})"
                    )
                    engine = self._engine_for(workflow_id)
                    return getattr(engine, method)(*args, **kwargs)
            except (ShardOwnershipLostError,
                    PersistenceShardOwnershipLost) as e:
                last_err = e
        raise last_err

    # -- workflow mutations (routed by workflow_id) --------------------

    def start_workflow_execution(self, request, **kwargs):
        return self._call(
            request.workflow_id, "start_workflow_execution", request, **kwargs
        )

    def signal_workflow_execution(self, request):
        return self._call(
            request.workflow_id, "signal_workflow_execution", request
        )

    def signal_with_start_workflow_execution(self, request):
        return self._call(
            request.start.workflow_id,
            "signal_with_start_workflow_execution",
            request,
        )

    def terminate_workflow_execution(self, domain_name, workflow_id, run_id="",
                                     **kwargs):
        return self._call(
            workflow_id, "terminate_workflow_execution", domain_name,
            workflow_id, run_id, **kwargs
        )

    def request_cancel_workflow_execution(self, domain_name, workflow_id,
                                          run_id="", **kwargs):
        return self._call(
            workflow_id, "request_cancel_workflow_execution", domain_name,
            workflow_id, run_id, **kwargs
        )

    def record_decision_task_started(self, domain_id, workflow_id, run_id,
                                     schedule_id, request_id, identity=""):
        return self._call(
            workflow_id, "record_decision_task_started", domain_id,
            workflow_id, run_id, schedule_id, request_id, identity,
        )

    def record_activity_task_started(self, domain_id, workflow_id, run_id,
                                     schedule_id, request_id, identity=""):
        return self._call(
            workflow_id, "record_activity_task_started", domain_id,
            workflow_id, run_id, schedule_id, request_id, identity,
        )

    def respond_decision_task_completed(self, task_token, decisions, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_decision_task_completed",
            task_token, decisions, **kwargs
        )

    def respond_decision_task_failed(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_decision_task_failed",
            task_token, **kwargs
        )

    def respond_activity_task_completed(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_activity_task_completed",
            task_token, **kwargs
        )

    def respond_activity_task_failed(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_activity_task_failed",
            task_token, **kwargs
        )

    def respond_activity_task_canceled(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_activity_task_canceled",
            task_token, **kwargs
        )

    def record_activity_task_heartbeat(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "record_activity_task_heartbeat",
            task_token, **kwargs
        )

    def record_child_execution_completed(self, domain_id, workflow_id, run_id,
                                         initiated_id, close_event_type,
                                         **close_attrs):
        return self._call(
            workflow_id, "record_child_execution_completed", domain_id,
            workflow_id, run_id, initiated_id, close_event_type,
            **close_attrs
        )

    # -- reads ---------------------------------------------------------

    def get_workflow_execution_history(self, domain_name, workflow_id,
                                       run_id="", **kwargs):
        return self._call(
            workflow_id, "get_workflow_execution_history", domain_name,
            workflow_id, run_id, **kwargs
        )

    def describe_workflow_execution(self, domain_name, workflow_id, run_id=""):
        return self._call(
            workflow_id, "describe_workflow_execution", domain_name,
            workflow_id, run_id,
        )

    def query_workflow(self, domain_name, workflow_id, run_id="", **kwargs):
        return self._call(
            workflow_id, "query_workflow", domain_name, workflow_id, run_id,
            **kwargs
        )

    def reset_workflow_execution(self, domain_name, workflow_id, run_id="",
                                 **kwargs):
        return self._call(
            workflow_id, "reset_workflow_execution", domain_name,
            workflow_id, run_id, **kwargs
        )

    def reset_sticky_task_list(self, domain_name, workflow_id, run_id="",
                               **kwargs):
        return self._call(
            workflow_id, "reset_sticky_task_list", domain_name, workflow_id,
            run_id, **kwargs
        )
