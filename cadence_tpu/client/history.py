"""History client: workflowID → shard → owning host → engine.

Reference: /root/reference/client/history/client.go (GetClientForKey
routing :844-846) + clientBean. Every call resolves the target shard's
engine at call time, so shard movement between calls is handled by the
receiving controller (ShardOwnershipLostError surfaces to the caller,
which retries after the ring settles — retryableClient.go).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, Optional

from cadence_tpu.runtime.api import ServiceBusyError
from cadence_tpu.runtime.controller import (
    ShardController,
    ShardOwnershipLostError,
)
from cadence_tpu.runtime.persistence.errors import (
    ShardOwnershipLostError as PersistenceShardOwnershipLost,
)
from cadence_tpu.utils.metrics import NOOP, Scope
from cadence_tpu.utils.quotas import RetryBudget

# Bounded ownership-lost retry (reference retryableClient.go): every
# attempt re-resolves through the controllers, so a shard mid-move —
# reshard handoff or plain membership churn — is found at its new
# owner once the routing epoch flips. Jittered exponential backoff
# decorrelates the thundering herd of callers all retrying the same
# moved shard.
_OWNERSHIP_RETRY = 6
_OWNERSHIP_BACKOFF_S = 0.05
_OWNERSHIP_BACKOFF_MAX_S = 1.0

# ServiceBusy retries are BUDGETED, not merely bounded (ISSUE 15): a
# saturated server shedding load must not see every rejection come
# straight back N more times — that multiplies the overload it is
# shedding. The budget refills on successes, so a healthy client
# retries transient sheds freely while a client facing sustained
# overload converges to ~offered × (1 + ratio).
_BUSY_RETRY = 3
_BUSY_BACKOFF_MAX_S = 2.0


def _ownership_backoff_s(attempt: int, rng=random) -> float:
    base = min(
        _OWNERSHIP_BACKOFF_S * (2 ** (attempt - 1)), _OWNERSHIP_BACKOFF_MAX_S
    )
    return base * rng.uniform(0.5, 1.5)


def _busy_backoff_s(e: ServiceBusyError, attempt: int) -> float:
    """Honor the shed response's retry-after hint; fall back to the
    ownership backoff schedule when the server sent none."""
    hint = getattr(e, "retry_after_s", 0.0) or 0.0
    if hint > 0:
        return min(hint, _BUSY_BACKOFF_MAX_S)
    return _ownership_backoff_s(attempt)


class HistoryClient:
    """Routes engine calls through one or more in-process controllers.

    ``controllers`` maps host identity → ShardController; the owning
    host for a shard is whichever controller claims it. A single-host
    deployment passes one controller.
    """

    def __init__(
        self,
        controllers,
        retry_budget: Optional[RetryBudget] = None,
        metrics: Scope = NOOP,
    ) -> None:
        if isinstance(controllers, ShardController):
            controllers = {controllers.identity: controllers}
        self._controllers: Dict[str, ShardController] = dict(controllers)
        # per-client ServiceBusy retry budget (token bucket refilled by
        # successes); pass a shared instance to make several clients
        # share one budget, or None for the default
        self.retry_budget = retry_budget or RetryBudget()
        self._client_metrics = metrics.tagged(layer="client")

    def add_host(self, controller: ShardController) -> None:
        self._controllers[controller.identity] = controller

    def remove_host(self, identity: str) -> None:
        self._controllers.pop(identity, None)

    def _engine_for(self, workflow_id: str):
        """ONE ring/shard-map pass over the controllers (retry policy
        lives in _call, wrapping the engine invocation too)."""
        last_err = None
        for controller in self._controllers.values():
            try:
                return controller.get_engine(workflow_id)
            except ShardOwnershipLostError as e:
                last_err = e
        raise last_err or ShardOwnershipLostError(-1, "<unknown>")

    def _call(self, workflow_id: str, method: str, *args, **kwargs):
        """Dispatch under the ServiceBusy retry budget: a shed response
        (retryable, carries retry-after) is re-offered after its hint
        — but each re-offer WITHDRAWS a budget token, and the budget
        refills only on successes. Exhausted budget (or attempts) ⇒
        the shed surfaces to the caller; ``retry_budget_exhausted``
        counts the former — the retry-storm breaker observable."""
        attempt = 0
        while True:
            try:
                out = self._call_inner(
                    workflow_id, method, *args, **kwargs
                )
                self.retry_budget.record_success()
                return out
            except ServiceBusyError as e:
                attempt += 1
                if attempt > _BUSY_RETRY:
                    raise
                if not self.retry_budget.can_retry():
                    self._client_metrics.inc("retry_budget_exhausted")
                    raise
                time.sleep(_busy_backoff_s(e, attempt))

    def _call_inner(self, workflow_id: str, method: str, *args, **kwargs):
        """Resolve + invoke under a bounded ownership-lost retry: BOTH
        shapes — the controller's (no local handle) and the persistence
        rangeID-fencing sibling raised mid-call by a fenced/stolen
        shard — re-resolve and retry instead of surfacing to callers
        (frontends saw the raw error during any ownership change).
        Retried attempts ride the active trace as ``retry`` spans
        (utils/tracing.py), so a chaos/reshard run's recovery path is
        readable off the flight recorder instead of correlated from
        logs."""
        from cadence_tpu.utils.tracing import TRACER

        last_err = None
        for attempt in range(_OWNERSHIP_RETRY):
            if attempt:
                time.sleep(_ownership_backoff_s(attempt))
            try:
                if attempt == 0:
                    engine = self._engine_for(workflow_id)
                    return getattr(engine, method)(*args, **kwargs)
                with TRACER.span(
                    f"retry.{method}", service="history_client",
                    attempt=attempt,
                ) as span:
                    span.annotate(
                        f"ownership_lost retry attempt={attempt} "
                        f"({type(last_err).__name__})"
                    )
                    engine = self._engine_for(workflow_id)
                    return getattr(engine, method)(*args, **kwargs)
            except (ShardOwnershipLostError,
                    PersistenceShardOwnershipLost) as e:
                last_err = e
        raise last_err

    # -- workflow mutations (routed by workflow_id) --------------------

    def start_workflow_execution(self, request, **kwargs):
        return self._call(
            request.workflow_id, "start_workflow_execution", request, **kwargs
        )

    def signal_workflow_execution(self, request):
        return self._call(
            request.workflow_id, "signal_workflow_execution", request
        )

    def signal_with_start_workflow_execution(self, request):
        return self._call(
            request.start.workflow_id,
            "signal_with_start_workflow_execution",
            request,
        )

    def terminate_workflow_execution(self, domain_name, workflow_id, run_id="",
                                     **kwargs):
        return self._call(
            workflow_id, "terminate_workflow_execution", domain_name,
            workflow_id, run_id, **kwargs
        )

    def request_cancel_workflow_execution(self, domain_name, workflow_id,
                                          run_id="", **kwargs):
        return self._call(
            workflow_id, "request_cancel_workflow_execution", domain_name,
            workflow_id, run_id, **kwargs
        )

    def record_decision_task_started(self, domain_id, workflow_id, run_id,
                                     schedule_id, request_id, identity=""):
        return self._call(
            workflow_id, "record_decision_task_started", domain_id,
            workflow_id, run_id, schedule_id, request_id, identity,
        )

    def record_activity_task_started(self, domain_id, workflow_id, run_id,
                                     schedule_id, request_id, identity=""):
        return self._call(
            workflow_id, "record_activity_task_started", domain_id,
            workflow_id, run_id, schedule_id, request_id, identity,
        )

    def respond_decision_task_completed(self, task_token, decisions, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_decision_task_completed",
            task_token, decisions, **kwargs
        )

    def respond_decision_task_failed(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_decision_task_failed",
            task_token, **kwargs
        )

    def respond_activity_task_completed(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_activity_task_completed",
            task_token, **kwargs
        )

    def respond_activity_task_failed(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_activity_task_failed",
            task_token, **kwargs
        )

    def respond_activity_task_canceled(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "respond_activity_task_canceled",
            task_token, **kwargs
        )

    def record_activity_task_heartbeat(self, task_token, **kwargs):
        return self._call(
            task_token["workflow_id"], "record_activity_task_heartbeat",
            task_token, **kwargs
        )

    def record_child_execution_completed(self, domain_id, workflow_id, run_id,
                                         initiated_id, close_event_type,
                                         **close_attrs):
        return self._call(
            workflow_id, "record_child_execution_completed", domain_id,
            workflow_id, run_id, initiated_id, close_event_type,
            **close_attrs
        )

    # -- reads ---------------------------------------------------------

    def get_workflow_execution_history(self, domain_name, workflow_id,
                                       run_id="", **kwargs):
        return self._call(
            workflow_id, "get_workflow_execution_history", domain_name,
            workflow_id, run_id, **kwargs
        )

    def describe_workflow_execution(self, domain_name, workflow_id, run_id=""):
        return self._call(
            workflow_id, "describe_workflow_execution", domain_name,
            workflow_id, run_id,
        )

    def query_workflow(self, domain_name, workflow_id, run_id="", **kwargs):
        return self._call(
            workflow_id, "query_workflow", domain_name, workflow_id, run_id,
            **kwargs
        )

    def reset_workflow_execution(self, domain_name, workflow_id, run_id="",
                                 **kwargs):
        return self._call(
            workflow_id, "reset_workflow_execution", domain_name,
            workflow_id, run_id, **kwargs
        )

    def reset_sticky_task_list(self, domain_name, workflow_id, run_id="",
                               **kwargs):
        return self._call(
            workflow_id, "reset_sticky_task_list", domain_name, workflow_id,
            run_id, **kwargs
        )
