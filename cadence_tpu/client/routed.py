"""Cross-process routed clients: ring lookup → host address → gRPC stub.

Reference: client/history/client.go:844-846 (GetClientForKey: shard key
→ membership ring → host → RPC client) and client/matching/client.go
(task-list-name routing). The in-process clients (client/history.py,
client/matching.py) short-circuit to local engines; these variants add
the process boundary: a shard (or task list) owned by another host is
reached through that host's History/Matching gRPC endpoint
(rpc/server.py), with stubs cached per address.

Host identities in the membership ring ARE dial addresses (the
reference's ringpop identities are host:port the same way), so routing
needs no separate address registry.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import grpc

from cadence_tpu.runtime.controller import ShardOwnershipLostError
from cadence_tpu.runtime.membership import Monitor
from cadence_tpu.utils.backoff import ExponentialRetryPolicy, retry
from cadence_tpu.utils.hashing import shard_for_workflow

from .history import HistoryClient
from .matching import MatchingClient

# Service-client retry schedule: the reference wraps every service
# client in a retryable layer (client/history/retryableClient.go:1-60,
# client/matching/retryableClient.go) with
# CreateHistoryServiceRetryPolicy (50ms initial, bounded expiration).
# Each attempt re-resolves the ring, so a shard that moved mid-call is
# found at its new owner once the ring settles.
ROUTED_RETRY_POLICY = ExponentialRetryPolicy(
    initial_interval_s=0.05,
    backoff_coefficient=2.0,
    maximum_interval_s=2.0,
    expiration_interval_s=10.0,
    maximum_attempts=0,
)


def is_routed_retryable(e: Exception) -> bool:
    """ShardOwnershipLost + transport-level transients (the reference's
    common.IsServiceTransientError + membership re-resolution cases)."""
    from cadence_tpu.runtime.persistence.errors import (
        ShardOwnershipLostError as PersistenceShardOwnershipLost,
    )

    # both ownership-lost shapes: the controller's (remote handler, and
    # the rpc client rebuilds this class from the wire) AND the
    # persistence layer's rangeID-fencing sibling, which the LOCAL
    # engine path surfaces directly when the shard moved away mid-call
    if isinstance(e, (ShardOwnershipLostError,
                      PersistenceShardOwnershipLost, ConnectionError)):
        return True
    if isinstance(e, grpc.RpcError):
        # CANCELLED: the stub cache closed this channel under us (its
        # host left the ring mid-call) — the next attempt re-resolves
        # and dials fresh
        return e.code() in (grpc.StatusCode.UNAVAILABLE,
                            grpc.StatusCode.CANCELLED)
    if isinstance(e, ValueError) and "closed channel" in str(e):
        return True  # raced an evicted stub; re-resolve and redial
    # ring momentarily empty while a host is being replaced
    if isinstance(e, RuntimeError) and "no hosts in service ring" in str(e):
        return True
    return False


def _traced_attempts(fn, method: str):
    """Wrap a retried thunk so re-resolution attempts annotate the
    active trace (utils/tracing.py) — no active trace, no cost beyond
    an int increment."""
    from cadence_tpu.utils.tracing import TRACER

    state = {"n": 0}

    def attempt():
        state["n"] += 1
        if state["n"] > 1:
            TRACER.annotate(
                f"routed retry attempt={state['n'] - 1} op={method}"
            )
        return fn()

    return attempt


class _StubCache:
    def __init__(self, factory) -> None:
        self._factory = factory
        self._stubs: Dict[str, object] = {}
        self._lock = threading.Lock()

    def get(self, address: str):
        with self._lock:
            stub = self._stubs.get(address)
            if stub is None:
                stub = self._stubs[address] = self._factory(address)
            return stub

    def evict(self, addresses) -> None:
        """Drop (and close) stubs for hosts that left the ring — an
        address reused by a new instance must get a fresh channel."""
        with self._lock:
            stubs = [
                self._stubs.pop(a) for a in addresses if a in self._stubs
            ]
        for stub in stubs:
            try:
                stub.close()
            except Exception:
                pass

    def close(self) -> None:
        with self._lock:
            for stub in self._stubs.values():
                stub.close()
            self._stubs.clear()


class RoutedHistoryClient(HistoryClient):
    """HistoryClient surface; shard → ring("history") → local engine or
    remote History endpoint."""

    def __init__(
        self,
        monitor: Monitor,
        local_controller=None,
        num_shards: Optional[int] = None,
        retry_budget=None,
        metrics=None,
    ) -> None:
        from cadence_tpu.rpc.client import RemoteHistory
        from cadence_tpu.utils.metrics import NOOP

        super().__init__(
            {} if local_controller is None
            else {local_controller.identity: local_controller},
            retry_budget=retry_budget,
            metrics=metrics if metrics is not None else NOOP,
        )
        self.monitor = monitor
        self.local = local_controller
        self.num_shards = (
            num_shards if num_shards is not None
            else (local_controller.num_shards if local_controller else 1)
        )
        self._stubs = _StubCache(RemoteHistory)
        self.retry_policy = ROUTED_RETRY_POLICY
        self._listener = f"routed-history-{id(self)}"
        monitor.resolver("history").add_listener(
            self._listener,
            lambda ev: self._stubs.evict(ev.hosts_removed),
        )

    def _call_once(self, workflow_id: str, method: str, *args, **kwargs):
        # epoch-versioned routing: after a reshard flip the resolver's
        # ShardMap is the truth; the static modulo is only the pre-
        # reshard (epoch 0) fallback for monitors without a map
        shard_map = self.monitor.resolver("history").shard_map()
        if shard_map is not None:
            shard_id = shard_map.shard_for(workflow_id)
        else:
            shard_id = shard_for_workflow(workflow_id, self.num_shards)
        owner = self.monitor.resolver("history").lookup(
            str(shard_id)
        ).identity
        if self.local is not None and owner == self.local.identity:
            return getattr(
                self.local.get_engine_for_shard(shard_id), method
            )(*args, **kwargs)
        return getattr(self._stubs.get(owner), method)(*args, **kwargs)

    def _call_inner(self, workflow_id: str, method: str, *args, **kwargs):
        # the ownership/transport retry layer; the ServiceBusy retry
        # BUDGET lives above it in HistoryClient._call — a shed
        # response is deliberately NOT in is_routed_retryable, or the
        # unbudgeted transport retry would amplify the very overload
        # the server is shedding
        return retry(
            _traced_attempts(
                lambda: self._call_once(workflow_id, method, *args,
                                        **kwargs),
                method,
            ),
            policy=self.retry_policy,
            is_retriable=is_routed_retryable,
        )

    def close(self) -> None:
        self.monitor.resolver("history").remove_listener(self._listener)
        self._stubs.close()


class RoutedMatchingClient(MatchingClient):
    """MatchingClient surface; task list → ring("matching") → local
    engine or remote Matching endpoint."""

    def __init__(self, monitor: Monitor, local_engine=None,
                 local_identity: str = "") -> None:
        from cadence_tpu.rpc.client import RemoteMatching

        super().__init__(
            {local_identity or "local": local_engine}
            if local_engine is not None else {}
        )
        self.monitor = monitor
        self.local_engine = local_engine
        self.local_identity = local_identity or monitor.self_identity
        self._stubs = _StubCache(RemoteMatching)
        self.retry_policy = ROUTED_RETRY_POLICY
        self._listener = f"routed-matching-{id(self)}"
        monitor.resolver("matching").add_listener(
            self._listener,
            lambda ev: self._stubs.evict(ev.hosts_removed),
        )

    def _engine_for(self, task_list: str):
        owner = self.monitor.resolver("matching").lookup(task_list).identity
        if self.local_engine is not None and owner == self.local_identity:
            return self.local_engine
        return self._stubs.get(owner)

    def _invoke(self, task_list: str, method: str, *args, **kwargs):
        # each attempt re-resolves the ring (retryableClient.go parity)
        return retry(
            _traced_attempts(
                lambda: getattr(self._engine_for(task_list), method)(
                    *args, **kwargs
                ),
                method,
            ),
            policy=self.retry_policy,
            is_retriable=is_routed_retryable,
        )

    def close(self) -> None:
        self.monitor.resolver("matching").remove_listener(self._listener)
        self._stubs.close()
