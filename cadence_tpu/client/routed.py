"""Cross-process routed clients: ring lookup → host address → gRPC stub.

Reference: client/history/client.go:844-846 (GetClientForKey: shard key
→ membership ring → host → RPC client) and client/matching/client.go
(task-list-name routing). The in-process clients (client/history.py,
client/matching.py) short-circuit to local engines; these variants add
the process boundary: a shard (or task list) owned by another host is
reached through that host's History/Matching gRPC endpoint
(rpc/server.py), with stubs cached per address.

Host identities in the membership ring ARE dial addresses (the
reference's ringpop identities are host:port the same way), so routing
needs no separate address registry.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from cadence_tpu.runtime.membership import Monitor
from cadence_tpu.utils.hashing import shard_for_workflow

from .history import HistoryClient
from .matching import MatchingClient


class _StubCache:
    def __init__(self, factory) -> None:
        self._factory = factory
        self._stubs: Dict[str, object] = {}
        self._lock = threading.Lock()

    def get(self, address: str):
        with self._lock:
            stub = self._stubs.get(address)
            if stub is None:
                stub = self._stubs[address] = self._factory(address)
            return stub

    def close(self) -> None:
        with self._lock:
            for stub in self._stubs.values():
                stub.close()
            self._stubs.clear()


class RoutedHistoryClient(HistoryClient):
    """HistoryClient surface; shard → ring("history") → local engine or
    remote History endpoint."""

    def __init__(
        self,
        monitor: Monitor,
        local_controller=None,
        num_shards: Optional[int] = None,
    ) -> None:
        from cadence_tpu.rpc.client import RemoteHistory

        super().__init__(
            {} if local_controller is None
            else {local_controller.identity: local_controller}
        )
        self.monitor = monitor
        self.local = local_controller
        self.num_shards = (
            num_shards if num_shards is not None
            else (local_controller.num_shards if local_controller else 1)
        )
        self._stubs = _StubCache(RemoteHistory)

    def _call(self, workflow_id: str, method: str, *args, **kwargs):
        shard_id = shard_for_workflow(workflow_id, self.num_shards)
        owner = self.monitor.resolver("history").lookup(
            str(shard_id)
        ).identity
        if self.local is not None and owner == self.local.identity:
            return getattr(
                self.local.get_engine_for_shard(shard_id), method
            )(*args, **kwargs)
        return getattr(self._stubs.get(owner), method)(*args, **kwargs)

    def close(self) -> None:
        self._stubs.close()


class RoutedMatchingClient(MatchingClient):
    """MatchingClient surface; task list → ring("matching") → local
    engine or remote Matching endpoint."""

    def __init__(self, monitor: Monitor, local_engine=None,
                 local_identity: str = "") -> None:
        from cadence_tpu.rpc.client import RemoteMatching

        super().__init__(
            {local_identity or "local": local_engine}
            if local_engine is not None else {}
        )
        self.monitor = monitor
        self.local_engine = local_engine
        self.local_identity = local_identity or monitor.self_identity
        self._stubs = _StubCache(RemoteMatching)

    def _engine_for(self, task_list: str):
        owner = self.monitor.resolver("matching").lookup(task_list).identity
        if self.local_engine is not None and owner == self.local_identity:
            return self.local_engine
        return self._stubs.get(owner)

    def close(self) -> None:
        self._stubs.close()
