"""Matching client: thin routed wrapper over MatchingEngine hosts.

Reference: /root/reference/client/matching/client.go — routes by task
list name through the membership ring; the in-process transport keeps a
host registry and a load-balancer hook mirroring
client/matching/loadbalancer.go.
"""

from __future__ import annotations

from typing import Dict, Optional

from cadence_tpu.runtime.membership import Monitor


class MatchingClient:
    def __init__(self, engines, monitor: Optional[Monitor] = None) -> None:
        """``engines``: MatchingEngine, or {host identity → engine}."""
        if not isinstance(engines, dict):
            engines = {"matching": engines}
        self._engines: Dict[str, object] = dict(engines)
        # public: routing AND best-effort ring-owner decoration by
        # callers (RoutedMatchingClient overwrites with its own)
        self.monitor = monitor

    def _engine_for(self, task_list: str):
        if len(self._engines) == 1 or self.monitor is None:
            return next(iter(self._engines.values()))
        host = self.monitor.resolver("matching").lookup(task_list).identity
        return self._engines.get(host) or next(iter(self._engines.values()))

    def _invoke(self, task_list: str, method: str, *args, **kwargs):
        """Single routing hook every public method funnels through —
        RoutedMatchingClient overrides it with a ring-re-resolving
        retry loop (reference client/matching/retryableClient.go)."""
        return getattr(self._engine_for(task_list), method)(*args, **kwargs)

    def add_decision_task(self, domain_id, workflow_id, run_id, task_list,
                          schedule_id, schedule_to_start_timeout_seconds=0):
        return self._invoke(
            task_list, "add_decision_task", domain_id, workflow_id, run_id,
            task_list, schedule_id, schedule_to_start_timeout_seconds,
        )

    def add_activity_task(self, domain_id, workflow_id, run_id, task_list,
                          schedule_id, schedule_to_start_timeout_seconds=0):
        return self._invoke(
            task_list, "add_activity_task", domain_id, workflow_id, run_id,
            task_list, schedule_id, schedule_to_start_timeout_seconds,
        )

    def poll_for_decision_task(self, request):
        return self._invoke(
            request.task_list, "poll_for_decision_task", request
        )

    def poll_for_activity_task(self, request):
        return self._invoke(
            request.task_list, "poll_for_activity_task", request
        )

    def describe_task_list(self, domain_id, name, task_type):
        return self._invoke(
            name, "describe_task_list", domain_id, name, task_type
        )

    def list_task_list_partitions(self, domain_id, name):
        return self._invoke(
            name, "list_task_list_partitions", domain_id, name
        )

    def cancel_outstanding_polls(self, domain_id, name, task_type):
        return self._invoke(
            name, "cancel_outstanding_polls", domain_id, name, task_type
        )

    def query_workflow(self, domain_id, task_list, workflow_id, run_id,
                       query_type, query_args=b"", timeout_s=10.0):
        return self._invoke(
            task_list, "query_workflow", domain_id, task_list, workflow_id,
            run_id, query_type, query_args, timeout_s,
        )

    def respond_query_task_completed(self, task_list, query_id,
                                     result=b"", error=""):
        return self._invoke(
            task_list, "respond_query_task_completed", query_id, result,
            error
        )
