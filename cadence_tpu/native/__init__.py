"""ctypes bindings for the C++ packing/transport sidecar.

Builds native/sidecar.cpp on first use (g++ -O3 -shared, cached in the
source tree next to the .cpp) and exposes:

- scatter_time_major / scatter_batch_major — fused pad+layout of ragged
  event rows into the dense tensors the replay scan consumes
- fnv1a32_batch — bulk id hashing for slot keys
- tensor_compress / tensor_decompress — varint+zigzag delta codec for
  shipping packed tensors across hosts

Every entry point has a pure-Python/numpy fallback (`HAVE_NATIVE` tells
which path is live), and the test suite runs both differentially.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    "native", "sidecar.cpp",
)
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libctsidecar.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
HAVE_NATIVE = False


def _build() -> Optional[str]:
    if os.path.exists(_LIB_PATH) and (
        os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC)
    ):
        return _LIB_PATH
    # compile to a temp path and rename atomically: a killed compile or
    # two processes racing must never leave a half-written .so that
    # every later process accepts (fresh mtime) and fails to dlopen
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


_load_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, HAVE_NATIVE, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            # don't re-run a 120s compile attempt on EVERY call while
            # holding the module lock; the fallback path serves
            return None
        path = _build()
        if path is None:
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _load_failed = True
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.ct_scatter_time_major.argtypes = [
            i32p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, i32p,
        ]
        lib.ct_scatter_batch_major.argtypes = (
            lib.ct_scatter_time_major.argtypes
        )
        lib.ct_scatter_teb.argtypes = lib.ct_scatter_time_major.argtypes
        lib.ct_presence.argtypes = [
            i32p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, i32p,
        ]
        lib.ct_fnv1a32_batch.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, u32p,
        ]
        lib.ct_compress_bound.argtypes = [ctypes.c_int64]
        lib.ct_compress_bound.restype = ctypes.c_int64
        lib.ct_tensor_compress.argtypes = [i32p, ctypes.c_int64, u8p]
        lib.ct_tensor_compress.restype = ctypes.c_int64
        lib.ct_tensor_decompress.argtypes = [u8p, ctypes.c_int64, i32p]
        lib.ct_tensor_decompress.restype = ctypes.c_int64
        lib.ct_tensor_peek_count.argtypes = [u8p, ctypes.c_int64]
        lib.ct_tensor_peek_count.restype = ctypes.c_int64
        lib.ct_replay_sequential.argtypes = (
            [i32p, i64p] + [ctypes.c_int64] * 8 + [i32p] * 8
        )
        _lib = lib
        HAVE_NATIVE = True
        return lib


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


# -- scatter ---------------------------------------------------------------


def _check_scatter_args(
    rows: np.ndarray, lengths: np.ndarray, max_events: int
) -> None:
    """Bounds-check the public scatter API before handing buffers to C.

    The native scatter trusts its inputs (it clamps per-workflow copies
    to ``max_events`` but cannot detect a lengths/rows mismatch), so
    reject anything inconsistent here, matching the numpy fallback's
    broadcast errors.
    """
    if lengths.size and int(lengths.min()) < 0:
        raise ValueError("scatter: negative workflow length")
    if lengths.size and int(lengths.max()) > max_events:
        raise ValueError(
            f"scatter: workflow length {int(lengths.max())} exceeds "
            f"max_events={max_events}"
        )
    n_rows = rows.shape[0] if rows.ndim == 2 else 0
    if int(lengths.sum()) != n_rows:
        raise ValueError(
            f"scatter: sum(lengths)={int(lengths.sum())} != rows={n_rows}"
        )


def scatter_time_major(
    rows: np.ndarray, lengths: np.ndarray, max_events: int,
    type_pad: int = -1, force_python: bool = False,
) -> np.ndarray:
    """[sum(lengths), E] rows + [B] lengths → [T, B, E] dense tensor."""
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    lengths64 = np.ascontiguousarray(lengths, dtype=np.int64)
    _check_scatter_args(rows, lengths64, max_events)
    batch = len(lengths64)
    ev_n = rows.shape[1] if rows.ndim == 2 else 0
    lib = None if force_python else _load()
    if lib is not None and ev_n and rows.size:
        out = np.empty((max_events, batch, ev_n), dtype=np.int32)
        lib.ct_scatter_time_major(
            _i32p(rows), _i64p(lengths64), batch, ev_n, max_events,
            type_pad, _i32p(out),
        )
        return out
    # numpy fallback
    out = np.zeros((max_events, batch, ev_n), dtype=np.int32)
    if ev_n:
        out[:, :, 0] = type_pad
    start = 0
    for b, n in enumerate(lengths64):
        out[:n, b, :] = rows[start : start + n]
        start += n
    return out


def scatter_teb(
    rows: np.ndarray, lengths: np.ndarray, max_events: int,
    type_pad: int = -1, force_python: bool = False,
) -> np.ndarray:
    """[sum(lengths), E] rows + [B] lengths → [T, E, B] field-major tensor
    (the Pallas replay kernel's native operand layout)."""
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    lengths64 = np.ascontiguousarray(lengths, dtype=np.int64)
    _check_scatter_args(rows, lengths64, max_events)
    batch = len(lengths64)
    ev_n = rows.shape[1] if rows.ndim == 2 else 0
    lib = None if force_python else _load()
    if lib is not None and ev_n and rows.size:
        out = np.empty((max_events, ev_n, batch), dtype=np.int32)
        lib.ct_scatter_teb(
            _i32p(rows), _i64p(lengths64), batch, ev_n, max_events,
            type_pad, _i32p(out),
        )
        return out
    # numpy fallback
    out = np.zeros((max_events, ev_n, batch), dtype=np.int32)
    if ev_n:
        out[:, 0, :] = type_pad
    start = 0
    for b, n in enumerate(lengths64):
        out[:n, :, b] = rows[start : start + n]
        start += n
    return out


def presence_masks(
    rows: np.ndarray, lengths: np.ndarray, max_events: int, bt: int,
    force_python: bool = False,
) -> np.ndarray:
    """Per-(batch-tile, step) presence bitmasks for the Pallas replay
    kernel: [B/bt, T, 4] int32 (words 0-1 event-type bits, word 2 slot
    bits, word 3 zero). B must be a multiple of ``bt``."""
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    lengths64 = np.ascontiguousarray(lengths, dtype=np.int64)
    _check_scatter_args(rows, lengths64, max_events)
    batch = len(lengths64)
    if batch % bt:
        raise ValueError(f"presence: batch={batch} not a multiple of bt={bt}")
    ev_n = rows.shape[1] if rows.ndim == 2 else 0
    if rows.size and ev_n != 16:  # schema.EV_N; ct_presence reads cols 0 and 7
        raise ValueError(f"presence: ev_n={ev_n} != schema EV_N=16")
    lib = None if force_python else _load()
    if lib is not None and ev_n and rows.size:
        out = np.empty((batch // bt, max_events, 4), dtype=np.int32)
        lib.ct_presence(
            _i32p(rows), _i64p(lengths64), batch, ev_n, max_events, bt,
            _i32p(out),
        )
        return out
    # numpy fallback
    out = np.zeros((batch // bt, max_events, 4), dtype=np.int32)
    start = 0
    for b, n in enumerate(lengths64):
        n = min(int(n), max_events)
        g = b // bt
        ets = rows[start : start + n, 0]
        slots = rows[start : start + n, 7]
        ts = np.arange(n)
        ok = ets >= 0
        for w in (0, 1):
            sel = ok & (ets // 32 == w)
            np.bitwise_or.at(out[g, :, w], ts[sel],
                             np.int32(1) << (ets[sel] % 32))
        sel = ok & (slots >= 0)
        np.bitwise_or.at(out[g, :, 2], ts[sel],
                         np.int32(1) << (slots[sel] % 32))
        start += int(lengths64[b])
    return out


def scatter_batch_major(
    rows: np.ndarray, lengths: np.ndarray, max_events: int,
    type_pad: int = -1, force_python: bool = False,
) -> np.ndarray:
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    lengths64 = np.ascontiguousarray(lengths, dtype=np.int64)
    _check_scatter_args(rows, lengths64, max_events)
    batch = len(lengths64)
    ev_n = rows.shape[1] if rows.ndim == 2 else 0
    lib = None if force_python else _load()
    if lib is not None and ev_n and rows.size:
        out = np.empty((batch, max_events, ev_n), dtype=np.int32)
        lib.ct_scatter_batch_major(
            _i32p(rows), _i64p(lengths64), batch, ev_n, max_events,
            type_pad, _i32p(out),
        )
        return out
    out = np.zeros((batch, max_events, ev_n), dtype=np.int32)
    if ev_n:
        out[:, :, 0] = type_pad
    start = 0
    for b, n in enumerate(lengths64):
        out[b, :n, :] = rows[start : start + n]
        start += n
    return out


# -- hashing ---------------------------------------------------------------


def fnv1a32_batch(strings, force_python: bool = False) -> np.ndarray:
    """hash31 for a batch of strings (cadence_tpu.utils.hashing)."""
    lib = None if force_python else _load()
    if lib is None:
        from cadence_tpu.utils.hashing import hash31

        return np.array([hash31(s) for s in strings], dtype=np.uint32)
    encoded = [s.encode() for s in strings]
    data = b"".join(encoded)
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    out = np.empty(len(encoded), dtype=np.uint32)
    lib.ct_fnv1a32_batch(
        data, _i64p(offsets), len(encoded),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out


# -- transport codec -------------------------------------------------------


def tensor_compress(
    tensor: np.ndarray, force_python: bool = False
) -> Tuple[bytes, Tuple[int, ...]]:
    """int32 tensor → (blob, shape). Delta+zigzag+varint."""
    flat = np.ascontiguousarray(tensor, dtype=np.int32).reshape(-1)
    lib = None if force_python else _load()
    if lib is None:
        return _py_compress(flat), tensor.shape
    bound = lib.ct_compress_bound(flat.size)
    buf = np.empty(bound, dtype=np.uint8)
    n = lib.ct_tensor_compress(
        _i32p(flat), flat.size,
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return bytes(buf[:n]), tensor.shape


def tensor_decompress(
    blob: bytes, shape: Tuple[int, ...], force_python: bool = False
) -> np.ndarray:
    expected = int(np.prod(shape)) if shape else 1
    lib = None if force_python else _load()
    if lib is None:
        return _py_decompress(blob, expected).reshape(shape)
    raw = np.frombuffer(blob, dtype=np.uint8)
    u8 = raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    count = lib.ct_tensor_peek_count(u8, len(blob))
    if count < 0 or count != expected:
        raise ValueError(
            f"tensor_decompress: corrupt blob (count={count}, "
            f"expected {expected})"
        )
    out = np.empty(count, dtype=np.int32)
    decoded = lib.ct_tensor_decompress(u8, len(blob), _i32p(out))
    if decoded != count:
        raise ValueError(
            f"tensor_decompress: truncated blob (decoded {decoded} of "
            f"{count})"
        )
    return out.reshape(shape)


def _py_compress(flat: np.ndarray) -> bytes:
    out = bytearray()

    def put(v: int) -> None:
        while v >= 0x80:
            out.append((v & 0x7F) | 0x80)
            v >>= 7
        out.append(v)

    put(flat.size)
    prev = 0
    for v in flat.tolist():
        # wrap the delta to int32 first: Python ints are unbounded, so
        # a raw (d >> 31) sign probe is wrong for |d| >= 2^31 (e.g. a
        # -1 pad followed by a 2^31-1 hash31 slot key) and would break
        # encode/decode symmetry with the native codec
        d = ((v - prev + 0x80000000) & 0xFFFFFFFF) - 0x80000000
        prev = v
        put(((d << 1) ^ (d >> 31)) & 0xFFFFFFFF)
    return bytes(out)


def _py_decompress(blob: bytes, expected: Optional[int] = None) -> np.ndarray:
    pos = 0

    def get() -> int:
        nonlocal pos
        shift = 0
        v = 0
        while True:
            if pos >= len(blob) or shift > 28:
                raise ValueError("tensor_decompress: corrupt blob")
            b = blob[pos]
            pos += 1
            v |= (b & 0x7F) << shift
            if not (b & 0x80):
                return v & 0xFFFFFFFF
            shift += 7

    n = get()
    if expected is not None and n != expected:
        # validate the header BEFORE allocating: a forged count would
        # otherwise trigger a giant np.empty from a few corrupt bytes
        raise ValueError(
            f"tensor_decompress: corrupt blob (count={n}, "
            f"expected {expected})"
        )
    out = np.empty(n, dtype=np.int32)
    prev = 0
    for i in range(n):
        z = get()
        d = (z >> 1) ^ -(z & 1)
        prev = (prev + d) & 0xFFFFFFFF
        if prev >= 0x80000000:
            prev -= 0x100000000
        out[i] = prev
    return out


# -- sequential replayer (compiled-host baseline) --------------------------


def replay_sequential(packed, caps=None):
    """Replay packed histories with the C++ sequential loop.

    The compiled-host baseline for bench.py: identical transition
    semantics to the TPU kernel (ops/replay.py) applied one workflow,
    one event at a time — the shape of the reference's Go
    stateBuilder.applyEvents loop (service/history/stateBuilder.go:112-613).
    Returns StateTensors (numpy). Requires the native sidecar; raises
    RuntimeError when g++ is unavailable (the baseline must be compiled
    code, never interpreted Python).
    """
    from cadence_tpu.ops import schema as S

    lib = _load()
    if lib is None:
        raise RuntimeError("native sidecar unavailable: no compiled baseline")
    caps = caps or packed.caps
    events = np.ascontiguousarray(packed.events, dtype=np.int32)  # [B,T,E]
    batch, T, ev_n = events.shape
    if ev_n != S.EV_N:
        raise ValueError(f"event width {ev_n} != schema EV_N {S.EV_N}")
    lengths = np.ascontiguousarray(packed.lengths, dtype=np.int64)
    state = S.empty_state(batch, caps)
    lib.ct_replay_sequential(
        _i32p(events), _i64p(lengths), batch, T,
        caps.max_activities, caps.max_timers, caps.max_children,
        caps.max_request_cancels, caps.max_signals_ext,
        caps.max_version_items,
        _i32p(state.exec_info), _i32p(state.activities),
        _i32p(state.timers), _i32p(state.children),
        _i32p(state.cancels), _i32p(state.signals),
        _i32p(state.vh_items), _i32p(state.vh_len),
    )
    return state
