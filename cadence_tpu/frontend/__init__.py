"""Frontend service: the stateless public API gateway.

Reference: service/frontend/ — WorkflowHandler (workflowHandler.go:
247-2850, the full public API), AdminHandler, DC-redirection policy,
version checker, per-domain rate limiting, and the domain handler
(common/domain/handler.go) it fronts.
"""

from .domain_handler import (
    ArchivalStatus,
    DomainAlreadyExistsError,
    DomainHandler,
)
from .handler import WorkflowHandler
from .admin_handler import AdminHandler
from .dc_redirection import DCRedirectionHandler, SelectedAPIsForwardingPolicy
from .version_checker import ClientVersionChecker, ClientVersionNotSupportedError

__all__ = [
    "ArchivalStatus",
    "DomainAlreadyExistsError",
    "DomainHandler",
    "WorkflowHandler",
    "AdminHandler",
    "DCRedirectionHandler",
    "SelectedAPIsForwardingPolicy",
    "ClientVersionChecker",
    "ClientVersionNotSupportedError",
]
