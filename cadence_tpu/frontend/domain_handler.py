"""Domain CRUD, failover, archival state machine, bad binaries.

Reference: common/domain/handler.go:85 (RegisterDomain/UpdateDomain/
DescribeDomain/ListDomains/DeprecateDomain), attrValidator.go (name /
cluster / retention validation), archivalConfigStateMachine.go (the
never-enabled → enabled → disabled transitions with an immutable URI).
Domain metadata changes on a global domain are published to the domain-
replication topic so other clusters converge
(service/worker/replicator/domainReplicationTaskHandler.go).
"""

from __future__ import annotations

import dataclasses
import re
import uuid
from typing import Any, Dict, List, Optional

from cadence_tpu.cluster import ClusterMetadata
from cadence_tpu.runtime.api import BadRequestError, EntityNotExistsServiceError
from cadence_tpu.runtime.persistence.errors import EntityNotExistsError
from cadence_tpu.runtime.persistence.interfaces import MetadataManager
from cadence_tpu.runtime.persistence.records import (
    DomainConfig,
    DomainInfo,
    DomainRecord,
    DomainReplicationConfig,
)

DOMAIN_REPLICATION_TOPIC = "domain-replication"

_NAME_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9_.-]*$")
_MIN_RETENTION_DAYS = 1
_MAX_BAD_BINARIES = 16


class DomainAlreadyExistsError(Exception):
    pass


class ArchivalStatus:
    NEVER_ENABLED = 0
    DISABLED = 1
    ENABLED = 2


def _next_archival_state(
    status: int, uri: str, req_status: Optional[int], req_uri: str,
    kind: str = "history",
) -> tuple:
    """(status', uri') — reference archivalConfigStateMachine.getNextState:
    the URI is write-once; enabling requires a URI; disable keeps it."""
    if req_uri and uri and req_uri != uri:
        raise BadRequestError("archival URI is immutable once set")
    if req_uri and not uri:
        # validate at SET time against the archiver registry for the
        # RIGHT kind — the URI is write-once, so accepting a history
        # scheme as a visibility URI (or a typo) permanently breaks
        # the domain's archival
        from cadence_tpu.archival import ArchiverProvider, URI

        try:
            parsed = URI.parse(req_uri)
            provider = ArchiverProvider.default()
            if kind == "visibility":
                provider.get_visibility_archiver(parsed.scheme)
            else:
                provider.get_history_archiver(parsed.scheme)
        except Exception as e:
            raise BadRequestError(f"invalid archival URI {req_uri!r}: {e}")
    new_uri = uri or req_uri
    if req_status is None:
        return status, new_uri
    if req_status == ArchivalStatus.ENABLED and not new_uri:
        raise BadRequestError("cannot enable archival without a URI")
    if req_status == ArchivalStatus.NEVER_ENABLED:
        raise BadRequestError("cannot transition back to never-enabled")
    return req_status, new_uri


class DomainHandler:
    def __init__(
        self,
        metadata: MetadataManager,
        cluster_metadata: Optional[ClusterMetadata] = None,
        replication_producer=None,  # messaging.Producer on the domain topic
    ) -> None:
        self.metadata = metadata
        self.cluster = cluster_metadata or ClusterMetadata()
        self._producer = replication_producer

    # -- validation (attrValidator.go) ---------------------------------

    def _validate_name(self, name: str) -> None:
        if not name or len(name) > 256 or not _NAME_RE.match(name):
            raise BadRequestError(f"invalid domain name {name!r}")

    def _validate_retention(self, days: int) -> None:
        if days < _MIN_RETENTION_DAYS:
            raise BadRequestError(
                f"retention {days}d below minimum {_MIN_RETENTION_DAYS}d"
            )

    def _validate_clusters(
        self, clusters: List[str], active: str, is_global: bool
    ) -> None:
        known = self.cluster.all_cluster_info()
        for c in clusters:
            if c not in known:
                raise BadRequestError(f"unknown cluster {c!r}")
        if active not in clusters:
            raise BadRequestError(
                f"active cluster {active!r} not in replication clusters"
            )
        if is_global and not self.cluster.is_global_domain_enabled:
            raise BadRequestError("global domains are disabled")
        if is_global and len(clusters) < 2:
            raise BadRequestError("a global domain needs >= 2 clusters")
        if not is_global and len(clusters) > 1:
            raise BadRequestError("a local domain cannot span clusters")

    # -- CRUD ----------------------------------------------------------

    def register_domain(
        self,
        name: str,
        description: str = "",
        owner_email: str = "",
        retention_days: int = 7,
        emit_metric: bool = True,
        clusters: Optional[List[str]] = None,
        active_cluster: str = "",
        is_global: bool = False,
        data: Optional[Dict[str, str]] = None,
        history_archival_status: Optional[int] = None,
        history_archival_uri: str = "",
        visibility_archival_status: Optional[int] = None,
        visibility_archival_uri: str = "",
        domain_id: str = "",
        failover_version: Optional[int] = None,
    ) -> str:
        """Reference handler.go RegisterDomain. Returns the domain id."""
        if is_global and not self.cluster.is_master_cluster and failover_version is None:
            raise BadRequestError(
                "global domains register on the master cluster only"
            )
        self._validate_name(name)
        self._validate_retention(retention_days)
        active = active_cluster or self.cluster.current_cluster_name
        cluster_list = list(clusters or [active])
        self._validate_clusters(cluster_list, active, is_global)
        try:
            self.metadata.get_domain(name=name)
            raise DomainAlreadyExistsError(f"domain {name} exists")
        except EntityNotExistsError:
            pass

        h_status, h_uri = _next_archival_state(
            ArchivalStatus.NEVER_ENABLED, "", history_archival_status,
            history_archival_uri,
        )
        v_status, v_uri = _next_archival_state(
            ArchivalStatus.NEVER_ENABLED, "", visibility_archival_status,
            visibility_archival_uri, kind="visibility",
        )
        if failover_version is None:
            failover_version = (
                self.cluster.next_failover_version(active, 0)
                if is_global
                else 0
            )
        rec = DomainRecord(
            info=DomainInfo(
                id=domain_id or str(uuid.uuid4()), name=name,
                description=description, owner_email=owner_email,
                data=dict(data or {}),
            ),
            config=DomainConfig(
                retention_days=retention_days,
                emit_metric=emit_metric,
                history_archival_status=h_status,
                history_archival_uri=h_uri,
                visibility_archival_status=v_status,
                visibility_archival_uri=v_uri,
            ),
            replication_config=DomainReplicationConfig(
                active_cluster_name=active, clusters=cluster_list
            ),
            is_global=is_global,
            failover_version=failover_version,
        )
        out = self.metadata.create_domain(rec)
        self._replicate(rec, operation="create")
        return out

    def describe_domain(
        self, name: str = "", id: str = ""
    ) -> DomainRecord:
        try:
            return self.metadata.get_domain(id=id, name=name)
        except EntityNotExistsError:
            raise EntityNotExistsServiceError(f"domain {name or id} not found")

    def list_domains(self) -> List[DomainRecord]:
        return self.metadata.list_domains()

    def deprecate_domain(self, name: str) -> None:
        rec = self.describe_domain(name=name)
        rec.info.status = 1
        rec.config_version += 1
        self.metadata.update_domain(rec)
        self._replicate(rec, operation="update")

    # -- update / failover ---------------------------------------------

    def update_domain(
        self,
        name: str,
        description: Optional[str] = None,
        owner_email: Optional[str] = None,
        retention_days: Optional[int] = None,
        emit_metric: Optional[bool] = None,
        data: Optional[Dict[str, str]] = None,
        active_cluster: Optional[str] = None,
        clusters: Optional[List[str]] = None,
        history_archival_status: Optional[int] = None,
        history_archival_uri: str = "",
        visibility_archival_status: Optional[int] = None,
        visibility_archival_uri: str = "",
        add_bad_binary: Optional[Dict[str, str]] = None,
        remove_bad_binary: str = "",
    ) -> DomainRecord:
        """Reference handler.go UpdateDomain — config updates are master-
        only for global domains; a pure failover (active_cluster change)
        is allowed from any cluster."""
        rec = self.describe_domain(name=name)
        config_changed = any(
            v is not None
            for v in (
                description, owner_email, retention_days, emit_metric,
                data, clusters, history_archival_status,
                visibility_archival_status,
            )
        ) or bool(
            history_archival_uri or visibility_archival_uri
            or add_bad_binary or remove_bad_binary
        )
        failover = (
            active_cluster is not None
            and active_cluster != rec.replication_config.active_cluster_name
        )
        if (
            rec.is_global
            and config_changed
            and not self.cluster.is_master_cluster
        ):
            raise BadRequestError(
                "global domain config updates are master-cluster only"
            )
        if config_changed and failover:
            raise BadRequestError(
                "cannot combine a config update with a failover"
            )

        if description is not None:
            rec.info.description = description
        if owner_email is not None:
            rec.info.owner_email = owner_email
        if data is not None:
            rec.info.data.update(data)
        if retention_days is not None:
            self._validate_retention(retention_days)
            rec.config.retention_days = retention_days
        if emit_metric is not None:
            rec.config.emit_metric = emit_metric
        if clusters is not None:
            self._validate_clusters(
                clusters, rec.replication_config.active_cluster_name,
                rec.is_global,
            )
            rec.replication_config.clusters = list(clusters)

        rec.config.history_archival_status, rec.config.history_archival_uri = (
            _next_archival_state(
                rec.config.history_archival_status,
                rec.config.history_archival_uri,
                history_archival_status, history_archival_uri,
            )
        )
        (
            rec.config.visibility_archival_status,
            rec.config.visibility_archival_uri,
        ) = _next_archival_state(
            rec.config.visibility_archival_status,
            rec.config.visibility_archival_uri,
            visibility_archival_status, visibility_archival_uri,
            kind="visibility",
        )

        if add_bad_binary:
            if len(rec.config.bad_binaries) >= _MAX_BAD_BINARIES:
                raise BadRequestError(
                    f"bad binaries limit {_MAX_BAD_BINARIES} reached"
                )
            checksum = add_bad_binary.get("checksum", "")
            if not checksum:
                raise BadRequestError("bad binary needs a checksum")
            rec.config.bad_binaries[checksum] = {
                "reason": add_bad_binary.get("reason", ""),
                "operator": add_bad_binary.get("operator", ""),
            }
        if remove_bad_binary:
            rec.config.bad_binaries.pop(remove_bad_binary, None)

        if failover:
            if active_cluster not in rec.replication_config.clusters:
                raise BadRequestError(
                    f"failover target {active_cluster!r} not in domain clusters"
                )
            if not rec.is_global:
                raise BadRequestError("local domains cannot fail over")
            rec.replication_config.active_cluster_name = active_cluster
            rec.failover_version = self.cluster.next_failover_version(
                active_cluster, rec.failover_version + 1
            )
            rec.failover_notification_version = rec.notification_version
        if config_changed:
            rec.config_version += 1

        self.metadata.update_domain(rec)
        self._replicate(rec, operation="update")
        return self.describe_domain(name=name)

    def failover_domain(self, name: str, target_cluster: str) -> DomainRecord:
        return self.update_domain(name, active_cluster=target_cluster)

    # -- cross-cluster propagation -------------------------------------

    def _replicate(self, rec: DomainRecord, operation: str) -> None:
        if self._producer is None or not rec.is_global:
            return
        self._producer.publish(
            rec.info.name,
            {"operation": operation, "record": _record_to_dict(rec)},
        )

    def apply_replication_record(self, payload: Dict[str, Any]) -> None:
        """Apply a domain-replication message from the master cluster
        (reference: domainReplicationTaskHandler.go) — upsert by id."""
        rec = _record_from_dict(payload["record"])
        try:
            existing = self.metadata.get_domain(id=rec.info.id)
        except EntityNotExistsError:
            self.metadata.create_domain(rec)
            return
        # PER-FIELD merge (reference domainReplicationTaskExecutor):
        # failover state and config state version independently — a
        # pure failover published by a cluster that hasn't seen the
        # latest config update must still land (an OR-reject would
        # silently drop it and the clusters would diverge on the
        # active cluster forever)
        if (
            rec.failover_version <= existing.failover_version
            and rec.config_version <= existing.config_version
        ):
            return
        merged = rec
        if rec.config_version < existing.config_version:
            # keep the newer local config, take the newer failover
            merged = dataclasses.replace(
                existing,
                replication_config=rec.replication_config,
                failover_version=rec.failover_version,
                failover_notification_version=(
                    rec.failover_notification_version
                ),
            )
        elif rec.failover_version < existing.failover_version:
            # keep the newer local failover, take the newer config
            merged = dataclasses.replace(
                rec,
                replication_config=existing.replication_config,
                failover_version=existing.failover_version,
                failover_notification_version=(
                    existing.failover_notification_version
                ),
            )
        self.metadata.update_domain(merged)


def _record_to_dict(rec: DomainRecord) -> Dict[str, Any]:
    return {
        "info": dataclasses.asdict(rec.info),
        "config": dataclasses.asdict(rec.config),
        "replication_config": dataclasses.asdict(rec.replication_config),
        "is_global": rec.is_global,
        "config_version": rec.config_version,
        "failover_version": rec.failover_version,
        "failover_notification_version": rec.failover_notification_version,
    }


def _record_from_dict(d: Dict[str, Any]) -> DomainRecord:
    return DomainRecord(
        info=DomainInfo(**d["info"]),
        config=DomainConfig(**d["config"]),
        replication_config=DomainReplicationConfig(**d["replication_config"]),
        is_global=d.get("is_global", False),
        config_version=d.get("config_version", 0),
        failover_version=d.get("failover_version", 0),
        failover_notification_version=d.get(
            "failover_notification_version", 0
        ),
    )
