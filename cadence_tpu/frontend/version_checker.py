"""Client SDK version gate.

Reference: service/frontend/versionChecker.go — requests carry
feature-version headers; clients older than the supported floor are
rejected with ClientVersionNotSupportedError.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple


class ClientVersionNotSupportedError(Exception):
    def __init__(self, client: str, version: str, supported: str) -> None:
        super().__init__(
            f"client {client} version {version} < supported {supported}"
        )
        self.client = client
        self.version = version
        self.supported = supported


def _parse(version: str) -> Tuple[int, ...]:
    try:
        return tuple(int(p) for p in version.split("."))
    except ValueError:
        return ()


class ClientVersionChecker:
    DEFAULT_SUPPORTED = {
        "cadence-tpu-py": "0.1.0",
        "uber-go": "1.5.0",
        "uber-java": "1.5.0",
        "cli": "1.0.0",
    }

    def __init__(
        self, supported: Optional[Dict[str, str]] = None,
        enabled: bool = True,
    ) -> None:
        self.supported = dict(supported or self.DEFAULT_SUPPORTED)
        self.enabled = enabled

    def check(self, client_impl: str = "", feature_version: str = "") -> None:
        """No headers → no check (reference: missing headers pass)."""
        if not self.enabled or not client_impl or not feature_version:
            return
        floor = self.supported.get(client_impl)
        if floor is None:
            return  # unknown client impls pass
        got = _parse(feature_version)
        want = _parse(floor)
        if got and want and got < want:
            raise ClientVersionNotSupportedError(
                client_impl, feature_version, floor
            )
