"""WorkflowHandler: the full public API surface.

Reference: service/frontend/workflowHandler.go:247-2850 — every RPC
validates (domain status, ID lengths, payload sizes), rate-limits per
domain, resolves the domain, then delegates to the history/matching
clients or the visibility store. Worker task-list APIs poll matching;
visibility queries go to the visibility manager (advanced queries via
the query translator in cadence_tpu.visibility).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple

from cadence_tpu.core.enums import EventType
from cadence_tpu.matching import PollRequest
from cadence_tpu.runtime.api import (
    BadRequestError,
    Decision,
    EntityNotExistsServiceError,
    ServiceBusyError,
    SignalRequest,
    SignalWithStartRequest,
    StartWorkflowRequest,
)
from cadence_tpu.runtime.domains import DomainCache
from cadence_tpu.runtime.persistence.errors import (
    EntityNotExistsError as PersistenceEntityNotExistsError,
)
from cadence_tpu.utils.quotas import MultiStageRateLimiter

from .domain_handler import DomainHandler
from .version_checker import ClientVersionChecker

_MAX_ID_LENGTH = 1000  # reference workflowHandler maxIDLengthLimit
_DEFAULT_BLOB_LIMIT = 2 * 1024 * 1024  # blobSizeLimitError default


class WorkflowHandler:
    def __init__(
        self,
        domain_handler: DomainHandler,
        domain_cache: DomainCache,
        history_client,
        matching_client,
        visibility=None,
        rate_limiter: Optional[MultiStageRateLimiter] = None,
        version_checker: Optional[ClientVersionChecker] = None,
        blob_size_limit: int = _DEFAULT_BLOB_LIMIT,
        metrics=None,
    ) -> None:
        self.domain_handler = domain_handler
        self.domains = domain_cache
        self.history = history_client
        self.matching = matching_client
        self.visibility = visibility
        self.limiter = rate_limiter or MultiStageRateLimiter(
            global_rps=100000.0, domain_rps=lambda domain: 100000.0
        )
        self.versions = version_checker or ClientVersionChecker()
        # per-API requests/latency/errors (ref common/metrics/defs.go
        # frontend scopes, applied as in the scoped metrics clients)
        from cadence_tpu.utils.metrics import NOOP
        from cadence_tpu.utils.metrics_defs import (
            FRONTEND_OPS,
            instrument_methods,
        )

        from cadence_tpu.utils.log import get_logger

        self._log = get_logger("cadence_tpu.frontend")
        self.metrics = (metrics or NOOP).tagged(service="frontend")
        instrument_methods(self, self.metrics, FRONTEND_OPS)

    # -- request plumbing ----------------------------------------------

    def _check(
        self, domain_name: str,
        client_impl: str = "", feature_version: str = "",
    ) -> str:
        """Common preamble: version gate, rate limit, domain resolution.
        Returns the domain id."""
        self.versions.check(client_impl, feature_version)
        if not domain_name:
            raise BadRequestError("domain is not set")
        if len(domain_name) > _MAX_ID_LENGTH:
            raise BadRequestError("domain name too long")
        if not self.limiter.allow(domain_name):
            # shed with a retry-after hint (the bucket's refill
            # horizon) so well-behaved clients pace their re-offer
            # instead of hammering a saturated frontend; counted under
            # tags (service=frontend, domain=...) — the overload
            # dashboard's per-tenant shed rate
            self.metrics.tagged(domain=domain_name).inc(
                "frontend_requests_shed"
            )
            hint = getattr(self.limiter, "retry_after_s", None)
            raise ServiceBusyError(
                f"domain {domain_name} rate limit",
                retry_after_s=hint(domain_name) if hint else 0.0,
            )
        try:
            rec = self.domains.get_by_name(domain_name)
        except PersistenceEntityNotExistsError:
            raise EntityNotExistsServiceError(
                f"domain {domain_name} not found"
            )
        if rec.info.status != 0:
            raise EntityNotExistsServiceError(
                f"domain {domain_name} is deprecated"
            )
        return rec.info.id

    def _check_id(self, value: str, what: str) -> None:
        if not value:
            raise BadRequestError(f"{what} is not set")
        if len(value) > _MAX_ID_LENGTH:
            raise BadRequestError(f"{what} exceeds {_MAX_ID_LENGTH} chars")

    def _check_blob(self, payload: Optional[bytes], what: str) -> None:
        if payload and len(payload) > _DEFAULT_BLOB_LIMIT:
            raise BadRequestError(f"{what} exceeds the blob size limit")

    # -- domain API ----------------------------------------------------

    def register_domain(self, name: str, **kwargs) -> str:
        return self.domain_handler.register_domain(name, **kwargs)

    def describe_domain(self, name: str = "", id: str = ""):
        return self.domain_handler.describe_domain(name=name, id=id)

    def list_domains(self):
        return self.domain_handler.list_domains()

    def update_domain(self, name: str, **kwargs):
        return self.domain_handler.update_domain(name, **kwargs)

    def deprecate_domain(self, name: str) -> None:
        self.domain_handler.deprecate_domain(name)

    # -- workflow lifecycle --------------------------------------------

    def start_workflow_execution(
        self, request: StartWorkflowRequest, **headers
    ) -> str:
        self._check(request.domain, **headers)
        self._check_id(request.workflow_id, "workflowId")
        self._check_id(request.workflow_type, "workflowType")
        self._check_id(request.task_list, "taskList")
        self._check_blob(request.input, "input")
        self._check_cron(request.cron_schedule)
        return self.history.start_workflow_execution(request)

    @staticmethod
    def _check_cron(cron_schedule: str) -> None:
        if not cron_schedule:
            return
        from cadence_tpu.utils.cron import validate_cron_schedule

        try:
            validate_cron_schedule(cron_schedule)
        except ValueError as e:
            raise BadRequestError(str(e))

    def signal_workflow_execution(
        self, request: SignalRequest, **headers
    ) -> None:
        self._check(request.domain, **headers)
        self._check_id(request.workflow_id, "workflowId")
        self._check_id(request.signal_name, "signalName")
        self._check_blob(request.input, "signal input")
        self.history.signal_workflow_execution(request)

    def signal_with_start_workflow_execution(
        self, request: SignalWithStartRequest, **headers
    ) -> str:
        self._check(request.start.domain, **headers)
        self._check_id(request.start.workflow_id, "workflowId")
        # the embedded START must pass the same frontend limits as
        # start_workflow_execution — without these, oversized inputs /
        # overlong identifiers bypass the limits entirely on this path
        self._check_id(request.start.workflow_type, "workflowType")
        self._check_id(request.start.task_list, "taskList")
        self._check_blob(request.start.input, "workflow input")
        self._check_id(request.signal_name, "signalName")
        self._check_blob(request.signal_input, "signal input")
        self._check_cron(request.start.cron_schedule)
        return self.history.signal_with_start_workflow_execution(request)

    def terminate_workflow_execution(
        self, domain: str, workflow_id: str, run_id: str = "",
        reason: str = "", details: bytes = b"", identity: str = "",
        **headers,
    ) -> None:
        self._check(domain, **headers)
        self._check_id(workflow_id, "workflowId")
        self.history.terminate_workflow_execution(
            domain, workflow_id, run_id,
            reason=reason, details=details, identity=identity,
        )

    def request_cancel_workflow_execution(
        self, domain: str, workflow_id: str, run_id: str = "",
        identity: str = "", request_id: str = "", **headers,
    ) -> None:
        self._check(domain, **headers)
        self._check_id(workflow_id, "workflowId")
        self.history.request_cancel_workflow_execution(
            domain, workflow_id, run_id,
            identity=identity, request_id=request_id or str(uuid.uuid4()),
        )

    def reset_workflow_execution(
        self, domain: str, workflow_id: str, run_id: str = "",
        reason: str = "", decision_finish_event_id: int = 0,
        request_id: str = "", reset_type: str = "",
        bad_binary_checksum: str = "", **headers,
    ) -> str:
        """Reset at a decision boundary. Either an explicit
        ``decision_finish_event_id`` or a ``reset_type`` the handler
        resolves (reference tools/cli resetTypes):

          FirstDecisionCompleted | LastDecisionCompleted |
          BadBinary (with bad_binary_checksum: the event BEFORE that
          binary's first completed decision, i.e. undo its work)
        """
        self._check(domain, **headers)
        self._check_id(workflow_id, "workflowId")
        if not decision_finish_event_id:
            if not reset_type:
                raise BadRequestError(
                    "either decisionFinishEventId or resetType is "
                    "required"
                )
            if not run_id:
                # pin the concrete run NOW: resolving the reset point
                # against one run and resetting "the current run" later
                # races continue-as-new
                run_id = self.history.describe_workflow_execution(
                    domain, workflow_id
                ).run_id
            decision_finish_event_id = self._resolve_reset_point(
                domain, workflow_id, run_id, reset_type,
                bad_binary_checksum,
            )
        return self.history.reset_workflow_execution(
            domain, workflow_id, run_id,
            reason=reason,
            decision_finish_event_id=decision_finish_event_id,
            request_id=request_id,
        )

    def _resolve_reset_point(
        self, domain: str, workflow_id: str, run_id: str,
        reset_type: str, bad_binary_checksum: str,
    ) -> int:
        if not reset_type:
            raise BadRequestError(
                "either decisionFinishEventId or resetType is required"
            )
        events, _ = self.history.get_workflow_execution_history(
            domain, workflow_id, run_id
        )
        completed = [
            e for e in events
            if e.event_type == EventType.DecisionTaskCompleted
        ]
        if reset_type == "FirstDecisionCompleted":
            if not completed:
                raise BadRequestError("run has no completed decision")
            return completed[0].event_id
        if reset_type == "LastDecisionCompleted":
            if not completed:
                raise BadRequestError("run has no completed decision")
            return completed[-1].event_id
        if reset_type == "BadBinary":
            if not bad_binary_checksum:
                raise BadRequestError(
                    "BadBinary reset needs badBinaryChecksum"
                )
            # fork AT the bad binary's first completed decision: the
            # cut keeps everything before it and re-drives that
            # decision on a good binary (reference resetter uses the
            # reset point's FirstDecisionCompletedId)
            for e in completed:
                if e.attributes.get(
                    "binary_checksum", ""
                ) == bad_binary_checksum:
                    return e.event_id
            raise BadRequestError(
                f"binary {bad_binary_checksum!r} completed no decision "
                "in this run"
            )
        raise BadRequestError(f"unknown resetType {reset_type!r}")

    def query_workflow(
        self, domain: str, workflow_id: str, run_id: str = "",
        query_type: str = "", query_args: bytes = b"",
        timeout_s: float = 10.0, reject_not_open: bool = False,
        **headers,
    ) -> bytes:
        """reject_not_open: the reference's QueryRejectCondition — fail
        the query instead of answering from a closed run's state."""
        self._check(domain, **headers)
        self._check_id(workflow_id, "workflowId")
        self._check_id(query_type, "queryType")
        return self.history.query_workflow(
            domain, workflow_id, run_id,
            query_type=query_type, query_args=query_args,
            reject_not_open=reject_not_open,
            timeout_s=timeout_s,
        )

    def describe_workflow_execution(
        self, domain: str, workflow_id: str, run_id: str = "", **headers
    ):
        self._check(domain, **headers)
        self._check_id(workflow_id, "workflowId")
        return self.history.describe_workflow_execution(
            domain, workflow_id, run_id
        )

    def get_workflow_execution_history(
        self, domain: str, workflow_id: str, run_id: str = "",
        first_event_id: int = 1, page_size: int = 0, next_token: int = 0,
        wait_for_new_event: bool = False, **headers,
    ):
        self._check(domain, **headers)
        self._check_id(workflow_id, "workflowId")
        if next_token < 0:
            # a token this handler issued from the archive (negative
            # tag distinguishes it from live event-id tokens): resume
            # the archive read directly. Transient archiver failures
            # propagate (retryable), only a truly-missing blob is 404
            archived = self._archived_history(
                domain, workflow_id, run_id,
                first_event_id=first_event_id, page_size=page_size,
                next_token=-next_token, strict=True,
            )
            if archived is None:
                raise EntityNotExistsServiceError(
                    f"archived history for {workflow_id}/{run_id} "
                    "is gone"
                )
            return archived
        try:
            return self.history.get_workflow_execution_history(
                domain, workflow_id, run_id,
                first_event_id=first_event_id, page_size=page_size,
                next_token=next_token,
                wait_for_new_event=wait_for_new_event,
            )
        except EntityNotExistsServiceError:
            # retention already deleted the run: serve the archive
            # (reference workflowHandler.getArchivedHistory fallback).
            # Only a fresh read falls back — a live-issued token is an
            # event id, meaningless as an archive batch index
            if next_token:
                raise
            archived = self._archived_history(
                domain, workflow_id, run_id,
                first_event_id=first_event_id, page_size=page_size,
            )
            if archived is None:
                raise
            return archived

    def _archival_target(self, domain: str, kind: str):
        """(parsed URI, domain_id) when ``kind`` ('history'/'visibility')
        archival is enabled for the domain, else None."""
        from cadence_tpu.archival import URI
        from cadence_tpu.frontend.domain_handler import ArchivalStatus

        rec = self.domains.get_by_name(domain)
        cfg = rec.config
        status = getattr(cfg, f"{kind}_archival_status")
        uri = getattr(cfg, f"{kind}_archival_uri")
        if status != ArchivalStatus.ENABLED or not uri:
            return None
        try:
            return URI.parse(uri), rec.info.id
        except Exception:
            # a malformed archival URI reads as "not archived", never
            # as an internal error on an unrelated request
            self._log.exception(
                f"domain {domain} has a malformed {kind} archival "
                f"URI {uri!r}"
            )
            return None

    def _archived_history(self, domain: str, workflow_id: str,
                          run_id: str, first_event_id: int = 1,
                          page_size: int = 0, next_token: int = 0,
                          strict: bool = False):

        if not run_id:
            return None  # the archive is keyed by concrete run
        target = self._archival_target(domain, "history")
        if target is None:
            return None
        uri, domain_id = target
        try:
            archiver = self._archival_provider().get_history_archiver(
                uri.scheme
            )
            batches, token = archiver.get(
                uri, domain_id, workflow_id, run_id,
                page_size=page_size, next_token=next_token,
            )
        except FileNotFoundError:
            return None
        except Exception:
            self._log.exception(
                f"archived-history read failed for {domain}/{workflow_id}"
            )
            if strict:
                # a resume KNOWS the blob exists — surface the
                # retryable failure instead of faking a permanent 404
                raise
            # fresh-read fallback: the caller re-raises the original
            # live-store NOT_FOUND
            return None
        events = [e for b in batches for e in b]
        if first_event_id > 1:
            events = [e for e in events if e.event_id >= first_event_id]
        # tag archive continuation tokens negative so the next request
        # routes back here instead of the live store
        return events, (-token if token else 0)

    def _archival_provider(self):
        if getattr(self, "_arch_provider", None) is None:
            from cadence_tpu.archival import ArchiverProvider

            self._arch_provider = ArchiverProvider.default()
        return self._arch_provider

    # -- worker APIs ---------------------------------------------------

    def poll_for_decision_task(
        self, domain: str, task_list: str, identity: str = "",
        timeout_s: float = 1.0, **headers,
    ):
        domain_id = self._check(domain, **headers)
        self._check_id(task_list, "taskList")
        return self.matching.poll_for_decision_task(
            PollRequest(domain_id, task_list, identity, timeout_s)
        )

    def poll_for_activity_task(
        self, domain: str, task_list: str, identity: str = "",
        timeout_s: float = 1.0, **headers,
    ):
        domain_id = self._check(domain, **headers)
        self._check_id(task_list, "taskList")
        return self.matching.poll_for_activity_task(
            PollRequest(domain_id, task_list, identity, timeout_s)
        )

    def respond_decision_task_completed(
        self, task_token: Dict[str, Any], decisions: List[Decision],
        **kwargs,
    ) -> None:
        self.history.respond_decision_task_completed(
            task_token, decisions, **kwargs
        )

    def respond_decision_task_failed(
        self, task_token: Dict[str, Any], **kwargs
    ) -> None:
        self.history.respond_decision_task_failed(task_token, **kwargs)

    def respond_activity_task_completed(self, task_token, **kwargs) -> None:
        self._check_blob(kwargs.get("result"), "activity result")
        self.history.respond_activity_task_completed(task_token, **kwargs)

    def respond_activity_task_failed(self, task_token, **kwargs) -> None:
        self.history.respond_activity_task_failed(task_token, **kwargs)

    def respond_activity_task_canceled(self, task_token, **kwargs) -> None:
        self.history.respond_activity_task_canceled(task_token, **kwargs)

    def record_activity_task_heartbeat(self, task_token, **kwargs):
        return self.history.record_activity_task_heartbeat(
            task_token, **kwargs
        )

    # ByID variants (workflowHandler RespondActivityTaskCompletedByID
    # etc.): resolve the task token from the pending-activity table
    def _activity_token_by_id(
        self, domain: str, workflow_id: str, run_id: str, activity_id: str
    ) -> Dict[str, Any]:
        domain_id = self._check(domain)
        desc = self.history.describe_workflow_execution(
            domain, workflow_id, run_id
        )
        for pa in desc.pending_activities:
            if pa["activity_id"] == activity_id:
                return {
                    "domain_id": domain_id,
                    "workflow_id": workflow_id,
                    "run_id": run_id or desc.run_id,
                    "schedule_id": pa["schedule_id"],
                    "started_id": 0,
                    "activity_id": activity_id,
                }
        raise EntityNotExistsServiceError(
            f"activity {activity_id} not pending"
        )

    def respond_activity_task_completed_by_id(
        self, domain: str, workflow_id: str, run_id: str,
        activity_id: str, **kwargs,
    ) -> None:
        token = self._activity_token_by_id(
            domain, workflow_id, run_id, activity_id
        )
        self.history.respond_activity_task_completed(token, **kwargs)

    def respond_activity_task_failed_by_id(
        self, domain: str, workflow_id: str, run_id: str,
        activity_id: str, **kwargs,
    ) -> None:
        token = self._activity_token_by_id(
            domain, workflow_id, run_id, activity_id
        )
        self.history.respond_activity_task_failed(token, **kwargs)

    def respond_activity_task_canceled_by_id(
        self, domain: str, workflow_id: str, run_id: str,
        activity_id: str, **kwargs,
    ) -> None:
        token = self._activity_token_by_id(
            domain, workflow_id, run_id, activity_id
        )
        self.history.respond_activity_task_canceled(token, **kwargs)

    def record_activity_task_heartbeat_by_id(
        self, domain: str, workflow_id: str, run_id: str,
        activity_id: str, **kwargs,
    ):
        token = self._activity_token_by_id(
            domain, workflow_id, run_id, activity_id
        )
        return self.history.record_activity_task_heartbeat(token, **kwargs)

    def respond_query_task_completed(
        self, task_list: str, query_id: str, result: bytes = b"",
        error: str = "",
    ) -> None:
        self.matching.respond_query_task_completed(
            task_list, query_id, result, error
        )

    def reset_sticky_task_list(
        self, domain: str, workflow_id: str, run_id: str = "", **headers
    ) -> None:
        self._check(domain, **headers)
        self.history.reset_sticky_task_list(domain, workflow_id, run_id)

    def describe_task_list(
        self, domain: str, task_list: str, task_type: int = 0, **headers
    ):
        domain_id = self._check(domain, **headers)
        return self.matching.describe_task_list(
            domain_id, task_list, task_type
        )

    def list_task_list_partitions(
        self, domain: str, task_list: str, **headers
    ) -> dict:
        """Partition layout + owning hosts (reference
        workflowHandler.ListTaskListPartitions)."""
        domain_id = self._check(domain, **headers)
        self._check_id(task_list, "taskList")
        out = self.matching.list_task_list_partitions(
            domain_id, task_list
        )
        # owner decoration is best-effort: an empty ring (startup
        # race) must not fail the listing itself
        monitor = getattr(self.matching, "monitor", None)
        if monitor is not None:
            resolver = monitor.resolver("matching")
            try:
                for plist in out.values():
                    for p in plist:
                        p["owner_host"] = resolver.lookup(
                            p["name"]
                        ).identity
            except RuntimeError:
                pass  # no hosts joined yet: return undecorated
        return out

    # -- visibility ----------------------------------------------------

    def _vis(self):
        if self.visibility is None:
            raise BadRequestError("visibility store not configured")
        return self.visibility

    def list_open_workflow_executions(
        self, domain: str, page_size: int = 100, next_token: int = 0,
        workflow_type: str = "", workflow_id: str = "",
        earliest_start: int = 0, latest_start: int = 2**63 - 1, **headers,
    ):
        domain_id = self._check(domain, **headers)
        return self._vis().list_open_workflow_executions(
            domain_id, earliest_start, latest_start,
            workflow_type, workflow_id, page_size, next_token,
        )

    def list_closed_workflow_executions(
        self, domain: str, page_size: int = 100, next_token: int = 0,
        workflow_type: str = "", workflow_id: str = "",
        close_status: int = -1,
        earliest_start: int = 0, latest_start: int = 2**63 - 1, **headers,
    ):
        domain_id = self._check(domain, **headers)
        return self._vis().list_closed_workflow_executions(
            domain_id, earliest_start, latest_start,
            workflow_type, workflow_id, close_status, page_size, next_token,
        )

    def list_workflow_executions(
        self, domain: str, query: str = "", page_size: int = 100,
        next_token: int = 0, **headers,
    ):
        """Advanced visibility: SQL-like query string
        (reference ListWorkflowExecutions + esql translation)."""
        domain_id = self._check(domain, **headers)
        vis = self._vis()
        if hasattr(vis, "list_workflow_executions"):
            return vis.list_workflow_executions(
                domain_id, query, page_size, next_token
            )
        raise BadRequestError("advanced visibility not configured")

    def scan_workflow_executions(
        self, domain: str, query: str = "", page_size: int = 100,
        next_token: int = 0, **headers,
    ):
        return self.list_workflow_executions(
            domain, query, page_size, next_token, **headers
        )

    def health(self) -> dict:
        """Liveness probe (reference workflowHandler.Health)."""
        return {"ok": True, "service": "frontend"}

    def get_cluster_info(self) -> dict:
        """Server capabilities + supported client versions (reference
        workflowHandler.GetClusterInfo)."""
        return {
            "supported_client_versions": dict(self.versions.supported),
            "server": "cadence-tpu",
        }

    def list_archived_workflow_executions(
        self, domain: str, query: str = "", page_size: int = 100,
        next_token: int = 0, **headers,
    ):
        """Query the domain's visibility archive (reference
        workflowHandler.ListArchivedWorkflowExecutions — serves records
        whose retention already deleted them from live visibility)."""
        self._check(domain, **headers)
        target = self._archival_target(domain, "visibility")
        if target is None:
            raise BadRequestError(
                f"domain {domain} has no visibility archival enabled"
            )
        uri, domain_id = target
        archiver = self._archival_provider().get_visibility_archiver(
            uri.scheme
        )
        return archiver.query(
            uri, domain_id, query,
            page_size=page_size, next_token=next_token,
        )

    def count_workflow_executions(
        self, domain: str, query: str = "", **headers
    ) -> int:
        domain_id = self._check(domain, **headers)
        vis = self._vis()
        if query:
            if not hasattr(vis, "count_workflow_executions_by_query"):
                # answering the TOTAL count for a filtered query would
                # be a silently wrong answer
                raise BadRequestError(
                    "advanced visibility is not configured; "
                    "count with a query is unavailable"
                )
            return vis.count_workflow_executions_by_query(domain_id, query)
        return vis.count_workflow_executions(domain_id)

    def get_search_attributes(self) -> Dict[str, str]:
        """Valid search attribute keys (reference GetSearchAttributes)."""
        from cadence_tpu.visibility.search_attributes import (
            DEFAULT_SEARCH_ATTRIBUTES,
        )

        return dict(DEFAULT_SEARCH_ATTRIBUTES)
