"""DC redirection: forward API calls for passive global domains.

Reference: service/frontend/dcRedirectionHandler.go +
dcRedirectionPolicy.go — under the "selected-apis-forwarding" policy,
non-worker APIs for a domain whose active cluster is elsewhere are
forwarded to that cluster's frontend; the "noop" policy serves locally
and lets the history engine raise DomainNotActiveError.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from cadence_tpu.runtime.api import DomainNotActiveError

# the API set the reference forwards (dcRedirectionPolicy.go
# selectedAPIsForwardingRedirectionPolicyAPIAllowlist)
FORWARDED_APIS = frozenset(
    {
        "start_workflow_execution",
        "signal_workflow_execution",
        "signal_with_start_workflow_execution",
        "request_cancel_workflow_execution",
        "terminate_workflow_execution",
        "reset_workflow_execution",
        "query_workflow",
    }
)


class NoopRedirectionPolicy:
    def pick_cluster(self, domain_record, api: str, current: str) -> str:
        return current


class SelectedAPIsForwardingPolicy:
    def pick_cluster(self, domain_record, api: str, current: str) -> str:
        if (
            domain_record is None
            or not domain_record.is_global
            or api not in FORWARDED_APIS
        ):
            return current
        return domain_record.replication_config.active_cluster_name


class DCRedirectionHandler:
    """Wraps a WorkflowHandler; remote frontends are plugged per cluster
    (in-process peers in tests, gRPC stubs across real clusters)."""

    def __init__(
        self,
        local_handler,
        current_cluster: str,
        policy=None,
        remote_frontends: Optional[Dict[str, object]] = None,
    ) -> None:
        self.local = local_handler
        self.current = current_cluster
        self.policy = policy or SelectedAPIsForwardingPolicy()
        self.remotes: Dict[str, object] = dict(remote_frontends or {})

    def add_remote(self, cluster: str, frontend) -> None:
        self.remotes[cluster] = frontend

    def _domain_record(self, domain_name: str):
        try:
            return self.local.domain_handler.describe_domain(name=domain_name)
        except Exception:
            return None

    def call(self, api: str, domain_name: str, *args, **kwargs):
        rec = self._domain_record(domain_name)
        target = self.policy.pick_cluster(rec, api, self.current)
        if target == self.current:
            return getattr(self.local, api)(*args, **kwargs)
        remote = self.remotes.get(target)
        if remote is None:
            raise DomainNotActiveError(
                f"domain {domain_name} is active in {target!r} and no "
                "forwarding route is configured",
                active_cluster=target,
            )
        return getattr(remote, api)(*args, **kwargs)

    def __getattr__(self, api: str):
        # transparently proxy everything else to the local handler
        return getattr(self.local, api)
