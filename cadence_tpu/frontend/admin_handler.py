"""Admin API.

Reference: service/frontend/adminHandler.go — operator-facing RPCs:
DescribeHistoryHost (shard distribution), CloseShard, RemoveTask,
raw history reads for replication debugging, and an admin
DescribeWorkflowExecution exposing the shard id + raw mutable state.
"""

from __future__ import annotations

from typing import Any, Dict, List

from cadence_tpu.runtime.api import BadRequestError, EntityNotExistsServiceError
from cadence_tpu.runtime.persistence.errors import EntityNotExistsError


class AdminHandler:
    def __init__(self, history_service, domain_cache, bus=None) -> None:
        self.history = history_service
        self.domains = domain_cache
        # message bus for DLQ operator verbs (None on hosts that don't
        # run the messaging plane)
        self.bus = bus

    # -- elastic resharding (runtime/resharding.py) --------------------

    @property
    def resharder(self):
        """The host's shared reshard coordinator — built and owned by
        ``HistoryService.reshard_coordinator()`` so the admin verbs and
        the capacity autopilot serialize plans on the SAME coordinator
        lock (one plan at a time is a host property, not a caller
        property). Multi-host in-process clusters build their own
        coordinator spanning every controller."""
        return self.history.reshard_coordinator()

    def reshard_split(self, shard_id: int) -> Dict[str, Any]:
        """Online shard split 1→2 (admin verb; returns the committed
        plan record)."""
        self._check_resharding_enabled()
        return self.resharder.split(int(shard_id)).to_dict()

    def reshard_merge(self, source_id: int, target_id: int) -> Dict[str, Any]:
        """Online shard merge 2→1."""
        self._check_resharding_enabled()
        return self.resharder.merge(int(source_id), int(target_id)).to_dict()

    def reshard_status(self) -> Dict[str, Any]:
        """Current routing epoch + the last plan's write-ahead record."""
        return self.resharder.status()

    def _check_resharding_enabled(self) -> None:
        cfg = getattr(self.history, "resharding_config", None)
        if cfg is not None and not cfg.enabled:
            raise BadRequestError("resharding is disabled by config")

    # -- capacity autopilot (runtime/autopilot.py) ---------------------

    def _require_autopilot(self):
        ap = getattr(self.history, "autopilot", None)
        if ap is None:
            raise BadRequestError(
                "capacity autopilot is not enabled on this host"
            )
        return ap

    def autopilot_status(self) -> Dict[str, Any]:
        """The controller's full decision state: setpoints, EWMAs, gate
        + freeze + pause flags, cooldowns, last sensed reading."""
        return self._require_autopilot().status()

    def autopilot_pause(self, reason: str = "") -> Dict[str, Any]:
        """Operator override: stop actuating (sensing continues) until
        ``autopilot_resume``. The last word stays with the human."""
        ap = self._require_autopilot()
        ap.pause(reason or "admin verb")
        return ap.status()

    def autopilot_resume(self) -> Dict[str, Any]:
        ap = self._require_autopilot()
        ap.resume()
        return ap.status()

    def describe_queue_states(self, shard_id: int) -> Dict[str, Any]:
        """Per-queue cursor/depth introspection for one owned shard
        (reference tools/cli/adminQueueCommands.go DescribeQueue) —
        collection lives on HistoryService, next to describe()."""
        try:
            return self.history.describe_queue_states(shard_id)
        except KeyError:
            raise EntityNotExistsServiceError(
                f"shard {shard_id} is not owned by this host"
            )

    # -- DLQ verbs (reference tools/cli/adminDLQCommands.go over
    # adminHandler Get/Purge/MergeDLQMessages) -------------------------

    def _require_bus(self):
        if self.bus is None:
            raise BadRequestError("no message bus on this host")
        return self.bus

    def read_dlq_messages(
        self, topic: str, last_message_id: int = -1, count: int = 100,
    ) -> List[Dict[str, Any]]:
        msgs = self._require_bus().dlq_read(topic, last_message_id, count)
        return [
            {
                "offset": m.offset,
                "key": m.key,
                "value": m.value,
                "redelivery_count": m.redelivery_count,
            }
            for m in msgs
        ]

    def purge_dlq_messages(
        self, topic: str, last_message_id: int = -1,
    ) -> int:
        return self._require_bus().dlq_purge(topic, last_message_id)

    def merge_dlq_messages(
        self, topic: str, last_message_id: int = -1,
    ) -> int:
        return self._require_bus().dlq_merge(topic, last_message_id)

    def dump_traces(self, trace_id: str = "") -> Dict[str, Any]:
        """The tracing flight recorder (utils/tracing.py) as
        Chrome-trace-format JSON — the RPC twin of
        ``GET /debug/pprof/traces``. ``trace_id`` filters to one
        request's trace; empty dumps the whole ring buffer."""
        from cadence_tpu.utils.tracing import TRACER

        return TRACER.chrome_trace(trace_id or None)

    def describe_history_host(self) -> Dict[str, Any]:
        desc = self.history.describe()
        desc["host"] = self.history.monitor.self_identity
        return desc

    def close_shard(self, shard_id: int) -> None:
        """Force-release one shard (reference adminHandler.CloseShard)."""
        self.history.controller.release_shard(shard_id)

    def remove_task(self, shard_id: int, task_type: str, task_id: int,
                    visibility_timestamp: int = 0) -> None:
        """Surgically drop a poisoned queue task."""
        execution = self.history.persistence.execution
        if task_type == "transfer":
            execution.complete_transfer_task(shard_id, task_id)
        elif task_type == "timer":
            execution.complete_timer_task(
                shard_id, visibility_timestamp, task_id
            )
        elif task_type == "replication":
            execution.complete_replication_task(shard_id, task_id)
        else:
            raise BadRequestError(f"unknown task type {task_type!r}")

    def get_workflow_execution_raw_history(
        self, domain_name: str, workflow_id: str, run_id: str,
        start_event_id: int = 1, end_event_id: int = 1 << 60,
    ):
        """Raw batches + version-history items (replication debugging)."""
        domain_id = self.domains.get_by_name(domain_name).info.id
        return self.history.get_workflow_history_raw(
            domain_id, workflow_id, run_id, start_event_id, end_event_id
        )

    def refresh_workflow_tasks(
        self, domain_name: str, workflow_id: str, run_id: str = ""
    ) -> Dict[str, Any]:
        """Regenerate a run's queue tasks from state (reference
        adminHandler.RefreshWorkflowTasks) — pairs with remove_task for
        recovering from a poisoned or lost task."""
        domain_id = self.domains.get_by_name(domain_name).info.id
        engine = self.history.controller.get_engine(workflow_id)
        n = engine.refresh_workflow_tasks(domain_id, workflow_id, run_id)
        return {"tasks_generated": n}

    def admin_describe_workflow_execution(
        self, domain_name: str, workflow_id: str, run_id: str = ""
    ) -> Dict[str, Any]:
        """RPC-reachable name for the admin variant: the frontend
        endpoint dispatches by name across [frontend, admin] targets
        with first-match, so the shared name
        ``describe_workflow_execution`` always resolves to the PUBLIC
        WorkflowHandler — this alias keeps the admin introspection
        surface reachable over the wire."""
        return self.describe_workflow_execution(
            domain_name, workflow_id, run_id
        )

    def describe_workflow_execution(
        self, domain_name: str, workflow_id: str, run_id: str = ""
    ) -> Dict[str, Any]:
        """Admin variant: shard id + raw mutable-state snapshot."""
        domain_id = self.domains.get_by_name(domain_name).info.id
        # epoch-versioned routing: the controller's ShardMap, not a
        # static modulo (a resharded workflow lives on its NEW shard)
        shard_id = self.history.controller.shard_for(workflow_id)
        engine = self.history.controller.get_engine_for_shard(shard_id)
        if not run_id:
            run_id = engine._current_run_id(domain_id, workflow_id)
        try:
            resp = engine.shard.persistence.execution.get_workflow_execution(
                shard_id, domain_id, workflow_id, run_id
            )
        except EntityNotExistsError:
            raise EntityNotExistsServiceError(
                f"workflow {workflow_id}/{run_id} not found"
            )
        return {
            "shard_id": shard_id,
            "history_host": self.history.monitor.self_identity,
            "mutable_state": resp.snapshot,
            "next_event_id": resp.next_event_id,
        }
