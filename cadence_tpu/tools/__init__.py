"""Operator tooling (reference: tools/cli, tools/cassandra, tools/sql)."""
