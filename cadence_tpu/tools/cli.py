"""The operator CLI.

Reference: tools/cli/ (app.go, domainCommands.go, workflowCommands.go,
adminCommands.go) — domain CRUD/failover, workflow
start/show/signal/terminate/cancel/reset/query/list, task-list
describe, admin shard/host introspection, batch operations, plus
``server`` (cmd/server/cadence.go start) which boots a onebox over
sqlite with the gRPC endpoint.

Usage:
    python -m cadence_tpu.tools.cli server --db /tmp/c.db --port 7933
    python -m cadence_tpu.tools.cli --address 127.0.0.1:7933 \\
        domain register --name dev
    python -m cadence_tpu.tools.cli --address 127.0.0.1:7933 \\
        workflow start --domain dev --workflow-id w1 --type t --tasklist tl
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import signal
import sys
import time
from typing import Any


def _print(obj: Any) -> None:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        obj = dataclasses.asdict(obj)
    if isinstance(obj, (dict, list)):
        print(json.dumps(obj, indent=2, default=_default))
    else:
        print(obj)


def _default(o: Any) -> Any:
    if isinstance(o, bytes):
        try:
            return o.decode()
        except UnicodeDecodeError:
            return o.hex()
    if dataclasses.is_dataclass(o) and not isinstance(o, type):
        return dataclasses.asdict(o)
    return str(o)


def _frontend(args):
    from cadence_tpu.rpc import RemoteFrontend

    if not args.address:
        sys.exit("--address is required (or run `server` first)")
    return RemoteFrontend(args.address)


# -- server ---------------------------------------------------------------


def cmd_server(args) -> None:
    if args.config:
        conflicting = [
            flag for flag, default in (
                ("--db", args.db == ""), ("--port", args.port == 7933),
                ("--shards", args.shards == 4),
                ("--no-worker", not args.no_worker),
                ("--pprof-port", args.pprof_port == 0),
            ) if not default
        ]
        if conflicting:
            sys.exit(
                f"--config conflicts with {', '.join(conflicting)}: "
                "those settings come from the config file"
            )
        _config_server(args)
        return
    from cadence_tpu.rpc import FrontendRPCServer
    from cadence_tpu.runtime.persistence.sqlite import create_sqlite_bundle
    from cadence_tpu.testing.onebox import Onebox

    pprof = None
    if args.pprof_port:
        # bind BEFORE the heavyweight components: a bad port fails fast
        # with nothing to tear down
        from cadence_tpu.utils.pprof import PProfServer

        pprof = PProfServer(port=args.pprof_port).start()
    persistence = (
        create_sqlite_bundle(args.db) if args.db else None
    )
    box = Onebox(
        num_shards=args.shards,
        persistence=persistence,
        start_worker=not args.no_worker,
    ).start()
    server = FrontendRPCServer(
        box.frontend, box.admin, address=f"127.0.0.1:{args.port}"
    ).start()
    print(f"cadence-tpu server listening on {server.address} "
          f"(shards={args.shards}, db={args.db or 'memory'}"
          + (f", pprof={pprof.address}" if pprof else "") + ")")
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        if pprof is not None:
            pprof.stop()
        server.stop()
        box.stop()


def _config_server(args) -> None:
    """Config-driven start (ref cmd/server/server.go:207-219): only the
    requested services run in this process; peers resolve over the
    ring + gRPC plane."""
    from cadence_tpu.config import load_config, start_services

    cfg = load_config(args.config)
    services = (
        [s.strip() for s in args.services.split(",") if s.strip()]
        if args.services else None
    )
    server = start_services(cfg, services)
    print(
        f"cadence-tpu services {server.services} up; endpoints: "
        f"{server.addresses}"
    )
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        server.stop()


# -- schema ---------------------------------------------------------------


def cmd_schema(args) -> None:
    """Versioned schema tooling (ref tools/cassandra/handler.go
    setup-schema / update-schema)."""
    import sqlite3

    from cadence_tpu.runtime.persistence import schema as S

    conn = sqlite3.connect(args.db)
    try:
        if args.schema_cmd == "version":
            _print({
                "db_version": S.get_schema_version(conn),
                "build_version": S.CURRENT_SCHEMA_VERSION,
            })
        elif args.schema_cmd in ("setup", "update"):
            applied = S.update_schema(conn)
            _print({
                "applied": [
                    {"version": v, "name": n} for v, n in applied
                ],
                "db_version": S.get_schema_version(conn),
            })
        elif args.schema_cmd == "check":
            try:
                S.check_compat(conn)
                _print({"compatible": True})
            except S.SchemaVersionError as e:
                _print({"compatible": False, "error": str(e)})
                sys.exit(1)
    finally:
        conn.close()


# -- domain ---------------------------------------------------------------


def cmd_domain(args) -> None:
    fe = _frontend(args)
    if args.domain_cmd == "register":
        out = fe.register_domain(
            args.name, description=args.description or "",
            retention_days=args.retention,
            is_global=args.global_domain,
            clusters=args.clusters.split(",") if args.clusters else None,
            active_cluster=args.active_cluster or "",
        )
        _print({"domain_id": out})
    elif args.domain_cmd == "describe":
        _print(fe.describe_domain(name=args.name))
    elif args.domain_cmd == "list":
        _print(fe.list_domains())
    elif args.domain_cmd == "update":
        kwargs = {}
        if args.description is not None:
            kwargs["description"] = args.description
        if args.retention:
            kwargs["retention_days"] = args.retention
        if args.add_bad_binary:
            kwargs["add_bad_binary"] = {
                "checksum": args.add_bad_binary, "reason": args.reason or ""
            }
        _print(fe.update_domain(args.name, **kwargs))
    elif args.domain_cmd == "failover":
        _print(fe.update_domain(args.name, active_cluster=args.to))
    elif args.domain_cmd == "deprecate":
        fe.deprecate_domain(args.name)
        _print({"deprecated": args.name})


# -- workflow -------------------------------------------------------------


def cmd_workflow(args) -> None:
    from cadence_tpu.runtime.api import SignalRequest, StartWorkflowRequest

    fe = _frontend(args)
    wc = args.workflow_cmd
    if wc == "start":
        run_id = fe.start_workflow_execution(
            StartWorkflowRequest(
                domain=args.domain, workflow_id=args.workflow_id,
                workflow_type=args.type, task_list=args.tasklist,
                input=(args.input or "").encode(),
                execution_start_to_close_timeout_seconds=args.timeout,
                cron_schedule=args.cron or "",
            )
        )
        _print({"run_id": run_id})
    elif wc == "show":
        events, _ = fe.get_workflow_execution_history(
            args.domain, args.workflow_id, args.run_id or ""
        )
        _print([
            {
                "id": e.event_id,
                "type": e.event_type.name,
                "version": e.version,
                "attributes": {
                    k: v for k, v in e.attributes.items() if v not in
                    (None, "", b"")
                },
            }
            for e in events
        ])
    elif wc == "describe":
        _print(fe.describe_workflow_execution(
            args.domain, args.workflow_id, args.run_id or ""
        ))
    elif wc == "signal":
        fe.signal_workflow_execution(
            SignalRequest(
                domain=args.domain, workflow_id=args.workflow_id,
                run_id=args.run_id or "", signal_name=args.name,
                input=(args.input or "").encode(),
            )
        )
        _print({"signaled": args.workflow_id})
    elif wc == "terminate":
        fe.terminate_workflow_execution(
            args.domain, args.workflow_id, args.run_id or "",
            reason=args.reason or "terminated via cli",
        )
        _print({"terminated": args.workflow_id})
    elif wc == "cancel":
        fe.request_cancel_workflow_execution(
            args.domain, args.workflow_id, args.run_id or ""
        )
        _print({"cancel_requested": args.workflow_id})
    elif wc == "reset":
        new_run = fe.reset_workflow_execution(
            args.domain, args.workflow_id, args.run_id or "",
            reason=args.reason or "reset via cli",
            decision_finish_event_id=args.event_id,
            reset_type=args.reset_type,
            bad_binary_checksum=args.bad_binary_checksum,
        )
        _print({"new_run_id": new_run})
    elif wc == "query":
        out = fe.query_workflow(
            args.domain, args.workflow_id, args.run_id or "",
            query_type=args.type, timeout_s=args.timeout,
            reject_not_open=args.reject_not_open,
        )
        _print({"result": out.decode(errors="replace")})
    elif wc == "list":
        recs, _ = fe.list_workflow_executions(
            args.domain, args.query or "", page_size=args.page_size
        )
        _print(recs)
    elif wc == "count":
        _print({"count": fe.count_workflow_executions(
            args.domain, args.query or ""
        )})
    elif wc == "signalwithstart":
        from cadence_tpu.runtime.api import SignalWithStartRequest

        run_id = fe.signal_with_start_workflow_execution(
            SignalWithStartRequest(
                start=StartWorkflowRequest(
                    domain=args.domain, workflow_id=args.workflow_id,
                    workflow_type=args.type, task_list=args.tasklist,
                    input=(args.input or "").encode(),
                    execution_start_to_close_timeout_seconds=args.timeout,
                    cron_schedule=args.cron or "",
                ),
                signal_name=args.name,
                signal_input=(args.signal_input or "").encode(),
            )
        )
        _print({"run_id": run_id})
    elif wc == "observe":
        # reference workflowCommands.go ObserveHistory: long-poll the
        # history from the last seen event (the server blocks until new
        # events land — no full re-fetch, no client-side poll loop)
        from cadence_tpu.core.enums import EventType

        terminal = {
            EventType.WorkflowExecutionCompleted,
            EventType.WorkflowExecutionFailed,
            EventType.WorkflowExecutionTimedOut,
            EventType.WorkflowExecutionCanceled,
            EventType.WorkflowExecutionTerminated,
            EventType.WorkflowExecutionContinuedAsNew,
        }
        printed = 0
        deadline = time.monotonic() + args.timeout
        while True:
            events, _ = fe.get_workflow_execution_history(
                args.domain, args.workflow_id, args.run_id or "",
                first_event_id=printed + 1, wait_for_new_event=True,
            )
            for e in events:
                print(f"{e.event_id:5d}  {e.event_type.name}")
                printed = max(printed, e.event_id)
            if events and events[-1].event_type in terminal:
                _print({"closed": True, "events": printed})
                return
            if time.monotonic() > deadline:
                _print({"closed": False, "events": printed})
                return
    elif wc == "export":
        # full-fidelity history dump (admin history-dump depth): every
        # event with all attributes, replayable JSON
        events, _ = fe.get_workflow_execution_history(
            args.domain, args.workflow_id, args.run_id or ""
        )
        payload = json.dumps(
            [
                {
                    "event_id": e.event_id,
                    "event_type": e.event_type.name,
                    "version": e.version,
                    "timestamp": e.timestamp,
                    "attributes": e.attributes,
                }
                for e in events
            ],
            indent=2, default=_default,
        )
        if args.output:
            with open(args.output, "w") as f:
                f.write(payload)
            _print({"exported": len(events), "to": args.output})
        else:
            print(payload)


# -- tasklist / admin / batch --------------------------------------------


def cmd_tasklist(args) -> None:
    fe = _frontend(args)
    _print(fe.describe_task_list(args.domain, args.name, args.task_type))


def _value_size(value) -> int:
    """Payload size in bytes for any payload shape (dead letters carry
    whatever the producer published: bytes, str, dict, ...)."""
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    if isinstance(value, str):
        return len(value.encode())
    try:
        return len(json.dumps(value, default=str).encode())
    except TypeError:
        return len(repr(value).encode())


def cmd_admin(args) -> None:
    fe = _frontend(args)
    if args.admin_cmd == "describe-host":
        _print(fe.describe_history_host())
    elif args.admin_cmd == "close-shard":
        fe.close_shard(args.shard_id)
        _print({"closed": args.shard_id})
    elif args.admin_cmd == "describe-workflow":
        # distinct RPC name: the public describe_workflow_execution
        # shadows the admin variant in by-name dispatch
        _print(fe.admin_describe_workflow_execution(
            args.domain, args.workflow_id, args.run_id or ""
        ))
    elif args.admin_cmd == "refresh-tasks":
        _print(fe.refresh_workflow_tasks(
            args.domain, args.workflow_id, args.run_id or ""
        ))
    elif args.admin_cmd == "queue-state":
        # reference tools/cli/adminQueueCommands.go DescribeQueue
        _print(fe.describe_queue_states(args.shard_id))
    elif args.admin_cmd == "dlq":
        # reference tools/cli/adminDLQCommands.go read|purge|merge with
        # a --last-message-id watermark
        if args.dlq_cmd == "read":
            msgs = fe.read_dlq_messages(
                args.topic, args.last_message_id, args.count
            )
            _print({"topic": args.topic, "messages": [
                {
                    "offset": m["offset"],
                    "key": m["key"],
                    "redelivery_count": m["redelivery_count"],
                    "value_bytes": _value_size(m["value"]),
                }
                for m in msgs
            ]})
        elif args.dlq_cmd == "purge":
            n = fe.purge_dlq_messages(args.topic, args.last_message_id)
            _print({"topic": args.topic, "purged": n})
        elif args.dlq_cmd == "merge":
            n = fe.merge_dlq_messages(args.topic, args.last_message_id)
            _print({"topic": args.topic, "merged": n})


def cmd_batch(args) -> None:
    from cadence_tpu.runtime.api import StartWorkflowRequest
    from cadence_tpu.worker.batcher import (
        BATCHER_TASK_LIST,
        BATCHER_WORKFLOW_TYPE,
    )
    from cadence_tpu.worker.service import SYSTEM_DOMAIN

    fe = _frontend(args)
    payload = json.dumps({
        "operation": args.operation,
        "domain": args.domain,
        "query": args.query or "",
        "params": {
            "reason": args.reason or "batch via cli",
            "signal_name": args.signal_name or "",
            "signal_input": args.input or "",
        },
    }).encode()
    run_id = fe.start_workflow_execution(
        StartWorkflowRequest(
            domain=SYSTEM_DOMAIN,
            workflow_id=f"cli-batch-{int(time.time())}",
            workflow_type=BATCHER_WORKFLOW_TYPE,
            task_list=BATCHER_TASK_LIST, input=payload,
            execution_start_to_close_timeout_seconds=3600,
        )
    )
    _print({"batch_run_id": run_id})


def cmd_canary(args) -> None:
    from cadence_tpu.canary.runner import run_canary

    results = run_canary(
        address=args.address, probes=args.probes.split(",") if args.probes
        else None,
    )
    _print(results)
    if any(not r["ok"] for r in results):
        sys.exit(1)


# -- parser ---------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cadence-tpu")
    p.add_argument("--address", default="",
                   help="frontend gRPC address (host:port)")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("server", help="run server services")
    s.add_argument("--db", default="", help="sqlite path (default memory)")
    s.add_argument("--port", type=int, default=7933)
    s.add_argument("--shards", type=int, default=4)
    s.add_argument("--no-worker", action="store_true")
    s.add_argument("--pprof-port", type=int, default=0,
                   help="serve /debug/pprof diagnostics on this port")
    s.add_argument("--config", default="",
                   help="static YAML config (enables --services)")
    s.add_argument("--services", default="",
                   help="comma list: frontend,history,matching,worker")
    s.set_defaults(fn=cmd_server)

    sc = sub.add_parser("schema", help="versioned sqlite schema tooling")
    scsub = sc.add_subparsers(dest="schema_cmd", required=True)
    for name in ("setup", "update", "version", "check"):
        sp = scsub.add_parser(name)
        sp.add_argument("--db", required=True)
    sc.set_defaults(fn=cmd_schema)

    d = sub.add_parser("domain")
    dsub = d.add_subparsers(dest="domain_cmd", required=True)
    for name in ("register", "describe", "update", "deprecate"):
        dp = dsub.add_parser(name)
        dp.add_argument("--name", required=True)
        dp.add_argument("--description")
        dp.add_argument("--retention", type=int, default=7)
        dp.add_argument("--global-domain", action="store_true")
        dp.add_argument("--clusters", default="")
        dp.add_argument("--active-cluster", default="")
        dp.add_argument("--add-bad-binary", default="")
        dp.add_argument("--reason", default="")
    dl = dsub.add_parser("list")
    df = dsub.add_parser("failover")
    df.add_argument("--name", required=True)
    df.add_argument("--to", required=True)
    d.set_defaults(fn=cmd_domain)

    w = sub.add_parser("workflow")
    wsub = w.add_subparsers(dest="workflow_cmd", required=True)
    for name in ("start", "show", "describe", "signal", "terminate",
                 "cancel", "reset", "query", "list", "count",
                 "signalwithstart", "observe", "export"):
        wp = wsub.add_parser(name)
        wp.add_argument("--domain", required=True)
        if name not in ("list", "count"):
            wp.add_argument("--workflow-id", required=True)
        wp.add_argument("--run-id", default="")
        wp.add_argument("--type", default="")
        wp.add_argument("--tasklist", default="")
        wp.add_argument("--input", default="")
        wp.add_argument("--name", default="")
        wp.add_argument("--reason", default="")
        wp.add_argument("--query", default="")
        wp.add_argument("--cron", default="")
        wp.add_argument("--event-id", type=int, default=0)
        wp.add_argument("--timeout", type=int, default=60)
        wp.add_argument("--page-size", type=int, default=100)
        wp.add_argument("--signal-input", default="")
        wp.add_argument("--output", default="",
                        help="export: write history JSON here")
        wp.add_argument("--reject-not-open", action="store_true",
                        help="query: fail instead of answering from a "
                             "closed run")
        wp.add_argument("--reset-type", default="",
                        help="reset: FirstDecisionCompleted | "
                             "LastDecisionCompleted | BadBinary")
        wp.add_argument("--bad-binary-checksum", default="")
    w.set_defaults(fn=cmd_workflow)

    t = sub.add_parser("tasklist")
    t.add_argument("--domain", required=True)
    t.add_argument("--name", required=True)
    t.add_argument("--task-type", type=int, default=0)
    t.set_defaults(fn=cmd_tasklist)

    a = sub.add_parser("admin")
    asub = a.add_subparsers(dest="admin_cmd", required=True)
    asub.add_parser("describe-host")
    acs = asub.add_parser("close-shard")
    acs.add_argument("--shard-id", type=int, required=True)
    for name in ("describe-workflow", "refresh-tasks"):
        adw = asub.add_parser(name)
        adw.add_argument("--domain", required=True)
        adw.add_argument("--workflow-id", required=True)
        adw.add_argument("--run-id", default="")
    aqs = asub.add_parser("queue-state",
                          help="per-queue cursors/depths of one shard")
    aqs.add_argument("--shard-id", type=int, required=True)
    adlq = asub.add_parser("dlq", help="dead-letter queue operator verbs")
    adlq.add_argument("dlq_cmd", choices=("read", "purge", "merge"))
    adlq.add_argument("--topic", required=True)
    adlq.add_argument("--last-message-id", type=int, default=-1)
    adlq.add_argument("--count", type=int, default=100)
    a.set_defaults(fn=cmd_admin)

    b = sub.add_parser("batch")
    b.add_argument("--operation", required=True,
                   choices=("terminate", "cancel", "signal"))
    b.add_argument("--domain", required=True)
    b.add_argument("--query", default="")
    b.add_argument("--reason", default="")
    b.add_argument("--signal-name", default="")
    b.add_argument("--input", default="")
    b.set_defaults(fn=cmd_batch)

    c = sub.add_parser("canary", help="run health-probe workflows")
    c.add_argument("--probes", default="")
    c.set_defaults(fn=cmd_canary)
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
