"""Archival: history + visibility archivers behind a URI scheme.

Reference: common/archiver/ — interface.go:73,119 (HistoryArchiver /
VisibilityArchiver), provider/provider.go (scheme registry),
filestore/historyArchiver.go (file-backed implementation),
historyIterator.go (paginated reads sized into upload blobs).
"""

from .uri import URI, InvalidURIError
from .interfaces import (
    ArchiveHistoryRequest,
    ArchiveVisibilityRequest,
    HistoryArchiver,
    VisibilityArchiver,
)
from .provider import ArchiverProvider
from .filestore import FilestoreHistoryArchiver, FilestoreVisibilityArchiver
from .history_iterator import HistoryIterator

__all__ = [
    "URI",
    "InvalidURIError",
    "ArchiveHistoryRequest",
    "ArchiveVisibilityRequest",
    "HistoryArchiver",
    "VisibilityArchiver",
    "ArchiverProvider",
    "FilestoreHistoryArchiver",
    "FilestoreVisibilityArchiver",
    "HistoryIterator",
]
