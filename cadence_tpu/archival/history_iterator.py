"""History iterator: page a branch into bounded upload blobs.

Reference: common/archiver/historyIterator.go — archival uploads read
the history tree in pages and emit blobs capped by event count/size so
giant histories stream instead of loading whole.
"""

from __future__ import annotations

from typing import Iterator, List

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.runtime.persistence.records import BranchToken


class HistoryIterator:
    def __init__(
        self,
        history_manager,
        branch_token: bytes,
        next_event_id: int = 1 << 60,
        events_per_blob: int = 256,
    ) -> None:
        self.history = history_manager
        self.branch = BranchToken.from_json(branch_token.decode())
        self.next_event_id = next_event_id
        self.events_per_blob = events_per_blob

    def __iter__(self) -> Iterator[List[List[HistoryEvent]]]:
        token = 0
        blob: List[List[HistoryEvent]] = []
        count = 0
        while True:
            batches, token = self.history.read_history_branch(
                self.branch, 1, self.next_event_id,
                page_size=16, next_token=token,
            )
            for batch in batches:
                blob.append(batch)
                count += len(batch)
                if count >= self.events_per_blob:
                    yield blob
                    blob, count = [], 0
            if not token:
                break
        if blob:
            yield blob

    def all_batches(self) -> List[List[HistoryEvent]]:
        out: List[List[HistoryEvent]] = []
        for blob in self:
            out.extend(blob)
        return out
