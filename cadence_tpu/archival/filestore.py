"""File-backed archivers.

Reference: common/archiver/filestore/historyArchiver.go +
visibilityArchiver.go — archives land as JSON files under the URI path:
``<path>/<domain_id>/<workflow_id>/<run_id>/history.json`` and
``<path>/<domain_id>/visibility/<workflow_id>.<run_id>.json``. Writes
are atomic (tmp + rename) and idempotent (archival retries overwrite
with identical content).
"""

from __future__ import annotations

import copy
import json
import os
import tempfile
from typing import List, Tuple
from urllib.parse import quote

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.runtime.persistence.records import VisibilityRecord
from cadence_tpu.visibility.query import compile_query

from .interfaces import (
    ArchiveHistoryRequest,
    ArchiveVisibilityRequest,
    HistoryArchiver,
    VisibilityArchiver,
)
from .uri import URI, InvalidURIError


def _safe(component: str) -> str:
    """Workflow/run ids are caller-controlled; percent-encode every path
    separator AND '.' (quote leaves dots alone) so ids like '../../x'
    cannot escape the archive root and dotted ids cannot collide in
    the '{wid}.{rid}.json' naming scheme."""
    return quote(component, safe="").replace(".", "%2E") or "_"


def _atomic_write(path: str, data: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class FilestoreHistoryArchiver(HistoryArchiver):
    def validate_uri(self, uri: URI) -> None:
        if uri.scheme != "file" or not uri.path:
            raise InvalidURIError(f"filestore needs file://<dir>, got {uri}")

    def _path(self, uri: URI, domain_id, workflow_id, run_id) -> str:
        return os.path.join(
            uri.path, _safe(domain_id), _safe(workflow_id), _safe(run_id),
            "history.json",
        )

    def archive(
        self, uri: URI, request: ArchiveHistoryRequest,
        batches: List[List[HistoryEvent]],
    ) -> None:
        self.validate_uri(uri)
        payload = {
            "domain_id": request.domain_id,
            "domain_name": request.domain_name,
            "workflow_id": request.workflow_id,
            "run_id": request.run_id,
            "close_failover_version": request.close_failover_version,
            "batches": [[e.to_dict() for e in b] for b in batches],
        }
        _atomic_write(
            self._path(uri, request.domain_id, request.workflow_id,
                       request.run_id),
            json.dumps(payload),
        )

    def get(
        self, uri: URI, domain_id: str, workflow_id: str, run_id: str,
        page_size: int = 0, next_token: int = 0,
    ) -> Tuple[List[List[HistoryEvent]], int]:
        self.validate_uri(uri)
        path = self._path(uri, domain_id, workflow_id, run_id)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no archived history for {workflow_id}/{run_id}"
            )
        with open(path) as f:
            payload = json.load(f)
        batches = [
            [HistoryEvent.from_dict(d) for d in b]
            for b in payload["batches"]
        ]
        if page_size > 0:  # a negative size would return an empty page
            # with an unchanged token — the infinite-pagination bug
            # class fixed in the visibility paginators (r4)
            page = batches[next_token : next_token + page_size]
            token = next_token + len(page)
            return page, (token if token < len(batches) else 0)
        # unpaged read still honors a resume token — a client may page
        # the first call and fetch the remainder with page_size=0
        return batches[next_token:], 0


class FilestoreVisibilityArchiver(VisibilityArchiver):
    # parsed-payload cache bound (files, across all domain dirs): the
    # cache exists to kill O(N^2) re-parsing in paged scans, not to
    # mirror an unbounded archive in memory
    MAX_CACHED_FILES = 4096

    def validate_uri(self, uri: URI) -> None:
        if uri.scheme != "file" or not uri.path:
            raise InvalidURIError(f"filestore needs file://<dir>, got {uri}")

    def _dir(self, uri: URI, domain_id: str) -> str:
        return os.path.join(uri.path, _safe(domain_id), "visibility")

    def archive(self, uri: URI, request: ArchiveVisibilityRequest) -> None:
        self.validate_uri(uri)
        payload = {
            "domain_id": request.domain_id,
            "workflow_id": request.workflow_id,
            "run_id": request.run_id,
            "workflow_type": request.workflow_type,
            "start_time": request.start_time,
            "execution_time": request.execution_time,
            "close_time": request.close_time,
            "close_status": request.close_status,
            "history_length": request.history_length,
            "search_attributes": {
                k: v for k, v in request.search_attributes.items()
                if isinstance(v, (str, int, float, bool))
            },
        }
        _atomic_write(
            os.path.join(
                self._dir(uri, request.domain_id),
                f"{_safe(request.workflow_id)}.{_safe(request.run_id)}.json",
            ),
            json.dumps(payload),
        )

    def query(
        self, uri: URI, domain_id: str, query: str = "",
        page_size: int = 100, next_token: int = 0,
    ) -> Tuple[List[VisibilityRecord], int]:
        self.validate_uri(uri)
        d = self._dir(uri, domain_id)
        # archived visibility files are immutable (one atomic write per
        # closed run), so parse each file ONCE per archiver instance —
        # without this a paged scan re-reads every file per page
        # (O(N^2) opens across a listing). Only the parsed JSON dict is
        # cached; a fresh VisibilityRecord is constructed per call so a
        # caller mutating a returned record (store layers decorate
        # records in place) cannot poison every later query. Bounded by
        # capping INSERTION at MAX_CACHED_FILES — eviction (FIFO or
        # LRU) under a sorted sequential scan degrades to a 0% hit
        # rate once the archive outgrows the bound; keeping the head
        # hot and re-parsing only the tail preserves most of the win.
        cache = getattr(self, "_parsed", None)
        if cache is None:
            cache = self._parsed = {}
        records: List[VisibilityRecord] = []
        if os.path.isdir(d):
            for name in sorted(os.listdir(d)):
                if not name.endswith(".json"):
                    continue
                key = (d, name)
                p = cache.get(key)
                if p is None:
                    with open(os.path.join(d, name)) as f:
                        p = json.load(f)
                    if len(cache) < self.MAX_CACHED_FILES:
                        cache[key] = p
                records.append(VisibilityRecord(
                    domain_id=p["domain_id"],
                    workflow_id=p["workflow_id"],
                    run_id=p["run_id"],
                    workflow_type=p.get("workflow_type", ""),
                    start_time=p.get("start_time", 0),
                    execution_time=p.get("execution_time", 0),
                    close_time=p.get("close_time", 0),
                    close_status=p.get("close_status", 0),
                    history_length=p.get("history_length", 0),
                    # deep copy: archives written HERE hold only scalar
                    # values, but any *.json in the dir is read — a
                    # nested list/dict must not alias the cached payload
                    search_attributes=copy.deepcopy(
                        p.get("search_attributes", {})
                    ),
                ))
        if page_size <= 0:
            page_size = 100  # see AdvancedVisibilityStore: a zero page
            # would return the same token forever
        matched = compile_query(query).apply(records)
        page = matched[next_token : next_token + page_size]
        token = next_token + len(page)
        return page, (token if token < len(matched) else 0)
