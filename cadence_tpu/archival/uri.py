"""Archival URI: scheme://path.

Reference: common/archiver/URI.go — archival destinations are opaque
URIs whose scheme selects the archiver implementation.
"""

from __future__ import annotations

import dataclasses


class InvalidURIError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class URI:
    scheme: str
    path: str

    @classmethod
    def parse(cls, raw: str) -> "URI":
        if "://" not in raw:
            raise InvalidURIError(f"URI {raw!r} missing scheme://")
        scheme, _, path = raw.partition("://")
        if not scheme:
            raise InvalidURIError(f"URI {raw!r} has an empty scheme")
        return cls(scheme=scheme, path=path)

    def __str__(self) -> str:
        return f"{self.scheme}://{self.path}"
