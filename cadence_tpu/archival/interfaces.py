"""Archiver contracts.

Reference: common/archiver/interface.go:73 (HistoryArchiver: Archive /
Get / ValidateURI) and :119 (VisibilityArchiver: Archive / Query /
ValidateURI).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from cadence_tpu.core.events import HistoryEvent

from .uri import URI


@dataclasses.dataclass
class ArchiveHistoryRequest:
    domain_id: str
    domain_name: str
    workflow_id: str
    run_id: str
    branch_token: bytes = b""
    next_event_id: int = 0
    close_failover_version: int = 0


@dataclasses.dataclass
class ArchiveVisibilityRequest:
    domain_id: str
    domain_name: str
    workflow_id: str
    run_id: str
    workflow_type: str = ""
    start_time: int = 0
    execution_time: int = 0
    close_time: int = 0
    close_status: int = 0
    history_length: int = 0
    memo: Dict[str, Any] = dataclasses.field(default_factory=dict)
    search_attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)


class HistoryArchiver:
    def validate_uri(self, uri: URI) -> None:
        raise NotImplementedError

    def archive(
        self, uri: URI, request: ArchiveHistoryRequest,
        batches: List[List[HistoryEvent]],
    ) -> None:
        raise NotImplementedError

    def get(
        self, uri: URI, domain_id: str, workflow_id: str, run_id: str,
        page_size: int = 0, next_token: int = 0,
    ) -> Tuple[List[List[HistoryEvent]], int]:
        raise NotImplementedError


class VisibilityArchiver:
    def validate_uri(self, uri: URI) -> None:
        raise NotImplementedError

    def archive(self, uri: URI, request: ArchiveVisibilityRequest) -> None:
        raise NotImplementedError

    def query(
        self, uri: URI, domain_id: str, query: str = "",
        page_size: int = 100, next_token: int = 0,
    ):
        raise NotImplementedError
