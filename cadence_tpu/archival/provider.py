"""Archiver provider: scheme → implementation registry.

Reference: common/archiver/provider/provider.go — services resolve the
archiver for a domain's archival URI by scheme; unknown schemes error.
"""

from __future__ import annotations

from typing import Callable, Dict

from .interfaces import HistoryArchiver, VisibilityArchiver
from .uri import URI


class ArchiverProvider:
    def __init__(self) -> None:
        self._history: Dict[str, Callable[[], HistoryArchiver]] = {}
        self._visibility: Dict[str, Callable[[], VisibilityArchiver]] = {}

    def register_history_archiver(
        self, scheme: str, factory: Callable[[], HistoryArchiver]
    ) -> None:
        self._history[scheme] = factory

    def register_visibility_archiver(
        self, scheme: str, factory: Callable[[], VisibilityArchiver]
    ) -> None:
        self._visibility[scheme] = factory

    def get_history_archiver(self, scheme_or_uri: str) -> HistoryArchiver:
        scheme = (
            URI.parse(scheme_or_uri).scheme
            if "://" in scheme_or_uri
            else scheme_or_uri
        )
        try:
            return self._history[scheme]()
        except KeyError:
            raise ValueError(f"no history archiver for scheme {scheme!r}")

    def get_visibility_archiver(self, scheme_or_uri: str) -> VisibilityArchiver:
        scheme = (
            URI.parse(scheme_or_uri).scheme
            if "://" in scheme_or_uri
            else scheme_or_uri
        )
        try:
            return self._visibility[scheme]()
        except KeyError:
            raise ValueError(f"no visibility archiver for scheme {scheme!r}")

    @classmethod
    def default(cls) -> "ArchiverProvider":
        from .filestore import (
            FilestoreHistoryArchiver,
            FilestoreVisibilityArchiver,
        )

        p = cls()
        p.register_history_archiver("file", FilestoreHistoryArchiver)
        p.register_visibility_archiver("file", FilestoreVisibilityArchiver)
        return p
