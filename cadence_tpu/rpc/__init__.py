"""RPC layer: the host↔host plane.

Reference: common/rpc.go — YARPC dispatchers over TChannel. The
TPU-native equivalent per SURVEY §2.8 is gRPC for the host plane
(device↔device traffic rides ICI via jax collectives, never this
layer). Uses gRPC generic handlers with a JSON+dataclass codec, so no
IDL compilation step is needed.
"""

from .codec import decode, encode
from .server import FrontendRPCServer
from .client import RemoteClusterRPCClient, RemoteFrontend

__all__ = [
    "decode",
    "encode",
    "FrontendRPCServer",
    "RemoteClusterRPCClient",
    "RemoteFrontend",
]
