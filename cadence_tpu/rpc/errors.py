"""One registry tying service errors to gRPC status codes.

The server aborts with ``<ClassName>: <message>`` details and the
status from this table; the client reverses the mapping by class name.
A single registry (instead of the previous two hand-maintained tables)
makes drift impossible — adding an error class here wires both sides.
"""

from __future__ import annotations

import grpc

from cadence_tpu.frontend.domain_handler import DomainAlreadyExistsError
from cadence_tpu.frontend.version_checker import (
    ClientVersionNotSupportedError,
)
from cadence_tpu.runtime import api as A
from cadence_tpu.runtime.controller import ShardOwnershipLostError
from cadence_tpu.runtime.persistence.errors import EntityNotExistsError

# class name → (grpc status, exception type). EntityNotExistsError (the
# persistence-layer sibling) maps to the SERVICE error on the client so
# callers handle one class.
REGISTRY = {
    "BadRequestError": (
        grpc.StatusCode.INVALID_ARGUMENT, A.BadRequestError),
    "EntityNotExistsServiceError": (
        grpc.StatusCode.NOT_FOUND, A.EntityNotExistsServiceError),
    "EntityNotExistsError": (
        grpc.StatusCode.NOT_FOUND, A.EntityNotExistsServiceError),
    "WorkflowExecutionAlreadyStartedServiceError": (
        grpc.StatusCode.ALREADY_EXISTS,
        A.WorkflowExecutionAlreadyStartedServiceError),
    "DomainAlreadyExistsError": (
        grpc.StatusCode.ALREADY_EXISTS, DomainAlreadyExistsError),
    "DomainNotActiveError": (
        grpc.StatusCode.FAILED_PRECONDITION, A.DomainNotActiveError),
    "CancellationAlreadyRequestedError": (
        grpc.StatusCode.ALREADY_EXISTS,
        A.CancellationAlreadyRequestedError),
    "QueryFailedError": (
        grpc.StatusCode.FAILED_PRECONDITION, A.QueryFailedError),
    "ServiceBusyError": (
        grpc.StatusCode.RESOURCE_EXHAUSTED, A.ServiceBusyError),
    "ClientVersionNotSupportedError": (
        grpc.StatusCode.FAILED_PRECONDITION,
        ClientVersionNotSupportedError),
    "InternalServiceError": (
        grpc.StatusCode.INTERNAL, A.InternalServiceError),
    # shard moved: retryable routing error (retryableClient.go)
    "ShardOwnershipLostError": (
        grpc.StatusCode.UNAVAILABLE, ShardOwnershipLostError),
}

ERROR_CODES = {name: code for name, (code, _) in REGISTRY.items()}
ERROR_TYPES = {name: typ for name, (_, typ) in REGISTRY.items()}
