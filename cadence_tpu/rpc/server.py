"""gRPC server over generic handlers: the frontend's network endpoint.

Reference: common/rpc.go dispatcher + service/frontend Thrift server.
Methods are dispatched by name to the WorkflowHandler/AdminHandler;
requests/responses ride the JSON codec; service errors map to gRPC
status codes with the error class in the details for client-side
re-raise.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from cadence_tpu.runtime import api as A

from . import codec

_SERVICE = "cadence_tpu.Frontend"

# error class name → grpc status (client reverses via ERROR_TYPES)
ERROR_CODES = {
    "BadRequestError": grpc.StatusCode.INVALID_ARGUMENT,
    "EntityNotExistsServiceError": grpc.StatusCode.NOT_FOUND,
    "EntityNotExistsError": grpc.StatusCode.NOT_FOUND,
    "WorkflowExecutionAlreadyStartedServiceError": (
        grpc.StatusCode.ALREADY_EXISTS
    ),
    "DomainAlreadyExistsError": grpc.StatusCode.ALREADY_EXISTS,
    "DomainNotActiveError": grpc.StatusCode.FAILED_PRECONDITION,
    "CancellationAlreadyRequestedError": grpc.StatusCode.ALREADY_EXISTS,
    "QueryFailedError": grpc.StatusCode.FAILED_PRECONDITION,
    "ServiceBusyError": grpc.StatusCode.RESOURCE_EXHAUSTED,
    "ClientVersionNotSupportedError": grpc.StatusCode.FAILED_PRECONDITION,
    "InternalServiceError": grpc.StatusCode.INTERNAL,
}


class _Generic(grpc.GenericRpcHandler):
    def __init__(self, targets) -> None:
        self._targets = targets  # list of handler objects, first match

    def _resolve(self, name: str):
        for target in self._targets:
            fn = getattr(target, name, None)
            if fn is not None and callable(fn) and not name.startswith("_"):
                return fn
        return None

    def service(self, call_details):
        prefix = f"/{_SERVICE}/"
        if not call_details.method.startswith(prefix):
            return None
        name = call_details.method[len(prefix):]
        fn = self._resolve(name)
        if fn is None:
            return None

        def handler(request, context):
            args, kwargs = request
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                cls = type(e).__name__
                code = ERROR_CODES.get(cls, grpc.StatusCode.INTERNAL)
                context.abort(code, f"{cls}: {e}")

        return grpc.unary_unary_rpc_method_handler(
            handler,
            request_deserializer=codec.loads,
            response_serializer=codec.dumps_enveloped,
        )


class FrontendRPCServer:
    def __init__(
        self, frontend, admin=None, address: str = "127.0.0.1:0",
        max_workers: int = 16,
    ) -> None:
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        targets = [frontend] + ([admin] if admin is not None else [])
        self._server.add_generic_rpc_handlers((_Generic(targets),))
        self.port = self._server.add_insecure_port(address)
        self.address = f"127.0.0.1:{self.port}"

    def start(self) -> "FrontendRPCServer":
        self._server.start()
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)
