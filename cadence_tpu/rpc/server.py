"""gRPC servers over generic handlers: every service's network endpoint.

Reference: common/rpc.go dispatcher + the per-service Thrift servers
(service/frontend, service/history/handler.go:227,
service/matching/handler.go). Methods are dispatched by name to the
target handler objects; requests/responses ride the JSON codec; service
errors map to gRPC status codes with the error class in the details for
client-side re-raise.

The history endpoint's targets are an in-process HistoryClient bound to
the LOCAL shard controller plus the HistoryService — exactly the
reference shape where the receiving host's handler re-resolves the
shard's engine and surfaces ShardOwnershipLostError to the caller for
retry after the ring settles (handler.go:262).
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from . import codec
from .errors import ERROR_CODES

FRONTEND_SERVICE = "cadence_tpu.Frontend"
HISTORY_SERVICE = "cadence_tpu.History"
MATCHING_SERVICE = "cadence_tpu.Matching"

# lifecycle/assembly methods must NOT be remotely callable — the
# generic by-name dispatch would otherwise let anyone who can reach
# the port shut a service down or corrupt routing
DISPATCH_DENYLIST = frozenset({
    "start", "stop", "shutdown", "close", "wire", "drain",
    "drain_queues", "notify", "add_host", "remove_host",
    "unload_idle_task_lists", "enable_replication_from",
    "acquire_shards", "release_shard",
})


class _Generic(grpc.GenericRpcHandler):
    def __init__(self, targets, service: str = FRONTEND_SERVICE) -> None:
        self._targets = targets  # list of handler objects, first match
        self._service = service

    def _resolve(self, name: str):
        if name.startswith("_") or name in DISPATCH_DENYLIST:
            return None
        for target in self._targets:
            fn = getattr(target, name, None)
            if fn is not None and callable(fn):
                return fn
        return None

    def service(self, call_details):
        prefix = f"/{self._service}/"
        if not call_details.method.startswith(prefix):
            return None
        name = call_details.method[len(prefix):]
        if name == "ping":
            # built-in liveness probe for the failure detector
            # (membership.FailureDetector; ref ringpop's direct probe,
            # common/membership/rpMonitor.go) — no handler dispatch
            return grpc.unary_unary_rpc_method_handler(
                lambda request, context: {"ok": True},
                request_deserializer=codec.loads,
                response_serializer=codec.dumps_enveloped,
            )
        fn = self._resolve(name)
        if fn is None:
            return None

        service_short = self._service.rsplit(".", 1)[-1].lower()

        def handler(request, context):
            from cadence_tpu.utils.tracing import TRACER, extract_metadata

            args, kwargs = request
            # trace propagation: a caller-shipped context parents this
            # server's span (the cross-process hop of one trace); with
            # no inbound context the endpoint MAY root a new trace at
            # the configured sample rate (telemetry: YAML section) —
            # rate 0 (the default) makes this a no-op span
            ctx = extract_metadata(context.invocation_metadata())
            if ctx is not None:
                span = TRACER.span(
                    f"rpc.{name}", service=service_short, parent=ctx
                )
            else:
                span = TRACER.trace(f"rpc.{name}", service=service_short)
            try:
                with span:
                    return fn(*args, **kwargs)
            except Exception as e:
                cls = type(e).__name__
                code = ERROR_CODES.get(cls, grpc.StatusCode.INTERNAL)
                # ship the error's structured attributes so the client
                # rebuilds a faithful instance (e.run_id on
                # AlreadyStarted, e.shard_id/.owner on
                # ShardOwnershipLost), not a bare-message shell
                attrs = {
                    k: v for k, v in vars(e).items()
                    if isinstance(v, (str, int, float, bool, bytes))
                }
                if attrs:
                    try:
                        context.set_trailing_metadata(
                            (("error-attrs-bin", codec.dumps(attrs)),)
                        )
                    except Exception:
                        pass  # diagnostics only; the error still flows
                context.abort(code, f"{cls}: {e}")

        return grpc.unary_unary_rpc_method_handler(
            handler,
            request_deserializer=codec.loads,
            response_serializer=codec.dumps_enveloped,
        )


class ServiceRPCServer:
    """A gRPC endpoint dispatching one service's methods by name."""

    def __init__(
        self, service: str, targets, address: str = "127.0.0.1:0",
        max_workers: int = 64, server: Optional[grpc.Server] = None,
    ) -> None:
        self.service = service
        self._owns_server = server is None
        self._server = server or grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers(
            (_Generic(list(targets), service),)
        )
        if self._owns_server:
            self.port = self._server.add_insecure_port(address)
            self.address = f"127.0.0.1:{self.port}"

    def start(self) -> "ServiceRPCServer":
        if self._owns_server:
            self._server.start()
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        if self._owns_server:
            self._server.stop(grace)


class FrontendRPCServer(ServiceRPCServer):
    def __init__(
        self, frontend, admin=None, address: str = "127.0.0.1:0",
        max_workers: int = 64,
    ) -> None:
        targets = [frontend] + ([admin] if admin is not None else [])
        super().__init__(FRONTEND_SERVICE, targets, address, max_workers)


class HistoryRPCServer(ServiceRPCServer):
    """This host's history endpoint: an in-proc HistoryClient over the
    LOCAL controller resolves each call's shard engine (not-owned shards
    raise ShardOwnershipLostError back to the remote caller)."""

    def __init__(
        self, history_service, address: str = "127.0.0.1:0",
        max_workers: int = 64, server: Optional[grpc.Server] = None,
    ) -> None:
        from cadence_tpu.client.history import HistoryClient

        # share the service's metrics scope so the client-layer
        # retry_budget_exhausted counter is observable on this host
        local = HistoryClient(
            history_service.controller,
            metrics=history_service.metrics,
        )
        super().__init__(
            HISTORY_SERVICE, [local, history_service], address,
            max_workers, server=server,
        )


class MatchingRPCServer(ServiceRPCServer):
    def __init__(
        self, matching_engine, address: str = "127.0.0.1:0",
        max_workers: int = 64, server: Optional[grpc.Server] = None,
    ) -> None:
        super().__init__(
            MATCHING_SERVICE, [matching_engine], address, max_workers,
            server=server,
        )
