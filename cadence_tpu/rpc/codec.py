"""Wire codec: JSON with dataclass/bytes/enum/tuple envelopes.

Replaces the reference's thrift envelope (common/codec/
version0Thriftrw.go): every API type crossing the host plane is a
registered dataclass; bytes are base64; enums are ints; tuples are
tagged so (events, token) responses round-trip.
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
from typing import Any, Dict

from cadence_tpu.core.events import HistoryEvent, RetryPolicy

_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    _REGISTRY[cls.__name__] = cls
    return cls


def _register_defaults() -> None:
    from cadence_tpu.matching.engine import PollRequest
    from cadence_tpu.runtime import api as A
    from cadence_tpu.runtime.persistence import records as R
    from cadence_tpu.runtime.replication.messages import (
        HistoryTaskV2,
        ReplicationMessages,
    )

    for cls in (
        HistoryTaskV2,
        ReplicationMessages,
        PollRequest,
        A.StartWorkflowRequest,
        A.SignalRequest,
        A.SignalWithStartRequest,
        A.Decision,
        A.PollForDecisionTaskResponse,
        A.PollForActivityTaskResponse,
        A.DescribeWorkflowResponse,
        R.DomainInfo,
        R.DomainConfig,
        R.DomainReplicationConfig,
        R.DomainRecord,
        R.VisibilityRecord,
        R.TaskListInfo,
        RetryPolicy,
    ):
        register(cls)


def encode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return {"__b": base64.b64encode(obj).decode()}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, HistoryEvent):
        return {"__ev": obj.to_dict()}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        name = type(obj).__name__
        return {
            "__dc": name,
            "f": {
                fld.name: encode(getattr(obj, fld.name))
                for fld in dataclasses.fields(obj)
            },
        }
    if isinstance(obj, tuple):
        return {"__t": [encode(v) for v in obj]}
    if isinstance(obj, list):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                # silent str() coercion would corrupt int-keyed maps on
                # round-trip (d[1] -> KeyError server-side); fail loud
                raise TypeError(
                    f"cannot encode dict key {k!r} "
                    f"({type(k).__name__}): wire dicts are str-keyed"
                )
        enc = {k: encode(v) for k, v in obj.items()}
        if any(k in enc for k in ("__b", "__ev", "__t", "__dc", "__esc",
                                  "__s")):
            # user payloads may legitimately carry marker-shaped keys
            return {"__esc": enc}
        return enc
    if isinstance(obj, (set, frozenset)):
        return {"__s": [encode(v) for v in sorted(obj)]}
    raise TypeError(f"cannot encode {type(obj).__name__}")


def decode(obj: Any) -> Any:
    if isinstance(obj, list):
        return [decode(v) for v in obj]
    if isinstance(obj, dict):
        if "__b" in obj and len(obj) == 1:
            return base64.b64decode(obj["__b"])
        if "__ev" in obj and len(obj) == 1:
            return HistoryEvent.from_dict(obj["__ev"])
        if "__t" in obj and len(obj) == 1:
            return tuple(decode(v) for v in obj["__t"])
        if "__s" in obj and len(obj) == 1:
            return set(decode(v) for v in obj["__s"])
        if "__esc" in obj and len(obj) == 1:
            return {k: decode(v) for k, v in obj["__esc"].items()}
        if "__dc" in obj:
            if not _REGISTRY:
                _register_defaults()
            cls = _REGISTRY.get(obj["__dc"])
            if cls is None:
                raise TypeError(f"unknown wire type {obj['__dc']}")
            return cls(**{k: decode(v) for k, v in obj["f"].items()})
        return {k: decode(v) for k, v in obj.items()}
    return obj


def dumps(obj: Any) -> bytes:
    if not _REGISTRY:
        _register_defaults()
    return json.dumps(encode(obj)).encode()


def loads(raw: bytes) -> Any:
    if not _REGISTRY:
        _register_defaults()
    return decode(json.loads(raw.decode()))


# grpc-python treats a deserializer returning None as a deserialization
# FAILURE (grpc/_channel.py "Exception deserializing response!"), so
# void RPC results must ride in an envelope.


def dumps_enveloped(obj: Any) -> bytes:
    return dumps({"r": obj})


def loads_envelope(raw: bytes) -> Any:
    """Returns the ENVELOPE dict — the deserializer result itself must
    never be None (grpc reads that as failure); callers unwrap ["r"]."""
    return loads(raw)
