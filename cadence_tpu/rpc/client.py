"""gRPC client stub: a remote WorkflowHandler with the same surface.

Any method on the server-side frontend/admin is callable by name; the
stub re-raises the server's service errors as their local classes.
"""

from __future__ import annotations

from typing import Any

import grpc

from . import codec
from .errors import ERROR_TYPES

_SERVICE = "cadence_tpu.Frontend"


class _Method:
    def __init__(self, channel: grpc.Channel, name: str,
                 service: str = _SERVICE) -> None:
        self._call = channel.unary_unary(
            f"/{service}/{name}",
            request_serializer=codec.dumps,
            response_deserializer=codec.loads_envelope,
        )

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        # trace propagation (utils/tracing.py): a sampled caller's
        # context rides the metadata so the receiving host's spans join
        # the same trace; with no active trace this is one thread-local
        # read returning the metadata unchanged (None)
        from cadence_tpu.utils.tracing import inject_metadata

        try:
            return self._call(
                (list(args), kwargs), metadata=inject_metadata()
            )["r"]
        except grpc.RpcError as e:
            details = e.details() or ""
            cls_name, _, msg = details.partition(": ")
            exc_type = ERROR_TYPES.get(cls_name)
            if exc_type is not None:
                exc = _build(exc_type, msg)
                # restore the structured attributes the server attached
                # (e.run_id, e.shard_id, ...) — without them the rebuilt
                # instance is a bare-message shell
                for key, value in (e.trailing_metadata() or ()):
                    if key == "error-attrs-bin":
                        try:
                            exc.__dict__.update(codec.loads(value))
                        except Exception:
                            pass
                raise exc from None
            raise


def _build(exc_type, msg):
    try:
        return exc_type(msg)
    except TypeError:
        e = exc_type.__new__(exc_type)
        Exception.__init__(e, msg)
        return e


class RemoteService:
    """Dial a service endpoint; any method is callable by name with the
    same surface as the server-side handler."""

    _service = _SERVICE

    def __init__(self, address: str) -> None:
        self.address = address
        self._channel = grpc.insecure_channel(address)
        self._methods = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        m = self._methods.get(name)
        if m is None:
            m = self._methods[name] = _Method(
                self._channel, name, self._service
            )
        return m

    def close(self) -> None:
        self._channel.close()


class RemoteFrontend(RemoteService):
    """Dial a frontend; use exactly like a local WorkflowHandler."""

    _service = "cadence_tpu.Frontend"


class RemoteHistory(RemoteService):
    """Dial a history host; same surface as an in-proc HistoryClient."""

    _service = "cadence_tpu.History"


class RemoteMatching(RemoteService):
    """Dial a matching host; same surface as a MatchingEngine."""

    _service = "cadence_tpu.Matching"


class RemoteClusterRPCClient:
    """Cross-cluster replication transport: the DCN pull plane.

    Implements the fetcher's RemoteClusterClient contract
    (runtime/replication/processor.py) over the gRPC history endpoint
    of a host in the SOURCE cluster — the consumer cluster's fetchers
    dial the source and drain its replicator queue, exactly the
    reference's admin client GetReplicationMessages over the cross-DC
    connection (client/admin + common/rpc dispatching on
    ClusterInformation rpc addresses).
    """

    def __init__(self, address: str, consumer_cluster: str) -> None:
        self._stub = RemoteHistory(address)
        self.address = address
        self.consumer_cluster = consumer_cluster

    def get_replication_messages(
        self, shard_id: int, last_retrieved_id: int, max_tasks=None
    ):
        if max_tasks is None:
            # omit the argument entirely: a source host still running
            # the pre-paging handler signature keeps serving fetches
            # through a rolling upgrade (the same compatibility rule
            # ReplicationTaskFetcher.fetch applies)
            return self._stub.get_replication_messages(
                shard_id, last_retrieved_id, self.consumer_cluster
            )
        return self._stub.get_replication_messages(
            shard_id, last_retrieved_id, self.consumer_cluster,
            max_tasks,
        )

    def get_workflow_history_raw(
        self, domain_id: str, workflow_id: str, run_id: str,
        start_event_id: int, end_event_id: int,
    ):
        return self._stub.get_workflow_history_raw(
            domain_id, workflow_id, run_id, start_event_id, end_event_id
        )

    # -- bandwidth-adaptive state transfer (replication/transport.py) --

    def get_replication_backlog(
        self, shard_id: int, last_retrieved_id: int
    ):
        """Per-run backlog spans past the cursor (no event payloads) —
        the adaptive consumer's catch-up probe."""
        return self._stub.get_replication_backlog(
            shard_id, last_retrieved_id
        )

    def get_replication_checkpoint(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> bytes:
        """Delta-compressed branch-tip ReplayCheckpoint (snapshot
        shipping); b"" = no shippable snapshot."""
        return self._stub.get_replication_checkpoint(
            domain_id, workflow_id, run_id
        )

    def close(self) -> None:
        self._stub.close()


# -- liveness probe (failure detector transport) -------------------------

_PING_SERVICES = {
    "frontend": "cadence_tpu.Frontend",
    "history": "cadence_tpu.History",
    "matching": "cadence_tpu.Matching",
}


def grpc_ping(service: str, address: str, timeout_s: float = 1.0) -> bool:
    """One direct liveness probe: the built-in ``ping`` method every
    ServiceRPCServer exposes (rpc/server.py). Transport for
    membership.FailureDetector — the stand-in for ringpop's SWIM
    direct-probe (/root/reference/common/membership/rpMonitor.go:44).

    A fresh channel per probe keeps the probe honest: a cached channel
    can report a stale READY state for a port whose process just died.
    """
    service_name = _PING_SERVICES.get(service)
    if service_name is None:
        return True  # no RPC surface to probe (e.g. worker ring)
    channel = grpc.insecure_channel(address)
    try:
        call = channel.unary_unary(
            f"/{service_name}/ping",
            request_serializer=codec.dumps,
            response_deserializer=codec.loads_envelope,
        )
        call(([], {}), timeout=timeout_s)
        return True
    except grpc.RpcError as e:
        if e.code() == grpc.StatusCode.UNAVAILABLE:
            return False  # connection refused/reset: the process is gone
        # DEADLINE_EXCEEDED etc. can just mean the service thread pool
        # is saturated (64 long-polls queue the ping behind them) — a
        # busy host must not be evicted as dead. Distinguish with a raw
        # TCP connect: a live process still accepts; a crashed or
        # blackholed one does not.
        return _tcp_alive(address, timeout_s)
    finally:
        channel.close()


def _tcp_alive(address: str, timeout_s: float) -> bool:
    import socket

    host, _, port = address.rpartition(":")
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)),
                                      timeout=timeout_s):
            return True
    except OSError:
        return False
