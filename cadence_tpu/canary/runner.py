"""Canary runner: register the probe worker, drive every probe, report.

Reference: canary/canary.go + runner.go — the sanity workflow fans out
one child per probe type; here the runner drives probes directly and
reports per-probe latency + pass/fail.
"""

from __future__ import annotations

import time
import traceback
from typing import List, Optional

from cadence_tpu.frontend.domain_handler import DomainAlreadyExistsError
from cadence_tpu.worker import Worker

from .probes import (
    LOCAL_ACTIVITIES,
    PROBES,
    TASK_LIST,
    WORKFLOWS,
    make_activities,
)

CANARY_DOMAIN = "cadence-canary"


def run_canary(
    address: str = "", probes: Optional[List[str]] = None,
    frontend=None, keep_box=None,
) -> List[dict]:
    """Run probes against ``address`` (or an embedded onebox)."""
    box = None
    if frontend is None:
        if address:
            from cadence_tpu.rpc import RemoteFrontend

            frontend = RemoteFrontend(address)
        else:
            from cadence_tpu.testing.onebox import Onebox

            box = Onebox(num_shards=4).start()
            frontend = box.frontend
            if keep_box is not None:
                # hand the embedded box to the caller (tests read its
                # metrics registry after the run)
                keep_box.box = box
    try:
        try:
            frontend.register_domain(CANARY_DOMAIN, retention_days=1)
        except DomainAlreadyExistsError:
            pass

        worker = Worker(frontend, CANARY_DOMAIN, TASK_LIST,
                        identity="canary")
        for wf_type, fn in WORKFLOWS.items():
            worker.register_workflow(wf_type, fn)
        for name, fn in make_activities().items():
            worker.register_activity(name, fn)
        for name, fn in LOCAL_ACTIVITIES.items():
            worker.register_local_activity(name, fn)
        worker.register_query_handler(
            "canary-query", lambda qt, args: b"canary-query-alive"
        )
        worker.start()
        try:
            selected = probes or list(PROBES)
            results = []
            for name in selected:
                probe = PROBES.get(name)
                if probe is None:
                    results.append(
                        {"probe": name, "ok": False,
                         "error": "unknown probe"}
                    )
                    continue
                t0 = time.monotonic()
                try:
                    probe(frontend, CANARY_DOMAIN)
                    results.append({
                        "probe": name, "ok": True,
                        "latency_ms": round(
                            (time.monotonic() - t0) * 1000, 1
                        ),
                    })
                except Exception as e:
                    results.append({
                        "probe": name, "ok": False,
                        "latency_ms": round(
                            (time.monotonic() - t0) * 1000, 1
                        ),
                        "error": f"{type(e).__name__}: {e}",
                        "trace": traceback.format_exc()[-1000:],
                    })
            return results
        finally:
            worker.stop()
    finally:
        if box is not None:
            box.stop()
