"""Canary probe workflows.

Reference canary workflow set (canary/const.go:64-84): echo, signal,
signal.external, visibility, searchAttributes, concurrent-execution,
query, timeout, localactivity, cancellation, cancellation.external,
retry, reset.base/reset, cron, sanity (the batch/archival probes drive
worker subsystems and live with their services). Each probe here is
(workflow fn + activities + driver fn); the driver runs against any
frontend (local handler or gRPC stub) and asserts the outcome.
"""

from __future__ import annotations

import time
import uuid
from typing import Callable, Dict

from cadence_tpu.core.enums import EventType
from cadence_tpu.core.events import RetryPolicy
from cadence_tpu.runtime.api import SignalRequest, StartWorkflowRequest
from cadence_tpu.worker.sdk import WorkflowCancelled

TASK_LIST = "canary-tl"


# -- workflow definitions -------------------------------------------------


def echo_workflow(ctx, input):
    out = yield ctx.schedule_activity("echo_activity", input)
    return out


def signal_workflow(ctx, input):
    payload = yield ctx.wait_signal("canary-signal")
    return b"signaled:" + payload


def timer_workflow(ctx, input):
    yield ctx.start_timer(1)
    return b"timer-done"


def retry_workflow(ctx, input):
    from cadence_tpu.worker.sdk import ActivityError

    attempts = 0
    while True:
        try:
            out = yield ctx.schedule_activity("flaky_activity", input)
            return out + b":after-" + str(attempts).encode() + b"-retries"
        except ActivityError:
            attempts += 1
            if attempts > 3:
                raise


def concurrent_workflow(ctx, input):
    results = []
    for i in range(3):
        r = yield ctx.start_child_workflow(
            "canary-echo", f"canary-child-{input.decode()}-{i}",
            input=str(i).encode(), task_list=TASK_LIST,
        )
        results.append(r)
    return b",".join(results)


def query_workflow(ctx, input):
    yield ctx.wait_signal("done")
    return b"ok"


def sleeper_workflow(ctx, input):
    # blocks forever: the timeout probe closes it via workflow timeout
    yield ctx.wait_signal("never")
    return b"unreachable"


def cancellation_workflow(ctx, input):
    # reference canary/cancellation.go: await ctx.Done, return its error
    cause = yield ctx.wait_cancel()
    raise WorkflowCancelled(cause or b"canceled")


def canceller_workflow(ctx, input):
    # reference canary/cancellation.go external variant
    yield ctx.request_cancel_external("", input.decode())
    return b"cancel-sent"


def signaller_workflow(ctx, input):
    yield ctx.signal_external("", input.decode(), "canary-signal", b"ext")
    return b"signal-sent"


def local_activity_workflow(ctx, input):
    # result comes back through a MarkerRecorded event, never matching
    out = yield ctx.local_activity("echo_local", input)
    return b"local:" + out


def search_attr_workflow(ctx, input):
    yield ctx.upsert_search_attributes(
        {"CustomKeywordField": input.decode()}
    )
    return b"upserted"


def fail_once_workflow(ctx, input):
    # whole-RUN failure on the first attempt; the engine's workflow
    # retry policy starts attempt 2, which succeeds
    out = yield ctx.schedule_activity("fail_once_activity", input)
    return out


def cron_tick_workflow(ctx, input):
    return b"tick"


def sanity_workflow(ctx, input):
    # reference canary/sanity.go: the sanity workflow fans out one
    # child per probe workflow type and fails if any child fails
    key = input.decode()
    results = []
    for i, child_type in enumerate(
        ("canary-echo", "canary-timer", "canary-cron-tick")
    ):
        r = yield ctx.start_child_workflow(
            child_type, f"sanity-{key}-{i}", input=b"s",
            task_list=TASK_LIST,
        )
        results.append(r)
    return b"sanity:" + str(len(results)).encode()


def batch_parent_workflow(ctx, input):
    # reference canary/batch.go: waves of children
    key = input.decode()
    total = 0
    for wave in range(2):
        for i in range(2):
            yield ctx.start_child_workflow(
                "canary-echo", f"batch-{key}-{wave}-{i}", input=b"b",
                task_list=TASK_LIST,
            )
            total += 1
    return b"children:" + str(total).encode()


_flaky_counters: Dict[str, int] = {}


def make_activities():
    def echo_activity(data: bytes) -> bytes:
        return data

    def flaky_activity(data: bytes) -> bytes:
        key = data.decode() or "default"
        n = _flaky_counters.get(key, 0) + 1
        _flaky_counters[key] = n
        if n < 3:
            raise RuntimeError(f"flaking (attempt {n})")
        return b"succeeded"

    def fail_once_activity(data: bytes) -> bytes:
        key = "wf-retry:" + (data.decode() or "default")
        n = _flaky_counters.get(key, 0) + 1
        _flaky_counters[key] = n
        if n < 2:
            raise RuntimeError(f"failing the whole run (attempt {n})")
        return b"retried"

    return {
        "echo_activity": echo_activity,
        "flaky_activity": flaky_activity,
        "fail_once_activity": fail_once_activity,
    }


WORKFLOWS: Dict[str, Callable] = {
    "canary-echo": echo_workflow,
    "canary-signal": signal_workflow,
    "canary-timer": timer_workflow,
    "canary-retry": retry_workflow,
    "canary-concurrent": concurrent_workflow,
    "canary-query": query_workflow,
    "canary-sleeper": sleeper_workflow,
    "canary-cancellation": cancellation_workflow,
    "canary-canceller": canceller_workflow,
    "canary-signaller": signaller_workflow,
    "canary-local-activity": local_activity_workflow,
    "canary-search-attr": search_attr_workflow,
    "canary-fail-once": fail_once_workflow,
    "canary-cron-tick": cron_tick_workflow,
    "canary-sanity": sanity_workflow,
    "canary-batch-parent": batch_parent_workflow,
}

LOCAL_ACTIVITIES: Dict[str, Callable] = {
    "echo_local": lambda data: b"<" + data + b">",
}


# -- probe drivers --------------------------------------------------------


def _wait_result(fe, domain, wf_id, run_id, timeout_s=20.0) -> bytes:
    """Wait for a COMPLETED close and return its result."""
    last = _wait_close(fe, domain, wf_id, run_id, timeout_s)
    if last.event_type != EventType.WorkflowExecutionCompleted:
        raise AssertionError(
            f"closed as {last.event_type.name}: {last.attributes}"
        )
    return last.attributes.get("result", b"")


def _start(fe, domain, wf_type, wf_id, input=b"", timeout=120, **kw):
    return fe.start_workflow_execution(
        StartWorkflowRequest(
            domain=domain, workflow_id=wf_id, workflow_type=wf_type,
            task_list=TASK_LIST, input=input,
            execution_start_to_close_timeout_seconds=timeout,
            **kw,
        )
    )


def _wait_close(fe, domain, wf_id, run_id, timeout_s=20.0):
    """Wait for the run to close; returns its final history event."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        desc = fe.describe_workflow_execution(domain, wf_id, run_id)
        if not desc.is_running:
            events, _ = fe.get_workflow_execution_history(
                domain, wf_id, run_id
            )
            return events[-1]
        time.sleep(0.05)
    raise TimeoutError(f"{wf_id} still running after {timeout_s}s")


def probe_echo(fe, domain) -> None:
    wf = f"canary-echo-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-echo", wf, b"ping")
    assert _wait_result(fe, domain, wf, run) == b"ping"


def probe_signal(fe, domain) -> None:
    wf = f"canary-signal-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-signal", wf)
    fe.signal_workflow_execution(
        SignalRequest(
            domain=domain, workflow_id=wf,
            signal_name="canary-signal", input=b"hello",
        )
    )
    assert _wait_result(fe, domain, wf, run) == b"signaled:hello"


def probe_timer(fe, domain) -> None:
    wf = f"canary-timer-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-timer", wf)
    assert _wait_result(fe, domain, wf, run) == b"timer-done"


def probe_retry(fe, domain) -> None:
    key = uuid.uuid4().hex[:8]
    wf = f"canary-retry-{key}"
    run = _start(fe, domain, "canary-retry", wf, key.encode())
    out = _wait_result(fe, domain, wf, run)
    assert out.startswith(b"succeeded"), out


def probe_concurrent(fe, domain) -> None:
    key = uuid.uuid4().hex[:8]
    wf = f"canary-concurrent-{key}"
    run = _start(fe, domain, "canary-concurrent", wf, key.encode())
    assert _wait_result(fe, domain, wf, run) == b"0,1,2"


def probe_query(fe, domain) -> None:
    wf = f"canary-query-{uuid.uuid4().hex[:8]}"
    _start(fe, domain, "canary-query", wf)
    time.sleep(0.3)  # allow the first decision to settle
    out = fe.query_workflow(
        domain, wf, query_type="status", timeout_s=10.0
    )
    assert out == b"canary-query-alive", out


def probe_visibility(fe, domain) -> None:
    wf = f"canary-vis-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-echo", wf, b"v")
    _wait_result(fe, domain, wf, run)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        n = fe.count_workflow_executions(
            domain, f"WorkflowID = '{wf}' AND CloseStatus = 'COMPLETED'"
        )
        if n == 1:
            return
        time.sleep(0.1)
    raise AssertionError("closed workflow never became visible")


def probe_reset(fe, domain) -> None:
    wf = f"canary-reset-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-echo", wf, b"r")
    _wait_result(fe, domain, wf, run)
    events, _ = fe.get_workflow_execution_history(domain, wf, run)
    completed = [
        e for e in events
        if e.event_type == EventType.DecisionTaskCompleted
    ][0]
    new_run = fe.reset_workflow_execution(
        domain, wf, run, reason="canary",
        decision_finish_event_id=completed.event_id,
    )
    assert _wait_result(fe, domain, wf, new_run) == b"r"


def probe_timeout(fe, domain) -> None:
    # reference canary/timeout.go: a run that must close as TimedOut
    wf = f"canary-timeout-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-sleeper", wf, timeout=1)
    last = _wait_close(fe, domain, wf, run, timeout_s=20.0)
    assert last.event_type == EventType.WorkflowExecutionTimedOut, (
        last.event_type
    )


def probe_cancellation(fe, domain) -> None:
    wf = f"canary-cancel-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-cancellation", wf)
    fe.request_cancel_workflow_execution(domain, wf, run,
                                         identity="canary")
    last = _wait_close(fe, domain, wf, run)
    assert last.event_type == EventType.WorkflowExecutionCanceled, (
        last.event_type
    )


def probe_cancellation_external(fe, domain) -> None:
    key = uuid.uuid4().hex[:8]
    victim = f"canary-cancel-victim-{key}"
    victim_run = _start(fe, domain, "canary-cancellation", victim)
    canceller = f"canary-canceller-{key}"
    run = _start(fe, domain, "canary-canceller", canceller,
                 victim.encode())
    assert _wait_result(fe, domain, canceller, run) == b"cancel-sent"
    last = _wait_close(fe, domain, victim, victim_run)
    assert last.event_type == EventType.WorkflowExecutionCanceled, (
        last.event_type
    )


def probe_signal_external(fe, domain) -> None:
    key = uuid.uuid4().hex[:8]
    receiver = f"canary-sig-receiver-{key}"
    receiver_run = _start(fe, domain, "canary-signal", receiver)
    sender = f"canary-signaller-{key}"
    run = _start(fe, domain, "canary-signaller", sender, receiver.encode())
    assert _wait_result(fe, domain, sender, run) == b"signal-sent"
    assert _wait_result(fe, domain, receiver, receiver_run) == (
        b"signaled:ext"
    )


def probe_local_activity(fe, domain) -> None:
    wf = f"canary-local-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-local-activity", wf, b"la")
    assert _wait_result(fe, domain, wf, run) == b"local:<la>"
    events, _ = fe.get_workflow_execution_history(domain, wf, run)
    kinds = {e.event_type for e in events}
    assert EventType.MarkerRecorded in kinds, "no marker recorded"
    assert EventType.ActivityTaskScheduled not in kinds, (
        "local activity went through matching"
    )


def probe_search_attributes(fe, domain) -> None:
    key = f"canary-{uuid.uuid4().hex[:8]}"
    wf = f"canary-sa-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-search-attr", wf, key.encode())
    assert _wait_result(fe, domain, wf, run) == b"upserted"
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if fe.count_workflow_executions(
            domain, f"CustomKeywordField = '{key}'"
        ) >= 1:
            return
        time.sleep(0.1)
    raise AssertionError("upserted search attribute never became queryable")


def probe_workflow_retry(fe, domain) -> None:
    # run 1 fails; the workflow-level retry policy restarts it
    key = uuid.uuid4().hex[:8]
    wf = f"canary-wfretry-{key}"
    run = _start(
        fe, domain, "canary-fail-once", wf, key.encode(),
        retry_policy=RetryPolicy(
            initial_interval_seconds=1, backoff_coefficient=1.0,
            maximum_attempts=3, expiration_interval_seconds=0,
        ),
    )
    first = _wait_close(fe, domain, wf, run)
    assert first.event_type == EventType.WorkflowExecutionContinuedAsNew, (
        first.event_type
    )
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        desc = fe.describe_workflow_execution(domain, wf)
        if desc.run_id != run and not desc.is_running:
            assert _wait_result(fe, domain, wf, desc.run_id) == b"retried"
            return
        time.sleep(0.1)
    raise TimeoutError("retry attempt never completed")


def probe_cron(fe, domain) -> None:
    # reference canary/cron.go: the schedule keeps producing runs
    wf = f"canary-cron-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-cron-tick", wf,
                 cron_schedule="@every 1s")
    try:
        first = _wait_close(fe, domain, wf, run)
        assert first.event_type == (
            EventType.WorkflowExecutionContinuedAsNew
        ), first.event_type
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            n = fe.count_workflow_executions(
                domain,
                f"WorkflowID = '{wf}' AND "
                "CloseStatus = 'CONTINUED_AS_NEW'",
            )
            if n >= 2:
                return
            time.sleep(0.1)
        raise AssertionError("cron chain produced fewer than 2 fires")
    finally:
        try:
            fe.terminate_workflow_execution(
                domain, wf, reason="canary cron stop"
            )
        except Exception:
            pass  # the chain may be between runs


def probe_sanity(fe, domain) -> None:
    key = uuid.uuid4().hex[:8]
    wf = f"canary-sanity-{key}"
    run = _start(fe, domain, "canary-sanity", wf, key.encode())
    assert _wait_result(fe, domain, wf, run, timeout_s=30.0) == b"sanity:3"


def probe_batch_children(fe, domain) -> None:
    key = uuid.uuid4().hex[:8]
    wf = f"canary-batch-{key}"
    run = _start(fe, domain, "canary-batch-parent", wf, key.encode())
    assert _wait_result(fe, domain, wf, run, timeout_s=30.0) == b"children:4"


def probe_batch_operation(fe, domain) -> None:
    """Bulk terminate through the batcher system workflow
    (service/worker/batcher; canary batch coverage of the service)."""
    import json

    from cadence_tpu.worker.archiver import SYSTEM_DOMAIN
    from cadence_tpu.worker.batcher import (
        BATCHER_TASK_LIST,
        BATCHER_WORKFLOW_TYPE,
    )

    key = uuid.uuid4().hex[:8]
    victims = [f"canary-bt-{key}-{i}" for i in range(3)]
    runs = {
        wf: _start(fe, domain, "canary-sleeper", wf) for wf in victims
    }
    batch_wf = f"canary-batch-op-{key}"
    payload = json.dumps({
        "operation": "terminate",
        "domain": domain,
        "executions": [{"workflow_id": wf} for wf in victims],
        "params": {"reason": "canary batch"},
    }).encode()
    fe.start_workflow_execution(
        StartWorkflowRequest(
            domain=SYSTEM_DOMAIN, workflow_id=batch_wf,
            workflow_type=BATCHER_WORKFLOW_TYPE,
            task_list=BATCHER_TASK_LIST, input=payload,
            execution_start_to_close_timeout_seconds=60,
        )
    )
    for wf in victims:
        last = _wait_close(fe, domain, wf, runs[wf], timeout_s=30.0)
        assert last.event_type == EventType.WorkflowExecutionTerminated, (
            wf, last.event_type,
        )


def probe_archival(fe, domain) -> None:
    """Close → archived history in the filestore (host/archival_test.go
    shape). Uses ONE idempotently-registered archival-enabled domain —
    a periodic canary must not leak a domain per run — and closes the
    workflow by terminate so no worker is involved."""
    import os
    import tempfile

    from cadence_tpu.archival import ArchiverProvider, URI
    from cadence_tpu.frontend.domain_handler import (
        ArchivalStatus,
        DomainAlreadyExistsError,
    )

    tmp = os.path.join(tempfile.gettempdir(), "canary-archival")
    adomain = "canary-archival"
    try:
        fe.register_domain(
            adomain, retention_days=1,
            history_archival_status=ArchivalStatus.ENABLED,
            history_archival_uri=f"file://{tmp}/h",
            visibility_archival_status=ArchivalStatus.ENABLED,
            visibility_archival_uri=f"file://{tmp}/v",
        )
    except DomainAlreadyExistsError:
        pass
    key = uuid.uuid4().hex[:8]
    wf = f"canary-arch-wf-{key}"
    run = fe.start_workflow_execution(
        StartWorkflowRequest(
            domain=adomain, workflow_id=wf, workflow_type="canary-echo",
            task_list=TASK_LIST,
            execution_start_to_close_timeout_seconds=60,
        )
    )
    fe.terminate_workflow_execution(adomain, wf, run, reason="archive me")

    archiver = ArchiverProvider.default().get_history_archiver("file")
    uri = URI.parse(f"file://{tmp}/h")
    domain_id = fe.describe_domain(name=adomain).info.id
    deadline = time.monotonic() + 20.0
    batches = None
    while time.monotonic() < deadline:
        try:
            batches, _ = archiver.get(uri, domain_id, wf, run)
        except FileNotFoundError:
            batches = None  # not archived yet
        if batches:
            break
        time.sleep(0.2)
    assert batches, "history never reached the archive store"
    events = [e for b in batches for e in b]
    assert events[0].event_type == EventType.WorkflowExecutionStarted
    assert events[-1].event_type == EventType.WorkflowExecutionTerminated


PROBES: Dict[str, Callable] = {
    "echo": probe_echo,
    "signal": probe_signal,
    "timer": probe_timer,
    "retry": probe_retry,
    "concurrent": probe_concurrent,
    "query": probe_query,
    "visibility": probe_visibility,
    "reset": probe_reset,
    "timeout": probe_timeout,
    "cancellation": probe_cancellation,
    "cancellation_external": probe_cancellation_external,
    "signal_external": probe_signal_external,
    "local_activity": probe_local_activity,
    "search_attributes": probe_search_attributes,
    "workflow_retry": probe_workflow_retry,
    "cron": probe_cron,
    "sanity": probe_sanity,
    "batch": probe_batch_children,
    "batch_operation": probe_batch_operation,
    "archival": probe_archival,
}
