"""Canary probe workflows (reference canary/: echo.go, signal.go,
timeout.go, retry.go, concurrentExec.go, query.go, reset.go).

Each probe is (workflow fn + activities + driver fn); the driver runs
against any frontend (local handler or gRPC stub) and asserts the
outcome.
"""

from __future__ import annotations

import time
import uuid
from typing import Callable, Dict

from cadence_tpu.core.enums import EventType
from cadence_tpu.runtime.api import SignalRequest, StartWorkflowRequest

TASK_LIST = "canary-tl"


# -- workflow definitions -------------------------------------------------


def echo_workflow(ctx, input):
    out = yield ctx.schedule_activity("echo_activity", input)
    return out


def signal_workflow(ctx, input):
    payload = yield ctx.wait_signal("canary-signal")
    return b"signaled:" + payload


def timer_workflow(ctx, input):
    yield ctx.start_timer(1)
    return b"timer-done"


def retry_workflow(ctx, input):
    from cadence_tpu.worker.sdk import ActivityError

    attempts = 0
    while True:
        try:
            out = yield ctx.schedule_activity("flaky_activity", input)
            return out + b":after-" + str(attempts).encode() + b"-retries"
        except ActivityError:
            attempts += 1
            if attempts > 3:
                raise


def concurrent_workflow(ctx, input):
    results = []
    for i in range(3):
        r = yield ctx.start_child_workflow(
            "canary-echo", f"canary-child-{input.decode()}-{i}",
            input=str(i).encode(), task_list=TASK_LIST,
        )
        results.append(r)
    return b",".join(results)


def query_workflow(ctx, input):
    yield ctx.wait_signal("done")
    return b"ok"


_flaky_counters: Dict[str, int] = {}


def make_activities():
    def echo_activity(data: bytes) -> bytes:
        return data

    def flaky_activity(data: bytes) -> bytes:
        key = data.decode() or "default"
        n = _flaky_counters.get(key, 0) + 1
        _flaky_counters[key] = n
        if n < 3:
            raise RuntimeError(f"flaking (attempt {n})")
        return b"succeeded"

    return {"echo_activity": echo_activity, "flaky_activity": flaky_activity}


WORKFLOWS: Dict[str, Callable] = {
    "canary-echo": echo_workflow,
    "canary-signal": signal_workflow,
    "canary-timer": timer_workflow,
    "canary-retry": retry_workflow,
    "canary-concurrent": concurrent_workflow,
    "canary-query": query_workflow,
}


# -- probe drivers --------------------------------------------------------


def _wait_result(fe, domain, wf_id, run_id, timeout_s=20.0) -> bytes:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        desc = fe.describe_workflow_execution(domain, wf_id, run_id)
        if not desc.is_running:
            events, _ = fe.get_workflow_execution_history(
                domain, wf_id, run_id
            )
            last = events[-1]
            if last.event_type != EventType.WorkflowExecutionCompleted:
                raise AssertionError(
                    f"closed as {last.event_type.name}: {last.attributes}"
                )
            return last.attributes.get("result", b"")
        time.sleep(0.05)
    raise TimeoutError(f"{wf_id} still running after {timeout_s}s")


def _start(fe, domain, wf_type, wf_id, input=b"", timeout=120):
    return fe.start_workflow_execution(
        StartWorkflowRequest(
            domain=domain, workflow_id=wf_id, workflow_type=wf_type,
            task_list=TASK_LIST, input=input,
            execution_start_to_close_timeout_seconds=timeout,
        )
    )


def probe_echo(fe, domain) -> None:
    wf = f"canary-echo-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-echo", wf, b"ping")
    assert _wait_result(fe, domain, wf, run) == b"ping"


def probe_signal(fe, domain) -> None:
    wf = f"canary-signal-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-signal", wf)
    fe.signal_workflow_execution(
        SignalRequest(
            domain=domain, workflow_id=wf,
            signal_name="canary-signal", input=b"hello",
        )
    )
    assert _wait_result(fe, domain, wf, run) == b"signaled:hello"


def probe_timer(fe, domain) -> None:
    wf = f"canary-timer-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-timer", wf)
    assert _wait_result(fe, domain, wf, run) == b"timer-done"


def probe_retry(fe, domain) -> None:
    key = uuid.uuid4().hex[:8]
    wf = f"canary-retry-{key}"
    run = _start(fe, domain, "canary-retry", wf, key.encode())
    out = _wait_result(fe, domain, wf, run)
    assert out.startswith(b"succeeded"), out


def probe_concurrent(fe, domain) -> None:
    key = uuid.uuid4().hex[:8]
    wf = f"canary-concurrent-{key}"
    run = _start(fe, domain, "canary-concurrent", wf, key.encode())
    assert _wait_result(fe, domain, wf, run) == b"0,1,2"


def probe_query(fe, domain) -> None:
    wf = f"canary-query-{uuid.uuid4().hex[:8]}"
    _start(fe, domain, "canary-query", wf)
    time.sleep(0.3)  # allow the first decision to settle
    out = fe.query_workflow(
        domain, wf, query_type="status", timeout_s=10.0
    )
    assert out == b"canary-query-alive", out


def probe_visibility(fe, domain) -> None:
    wf = f"canary-vis-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-echo", wf, b"v")
    _wait_result(fe, domain, wf, run)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        n = fe.count_workflow_executions(
            domain, f"WorkflowID = '{wf}' AND CloseStatus = 'COMPLETED'"
        )
        if n == 1:
            return
        time.sleep(0.1)
    raise AssertionError("closed workflow never became visible")


def probe_reset(fe, domain) -> None:
    wf = f"canary-reset-{uuid.uuid4().hex[:8]}"
    run = _start(fe, domain, "canary-echo", wf, b"r")
    _wait_result(fe, domain, wf, run)
    events, _ = fe.get_workflow_execution_history(domain, wf, run)
    completed = [
        e for e in events
        if e.event_type == EventType.DecisionTaskCompleted
    ][0]
    new_run = fe.reset_workflow_execution(
        domain, wf, run, reason="canary",
        decision_finish_event_id=completed.event_id,
    )
    assert _wait_result(fe, domain, wf, new_run) == b"r"


PROBES: Dict[str, Callable] = {
    "echo": probe_echo,
    "signal": probe_signal,
    "timer": probe_timer,
    "retry": probe_retry,
    "concurrent": probe_concurrent,
    "query": probe_query,
    "visibility": probe_visibility,
    "reset": probe_reset,
}
