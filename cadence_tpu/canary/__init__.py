"""Canary: live health-probe workflows.

Reference: canary/ — const.go:64-84 lists the probe set (echo, signal,
timeout, retry, concurrentExec, cron, query, reset, ...); sanity.go:54
fans them out. run via ``python -m cadence_tpu.tools.cli canary``.
"""

from .runner import run_canary

__all__ = ["run_canary"]
