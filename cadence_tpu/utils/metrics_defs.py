"""Per-API metric scope catalog + mechanical instrumentation.

The shape of the reference's scope catalog
(/root/reference/common/metrics/defs.go — ~2k lines of per-operation
scope definitions indexed by service): here the catalog is the
operation lists below, and every listed API gets the standard triple —
``requests`` counter, ``latency`` histogram timer, ``errors`` counter —
recorded under tags (service=..., operation=...).
``instrument_methods`` applies it mechanically to a handler object's
bound methods, mirroring how the reference wraps every Thrift handler
method in a scoped metrics client; since the telemetry plane landed it
ALSO opens a child span per call when (and only when) the calling
thread carries a sampled trace (utils/tracing.py — the unsampled path
is one thread-local read).

The ``*_METRICS`` tuples below are the operator catalog AND a static
contract: the analysis pass ``metrics`` (cadence_tpu/analysis/
metric_decl.py, rule METRIC-UNDECLARED) scans every literal
``.inc``/``.gauge``/``.record`` emission under runtime/, ops/,
matching/ and checkpoint/ and fails the lint gate when a name is
emitted that no catalog declares — the docs here can never silently
trail the code. Per-tuple coverage tests (tests/test_telemetry.py,
tests/test_replication_transport.py) additionally prove the inverse
for the TELEMETRY/DEVICE/REPLICATION families: every declared name is
really emitted somewhere.
"""

from __future__ import annotations

import time
from typing import Iterable

from .metrics import Scope
from . import tracing as _tracing

# --------------------------------------------------------------------------
# Scope catalog (reference: common/metrics/defs.go scope enums per service)
# --------------------------------------------------------------------------

FRONTEND_OPS = (
    "register_domain", "describe_domain", "list_domains", "update_domain",
    "deprecate_domain", "failover_domain",
    "start_workflow_execution", "signal_workflow_execution",
    "signal_with_start_workflow_execution",
    "terminate_workflow_execution", "request_cancel_workflow_execution",
    "reset_workflow_execution",
    "poll_for_decision_task", "poll_for_activity_task",
    "respond_decision_task_completed", "respond_decision_task_failed",
    "respond_activity_task_completed", "respond_activity_task_failed",
    "respond_activity_task_canceled", "record_activity_task_heartbeat",
    "respond_query_task_completed", "query_workflow",
    "get_workflow_execution_history", "describe_workflow_execution",
    "describe_task_list", "reset_sticky_task_list",
    "list_open_workflow_executions", "list_closed_workflow_executions",
    "list_workflow_executions", "scan_workflow_executions",
    "count_workflow_executions", "get_search_attributes",
    "list_archived_workflow_executions", "health",
    "list_task_list_partitions", "get_cluster_info",
)

HISTORY_OPS = (
    "start_workflow_execution", "signal_workflow_execution",
    "signal_with_start_workflow_execution",
    "terminate_workflow_execution", "request_cancel_workflow_execution",
    "reset_workflow_execution", "reset_sticky_task_list",
    "record_decision_task_started", "record_activity_task_started",
    "respond_decision_task_completed", "respond_decision_task_failed",
    "respond_activity_task_completed", "respond_activity_task_failed",
    "respond_activity_task_canceled", "record_activity_task_heartbeat",
    "record_child_execution_completed",
    "record_external_cancel_result", "record_external_signal_result",
    "record_child_execution_started", "record_start_child_execution_failed",
    "get_workflow_execution_history", "describe_workflow_execution",
    "query_workflow", "replicate_events_v2", "get_replication_messages",
    "sync_shard_status",
)

MATCHING_OPS = (
    "add_decision_task", "add_activity_task",
    "poll_for_decision_task", "poll_for_activity_task",
    "query_workflow", "respond_query_task_completed",
    "describe_task_list", "cancel_outstanding_polls",
    "list_task_list_partitions",
)

# queue task-execution metrics are tagged (queue=..., task_type=...);
# task_outstanding gauges in-flight depth, task_held gauges the parked
# (DeferTask/retry) depth — the standby planes' hold depth. Replication
# emits replication_ack_lag (source side, tagged cluster=) plus
# replication_tasks_applied / replication_apply_latency (consumer side).
# Reference: common/metrics/defs.go task-type queue + replication scopes.
QUEUE_METRICS = (
    "task_requests", "task_latency", "task_errors", "task_outstanding",
    "task_held",
)
# Parallel queue executor (runtime/queues/parallel.py), scope tagged
# queue="parallel". parqueue_cycles / parqueue_tasks / parqueue_waves
# count pump cycles, tasks collected, and conflict groups executed;
# parqueue_wave_width records groups-per-cycle (the concurrency the
# matrix actually unlocked) and parqueue_conflict_frac the fraction of
# a cycle's tasks that conflicted into shared groups (1 - waves/tasks);
# parqueue_cycle_latency times one collect→schedule→execute round.
# parqueue_queues gauges registered pumps. The failure plane:
# parqueue_matrix_stale counts a commutativity-matrix artifact rejected
# at construction (version/fingerprint mismatch vs the live footprint
# table) with parqueue_degraded gauging the resulting sequential-only
# mode (1 = degraded — alert on it; the executor WARNS but will not
# resume parallel waves until rebuilt against a fresh artifact), and
# parqueue_stale_skipped counts tasks rejected wave-whole because their
# queue's ack generation moved (rewind/fence) between collect and run.
PARQUEUE_METRICS = (
    "parqueue_cycles", "parqueue_tasks", "parqueue_waves",
    "parqueue_wave_width", "parqueue_conflict_frac",
    "parqueue_cycle_latency", "parqueue_queues",
    "parqueue_matrix_stale", "parqueue_degraded",
    "parqueue_stale_skipped",
)
# Adaptive geo-replication (runtime/replication/transport.py) extends
# the consumer side: replication_lag_events / replication_lag_seconds
# gauge how far the standby's APPLIED STATE trails the source (events
# known outstanding on the link; seconds between the source clock and
# the newest applied event), replication_mode gauges the controller's
# link-wide mode (0 = event shipping, 1 = snapshot shipping) with
# replication_mode_switches counting transitions (hysteresis-damped),
# replication_bytes_shipped (tagged mode=) accounts every transfer,
# replication_snapshots_shipped / replication_snapshot_fallbacks count
# snapshot catch-ups and their event-path fallbacks (torn transfer,
# stale fingerprint, divergent branch), replication_backfill_events
# counts the deferred history bytes a snapshot owed, and
# replication_pump_backoffs counts failed pump cycles entering the
# capped jittered exponential backoff.
REPLICATION_METRICS = (
    "replication_ack_lag", "replication_tasks_applied",
    "replication_apply_latency",
    "replication_lag_events", "replication_lag_seconds",
    "replication_mode", "replication_mode_switches",
    "replication_bytes_shipped",
    "replication_snapshots_shipped", "replication_snapshot_fallbacks",
    "replication_backfill_events", "replication_pump_backoffs",
    # NDC conflict-resolution observability (runtime/replication/ndc.py):
    # branches_forked counts divergence points materialized (a fork at
    # the LCA), conflicts_resolved counts resolutions — the incoming
    # higher-version branch winning a rebuild-and-apply (inline or via
    # the batched drain) or a stale lower-version batch archived onto a
    # non-current branch. The failover drill reports read the counter
    # as "how big was the version-branch storm this failover caused".
    "replication_branches_forked", "replication_conflicts_resolved",
    # continue-as-new chain successors materialized by a catch-up heal
    # (rereplicator.py — the successor's first batch rides the
    # predecessor's task, which snapshot/raw-history catch-ups bypass)
    "replication_chain_heals",
    # dynamic per-link fetch paging (transport.page_size): the emit-page
    # cap last derived from the bandwidth/bytes-per-task EWMAs
    "replication_fetch_page_limit",
)
# chaos/fault-injection plane (testing/faults.py): every injected fault
# increments faults_injected under tags (layer=fault_injection,
# site=..., action=error|latency|torn_write), so a chaos run's blast
# radius is observable in the same registry as the errors it causes —
# the per-manager `<api>.errors.<ExcType>` counters from the metrics
# decorator count injected and real backend failures identically.
FAULT_METRICS = ("faults_injected",)

# checkpointed incremental replay (cadence_tpu/checkpoint/), emitted by
# the state rebuilder under tags (layer=checkpoint): every rebuild_many
# lookup counts exactly one of hit / miss / invalidated (invalidated =
# candidates existed but all failed validation: stale fingerprint,
# capacity mismatch, or NDC divergence before the snapshot), and
# events_replayed_saved accumulates the events a hit skipped — the
# direct measure of the O(depth) → O(new events) conversion.
CHECKPOINT_METRICS = (
    "checkpoint_hit",
    "checkpoint_miss",
    "checkpoint_invalidated",
    "events_replayed_saved",
)

# elastic resharding (runtime/resharding.py), emitted by the coordinator
# under tags (layer=resharding): reshard_epoch gauges the committed
# routing epoch, handoff_ms times each reconfiguration end-to-end,
# checkpoints_shipped counts the snapshots flushed for the new owner,
# and suffix_events_replayed counts the events the new owner actually
# re-ran (total moved events minus events_replayed_saved — the
# "checkpoints, not histories" shipping proof the chaos suite asserts).
RESHARD_METRICS = (
    "reshard_epoch",
    "handoff_ms",
    "reshard_pause_ms",
    "checkpoints_shipped",
    "suffix_events_replayed",
    "reshard_commits",
    "reshard_rollbacks",
)

# history engine workload counters (runtime/engine/engine.py), tagged
# (service=history, shard=...): today just the start rate; grows with
# the serving-path work (METRIC-UNDECLARED keeps this list honest).
ENGINE_METRICS = ("workflow_started",)

# domain failover drills (runtime/replication/failover.py), emitted by
# the coordinator under tags (layer=failover, kind=managed|forced|
# failback, domain=...): domain_failovers counts completed drills,
# failover_handover_ms times each drill end-to-end (histogram),
# failover_unavailability_ms times the flip-start → new-active-observes
# window (the span where neither side safely mints decisions),
# failover_replication_lag_at_promote gauges the events known
# outstanding on the inbound link when ownership flipped (0 for a
# drained managed handover; the dead link's last view for a forced
# promotion), and failover_conflicts_resolved accumulates the NDC
# version-branch resolutions each drill's heal phase caused (the
# registry delta of replication_conflicts_resolved across the drill).
FAILOVER_METRICS = (
    "domain_failovers",
    "failover_handover_ms",
    "failover_unavailability_ms",
    "failover_replication_lag_at_promote",
    "failover_conflicts_resolved",
)

# device-step kernel telemetry (ops/dispatch.py), emitted by the
# dispatcher per staged/replayed batch under tags (layer=device,
# kernel=xla|pallas, mode=hist|lanes|hist_assoc|lanes_assoc):
#
#   device_batches       counter — batches replayed
#   host_stage_seconds   histogram — pack + H2D staging wall time
#   device_step_seconds  histogram — kernel wall time (the run pump
#                        blocks on the result when telemetry is on, so
#                        this is honest device time, not dispatch time)
#   batch_width          histogram — padded batch width per dispatch
#                        (the compiled-executable grid in action)
#   padding_frac         gauge — padded slots ÷ real events of the last
#                        batch (the lane packer's waste)
#   lane_occupancy       gauge — histories per lane of the last
#                        lane-packed batch
#   jit_cache_entries    gauge — total compiled executables across the
#                        replay kernels visible to this dispatcher
#   jit_retraces         counter — cache-size growth observed after a
#                        batch (a retrace storm shows up here first,
#                        without re-running offline profiles)
DEVICE_METRICS = (
    "device_batches",
    "host_stage_seconds",
    "device_step_seconds",
    "batch_width",
    "padding_frac",
    "lane_occupancy",
    "jit_cache_entries",
    "jit_retraces",
)

# continuous-batching serving engine (cadence_tpu/serving/), emitted
# under tags (layer=serving) by the ResidentEngine and
# (layer=serving_harness) by the open-loop load harness:
#
#   serving_admits            counter — workflows seated into lanes
#   serving_admit_cold        counter — seats that cold-replayed the prefix
#   serving_admit_resume      counter — seats rehydrated from a checkpoint
#   serving_admit_queued      counter — admits parked (all lanes busy)
#   serving_admit_failures    counter — seats dropped (unpackable history)
#   serving_appends           counter — Δ suffixes staged
#   serving_append_events     counter — events across staged Δs
#   serving_stale_appends     counter — generation-stamp rejections (a
#                             stale ticket/in-flight step on a recycled
#                             slot — the invariant, observable)
#   serving_gapped_appends    counter — appends refused because events
#                             between the staged tip and the batch
#                             never arrived (bare lanes only; history-
#                             backed lanes record the debt and the
#                             catch-up heals it)
#   serving_ticks             counter — fused device steps run
#   serving_tick_seconds      histogram — per-tick wall time
#   serving_append_width      counter per grid-rounded width tag —
#                             lanes composed per tick (the batch shape)
#   serving_events_replayed   counter — events composed (O(Δ) proof:
#                             ≈ serving_append_events, never O(depth))
#   serving_compose_failures  counter — lanes whose Δ was unreplayable
#                             (lane freed; readmit-from-store recovers)
#   serving_lane_occupancy    gauge — seated lanes ÷ S
#   serving_evictions         counter — lanes flushed + freed
#   serving_recycles          counter — freed slots refilled from the
#                             admission queue
#   serving_flush_failures    counter — eviction flushes that did not
#                             land (readmit degrades to cold replay)
#   serving_resident_hits     counter — reads answered from a lane
#   serving_cold_misses       counter — reads that fell to cold replay
#   serving_cold_read_failures counter — cold reads the serving caps
#                             could not pack/replay (returned None;
#                             the rebuild verbs stay the recovery path)
#   serving_read_seconds      histogram — read wall time
#   serve_decision            histogram — open-loop decision latency
#                             (scheduled arrival → read done; p50/p99
#                             in the bench serve_continuous record)
#   serve_shed                counter — arrivals shed by the admission
#                             token bucket / a failed seat
#   serving_admit_starvation_age_ms histogram — how long a parked
#                             admission waited before the fair refill
#                             seated it (deadline aging bounds the p100:
#                             TestOverloadChaos's no-starvation proof)
#   serving_staleness_ms      histogram — first-dirty → composed per
#                             lane; the tick pump holds its p99 under
#                             the configured staleness bound even for
#                             write-heavy/read-light lanes
#   serving_tick_pump_errors  counter — pump cycles that failed (the
#                             pump logs, backs off capped, keeps going)
SERVING_METRICS = (
    "serving_admits",
    "serving_admit_cold",
    "serving_admit_resume",
    "serving_admit_queued",
    "serving_admit_failures",
    "serving_appends",
    "serving_append_events",
    "serving_stale_appends",
    "serving_gapped_appends",
    "serving_ticks",
    "serving_tick_seconds",
    "serving_append_width",
    "serving_events_replayed",
    "serving_compose_failures",
    "serving_lane_occupancy",
    "serving_evictions",
    "serving_recycles",
    "serving_flush_failures",
    "serving_resident_hits",
    "serving_cold_misses",
    "serving_cold_read_failures",
    "serving_read_seconds",
    "serve_decision",
    "serve_shed",
    "serving_admit_starvation_age_ms",
    "serving_staleness_ms",
    "serving_tick_pump_errors",
)

# overload control plane (ISSUE 15), emitted by the layers that shed
# or give up: frontend_requests_shed counts frontend rate-limit
# rejections under tags (service=frontend, domain=...) — each carries
# a retry-after hint on the ServiceBusyError; retry_budget_exhausted
# counts the moments a client's success-refilled retry budget denied a
# ServiceBusy re-offer (layer=client) or the open-loop harness's
# simulated client did the same (layer=serving_harness) — the
# retry-storm breaker firing, i.e. load that was offered once and NOT
# multiplied.
OVERLOAD_METRICS = (
    "frontend_requests_shed",
    "retry_budget_exhausted",
)

# tracing plane self-telemetry (utils/tracing.py + utils/metrics.py),
# tagged (layer=telemetry): traces_sampled counts sampled roots,
# spans_recorded/spans_dropped account the flight-recorder ring buffer
# (dropped = evicted by capacity before export), and
# metrics_dropped_series counts emissions the registry's max-series cap
# collapsed into the overflow sink (a tag-cardinality explosion is
# observable instead of an OOM).
TELEMETRY_METRICS = (
    "traces_sampled",
    "spans_recorded",
    "spans_dropped",
    "metrics_dropped_series",
)

# capacity autopilot (runtime/autopilot.py), tagged (layer=autopilot).
# The epoch loop: autopilot_epochs/autopilot_epoch_seconds count and
# time every sense→decide→actuate pass; autopilot_skipped_epochs are
# passes that sensed but did not actuate (paused / not the elected
# actuator / frozen); autopilot_errors are passes that raised (the loop
# backs off and keeps going). Sensing: autopilot_sensed_p99_ms /
# autopilot_sensed_shed_frac are the raw interval readings,
# autopilot_demand_rps the smoothed OFFERED rate (admitted + shed —
# shed traffic is demand) the rate plane tracks, autopilot_pressure
# the EWMA'd p99/target (escalated by shed/target only once latency
# is at target — shed alone must not spiral the gate) the gate sees,
# autopilot_overload_engaged the gate state (1 = overloaded).
# Rate plane: autopilot_rate_retunes counts setpoint changes,
# autopilot_rate_rps (key=...) gauges each current setpoint,
# autopilot_cooldown_skips counts actuations suppressed by a cooldown
# or reshard backoff. Topology plane: autopilot_reshard_plans counts
# committed split/merge plans, autopilot_reshard_failures aborted ones
# (each engages the proposal backoff — never a hot retry). Guardrail:
# autopilot_guardrail_freezes counts do-no-harm trips,
# autopilot_reverts the rates restored to last-known-good,
# autopilot_frozen the freeze state gauge. Operator plane:
# autopilot_pauses/autopilot_resumes count the admin verbs,
# autopilot_paused gauges the current pause state.
AUTOPILOT_METRICS = (
    "autopilot_epochs",
    "autopilot_epoch_seconds",
    "autopilot_skipped_epochs",
    "autopilot_errors",
    "autopilot_sensed_p99_ms",
    "autopilot_sensed_shed_frac",
    "autopilot_demand_rps",
    "autopilot_pressure",
    "autopilot_overload_engaged",
    "autopilot_rate_retunes",
    "autopilot_rate_rps",
    "autopilot_cooldown_skips",
    "autopilot_reshard_plans",
    "autopilot_reshard_failures",
    "autopilot_guardrail_freezes",
    "autopilot_reverts",
    "autopilot_frozen",
    "autopilot_pauses",
    "autopilot_resumes",
    "autopilot_paused",
)

# the standard per-operation triple
REQUESTS = "requests"
LATENCY = "latency"
ERRORS = "errors"


def raw_method(fn):
    """The pre-instrumentation bound method (identity if unwrapped).
    Internal delegations use this so one RPC never phantom-counts as
    several; unwraps through layered wrapping."""
    while hasattr(fn, "__wrapped__"):
        fn = fn.__wrapped__
    return fn


def instrument_methods(
    obj, scope: Scope, operations: Iterable[str],
) -> None:
    """Wrap each existing bound method in the standard triple plus a
    trace span. Missing names are skipped so the catalog can list the
    full API surface while handlers grow into it.

    The span piggybacks on the same mechanical wrapping: when the
    calling thread carries a sampled trace (utils/tracing.py), the call
    records a child span named after the operation under the scope's
    service tag — frontend → history → matching hops all run in the
    caller's thread, so this single hook links the whole in-process
    chain. With no active trace, ``TRACER.span`` returns the shared
    no-op after one thread-local read — the unsampled cost the bench
    ``telemetry_overhead`` guard pins at ≤3%."""
    service = getattr(scope, "_tags", {}).get("service", "")
    tracer = _tracing.TRACER
    for op in operations:
        fn = getattr(obj, op, None)
        if fn is None or not callable(fn):
            continue
        op_scope = scope.tagged(operation=op)

        def wrapped(*args, __fn=fn, __scope=op_scope, __op=op,
                    __tls=tracer._tls, **kwargs):
            __scope.inc(REQUESTS)
            t0 = time.perf_counter()
            if getattr(__tls, "span", None) is None:
                # unsampled fast path: one thread-local read, no span
                # machinery at all (the bench telemetry_overhead guard
                # pins this branch at ≤3% vs the metrics-only wrapper)
                try:
                    return __fn(*args, **kwargs)
                except Exception:
                    __scope.inc(ERRORS)
                    raise
                finally:
                    __scope.record(LATENCY, time.perf_counter() - t0)
            with tracer.span(__op, service=service):
                try:
                    return __fn(*args, **kwargs)
                except Exception:
                    __scope.inc(ERRORS)
                    raise
                finally:
                    __scope.record(LATENCY, time.perf_counter() - t0)

        wrapped.__name__ = op
        wrapped.__wrapped__ = fn
        setattr(obj, op, wrapped)
