"""Lightweight metrics registry: counters, gauges, timers, scopes.

The shape of the reference's tally-based metrics layer
(/root/reference/common/metrics/: Scope with Counter/Timer/Gauge, tagged
sub-scopes per service/operation/domain) without an external sink:
in-process aggregation with an introspection API, plus an optional
snapshot dump. Every runtime layer takes a Scope so per-API and
per-store latencies are observable in tests and benchmarks."""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional, Tuple

TagTuple = Tuple[Tuple[str, str], ...]


def _tags_key(tags: Optional[Dict[str, str]]) -> TagTuple:
    return tuple(sorted((tags or {}).items()))


class Registry:
    """Process-wide metric store; thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, TagTuple], int] = defaultdict(int)
        self._gauges: Dict[Tuple[str, TagTuple], float] = {}
        # timers: (count, total_s, max_s)
        self._timers: Dict[Tuple[str, TagTuple], Tuple[int, float, float]] = (
            defaultdict(lambda: (0, 0.0, 0.0))
        )

    def inc(self, name: str, tags: TagTuple, delta: int = 1) -> None:
        with self._lock:
            self._counters[(name, tags)] += delta

    def gauge(self, name: str, tags: TagTuple, value: float) -> None:
        with self._lock:
            self._gauges[(name, tags)] = value

    def record(self, name: str, tags: TagTuple, seconds: float) -> None:
        with self._lock:
            n, total, mx = self._timers[(name, tags)]
            self._timers[(name, tags)] = (n + 1, total + seconds, max(mx, seconds))

    # -- introspection -------------------------------------------------

    def counter_value(self, name: str, tags: Optional[Dict[str, str]] = None) -> int:
        with self._lock:
            if tags is not None:
                return self._counters.get((name, _tags_key(tags)), 0)
            return sum(v for (n, _), v in self._counters.items() if n == name)

    def timer_stats(
        self, name: str, tags: Optional[Dict[str, str]] = None
    ) -> Tuple[int, float, float]:
        with self._lock:
            if tags is not None:
                return self._timers.get((name, _tags_key(tags)), (0, 0.0, 0.0))
            agg = (0, 0.0, 0.0)
            for (n, _), (c, t, m) in self._timers.items():
                if n == name:
                    agg = (agg[0] + c, agg[1] + t, max(agg[2], m))
            return agg

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                "counters": {
                    f"{n}{dict(t)}": v for (n, t), v in self._counters.items()
                },
                "gauges": {
                    f"{n}{dict(t)}": v for (n, t), v in self._gauges.items()
                },
                "timers": {
                    f"{n}{dict(t)}": {"count": c, "total_s": ts, "max_s": m}
                    for (n, t), (c, ts, m) in self._timers.items()
                },
            }


class Timer:
    def __init__(self, registry: Registry, name: str, tags: TagTuple) -> None:
        self._registry, self._name, self._tags = registry, name, tags
        self._start = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._registry.record(
            self._name, self._tags, time.perf_counter() - self._start
        )


class Scope:
    """A tag context; sub-scopes add tags (tally-style)."""

    def __init__(
        self, registry: Optional[Registry] = None,
        tags: Optional[Dict[str, str]] = None,
    ) -> None:
        self.registry = registry or Registry()
        self._tags = dict(tags or {})
        self._key = _tags_key(self._tags)

    def tagged(self, **tags: str) -> "Scope":
        merged = dict(self._tags)
        merged.update(tags)
        return Scope(self.registry, merged)

    def inc(self, name: str, delta: int = 1) -> None:
        self.registry.inc(name, self._key, delta)

    def gauge(self, name: str, value: float) -> None:
        self.registry.gauge(name, self._key, value)

    def timer(self, name: str) -> Timer:
        return Timer(self.registry, name, self._key)

    def record(self, name: str, seconds: float) -> None:
        self.registry.record(name, self._key, seconds)


NOOP = Scope()  # shared default; fine because Registry is thread-safe
