"""Lightweight metrics registry: counters, gauges, histogram timers.

The shape of the reference's tally-based metrics layer
(/root/reference/common/metrics/: Scope with Counter/Timer/Gauge, tagged
sub-scopes per service/operation/domain) without an external sink:
in-process aggregation with an introspection API, plus an optional
snapshot dump. Every runtime layer takes a Scope so per-API and
per-store latencies are observable in tests and benchmarks.

Timers are fixed-boundary exponential-bucket histograms (base 1 µs,
doubling per bucket, 64 buckets ≈ up to 2^63 µs): bounded memory per
series, one integer increment per record under the registry lock, and
real percentiles — ``timer_stats`` returns a 3-tuple-compatible
``TimerStats`` carrying ``p50``/``p95``/``p99``/``avg`` alongside the
legacy ``(count, total_s, max_s)`` unpacking, and ``quantile(q)`` is
exact-to-a-bucket (linear interpolation inside the winning bucket,
clamped to the observed max). The previous ``(count, total, max)``
tuple could not answer "p99 decision latency under sustained QPS" at
all; every existing ``Scope.timer`` call site upgrades for free.

Cardinality is bounded: a Registry admits at most ``max_series``
distinct (name, tags) series per kind; past the cap, new series
collapse into an ``overflow="true"`` sink series per metric name and
``metrics_dropped_series`` counts the suppressed writes — a tag
explosion (e.g. a runaway per-workflow tag) degrades percentile
attribution, never process memory.
"""

from __future__ import annotations

import math
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from cadence_tpu.utils.locks import make_guarded, make_lock

TagTuple = Tuple[Tuple[str, str], ...]

# histogram geometry: bucket i holds values in (2^(i-1), 2^i] µs,
# bucket 0 holds <= 1 µs. 64 buckets cover any representable latency.
_BUCKET0_S = 1e-6
_NBUCKETS = 64

# where writes land once the series cap is hit (per metric name)
OVERFLOW_TAGS: TagTuple = (("overflow", "true"),)
DROPPED_SERIES = "metrics_dropped_series"

_DEFAULT_MAX_SERIES = 8192


def _tags_key(tags: Optional[Dict[str, str]]) -> TagTuple:
    return tuple(sorted((tags or {}).items()))


def _bucket_index(seconds: float) -> int:
    if seconds <= _BUCKET0_S:
        return 0
    # frexp is ~3x cheaper than log2: v = m * 2^e with m in [0.5, 1.0).
    # An exact power of two comes back as m == 0.5 and belongs to the
    # LOWER bucket (bounds are (2^(i-1), 2^i], upper-inclusive)
    m, e = math.frexp(seconds / _BUCKET0_S)
    if m == 0.5:
        e -= 1
    return e if e < _NBUCKETS else _NBUCKETS - 1


def bucket_bounds(index: int) -> Tuple[float, float]:
    """(lo_s, hi_s] covered by bucket ``index`` (diagnostics/tests)."""
    hi = _BUCKET0_S * (2.0 ** index)
    lo = 0.0 if index == 0 else hi / 2.0
    return lo, hi


class Histogram:
    """One series' distribution; NOT thread-safe (the registry lock
    owns every mutation)."""

    __slots__ = ("counts", "count", "total", "max")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * _NBUCKETS
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        if seconds > self.max:
            self.max = seconds
        self.counts[_bucket_index(seconds)] += 1

    def merge(self, other: "Histogram") -> None:
        self.count += other.count
        self.total += other.total
        if other.max > self.max:
            self.max = other.max
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]: walk to the bucket holding
        the target rank, interpolate linearly inside it, clamp to the
        observed max (the top bucket's upper bound is a geometry
        artifact, not an observation)."""
        if self.count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            cum += c
            if cum >= target:
                lo, hi = bucket_bounds(i)
                hi = min(hi, self.max)
                lo = min(lo, hi)
                frac = (target - (cum - c)) / c
                return lo + (hi - lo) * frac
        return self.max


class TimerStats(tuple):
    """``(count, total_s, max_s)`` — unpacks exactly like the legacy
    timer tuple — with the histogram-backed extras as attributes:
    ``p50``/``p95``/``p99``/``avg`` (seconds) and ``quantile(q)``."""

    def __new__(cls, hist: Optional[Histogram] = None):
        h = hist if hist is not None else Histogram()
        self = super().__new__(cls, (h.count, h.total, h.max))
        self._hist = h
        return self

    @property
    def count(self) -> int:
        return self[0]

    @property
    def total_s(self) -> float:
        return self[1]

    @property
    def max_s(self) -> float:
        return self[2]

    @property
    def avg(self) -> float:
        return self[1] / self[0] if self[0] else 0.0

    def quantile(self, q: float) -> float:
        return self._hist.quantile(q)

    @property
    def p50(self) -> float:
        return self._hist.quantile(0.50)

    @property
    def p95(self) -> float:
        return self._hist.quantile(0.95)

    @property
    def p99(self) -> float:
        return self._hist.quantile(0.99)


class Registry:
    """Process-wide metric store; thread-safe, cardinality-capped."""

    def __init__(self, max_series: int = _DEFAULT_MAX_SERIES) -> None:
        self._lock = make_lock("Registry._lock")
        self._max_series = max(int(max_series), 1)
        self._series = 0
        self._counters: Dict[Tuple[str, TagTuple], int] = make_guarded(
            defaultdict(int), "Registry._counters", self._lock
        )
        self._gauges: Dict[Tuple[str, TagTuple], float] = make_guarded(
            {}, "Registry._gauges", self._lock
        )
        self._timers: Dict[Tuple[str, TagTuple], Histogram] = make_guarded(
            {}, "Registry._timers", self._lock
        )

    def _admit(self, table, name: str, tags: TagTuple):
        """Series admission under the lock: an existing key passes; a
        new key past the cap collapses into the per-name overflow sink
        and bumps the dropped-writes counter."""
        key = (name, tags)
        if key in table:
            return key
        if self._series >= self._max_series and tags != OVERFLOW_TAGS:
            self._counters[(DROPPED_SERIES, ())] += 1
            # the sink series itself is admitted uncounted: names are
            # code-bounded, tags are what explode
            return (name, OVERFLOW_TAGS)
        if tags != OVERFLOW_TAGS:
            self._series += 1
        return key

    def inc(self, name: str, tags: TagTuple, delta: int = 1) -> None:
        with self._lock:
            self._counters[self._admit(self._counters, name, tags)] += delta

    def gauge(self, name: str, tags: TagTuple, value: float) -> None:
        with self._lock:
            self._gauges[self._admit(self._gauges, name, tags)] = value

    def record(self, name: str, tags: TagTuple, seconds: float) -> None:
        with self._lock:
            key = self._admit(self._timers, name, tags)
            hist = self._timers.get(key)
            if hist is None:
                hist = self._timers[key] = Histogram()
            hist.record(seconds)

    # -- introspection -------------------------------------------------

    def counter_value(self, name: str, tags: Optional[Dict[str, str]] = None) -> int:
        with self._lock:
            if tags is not None:
                return self._counters.get((name, _tags_key(tags)), 0)
            return sum(v for (n, _), v in self._counters.items() if n == name)

    def timer_stats(
        self, name: str, tags: Optional[Dict[str, str]] = None
    ) -> TimerStats:
        """Stats for one series (tags given) or the merged distribution
        across every series of ``name``. Returns a ``TimerStats``:
        unpacks as the legacy ``(count, total_s, max_s)`` and carries
        ``p50``/``p95``/``p99``/``avg``/``quantile(q)``."""
        agg = Histogram()
        with self._lock:
            if tags is not None:
                hist = self._timers.get((name, _tags_key(tags)))
                if hist is not None:
                    agg.merge(hist)
            else:
                for (n, _), hist in self._timers.items():
                    if n == name:
                        agg.merge(hist)
        return TimerStats(agg)

    def timer_quantile(
        self, name: str, q: float, tags: Optional[Dict[str, str]] = None
    ) -> float:
        """Histogram-backed quantile in seconds (0.0 when unobserved)."""
        return self.timer_stats(name, tags).quantile(q)

    def series_count(self) -> int:
        with self._lock:
            return self._series

    def snapshot(self) -> Dict[str, Dict]:
        # copy raw state under the lock, then do the expensive part —
        # per-series quantile walks and key formatting — OUTSIDE it: a
        # debug snapshot over thousands of series must not stall every
        # serving thread's inc()/record() on the shared registry
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers_raw = [
                (n, t, h.count, h.total, h.max, list(h.counts))
                for (n, t), h in self._timers.items()
            ]
        timers = {}
        for n, t, count, total, mx, counts in timers_raw:
            h = Histogram()
            h.count, h.total, h.max, h.counts = count, total, mx, counts
            timers[f"{n}{dict(t)}"] = {
                "count": count, "total_s": total, "max_s": mx,
                "p50_s": h.quantile(0.50), "p99_s": h.quantile(0.99),
            }
        return {
            "counters": {
                f"{n}{dict(t)}": v for (n, t), v in counters.items()
            },
            "gauges": {
                f"{n}{dict(t)}": v for (n, t), v in gauges.items()
            },
            "timers": timers,
        }


class WindowReading:
    """One interval's worth of samples: the difference between two
    consecutive ``Window.advance()`` snapshots. Counters are deltas,
    timers are delta histograms (real interval percentiles), gauges are
    the point-in-time value at the closing snapshot."""

    def __init__(
        self,
        counters: Dict[Tuple[str, TagTuple], int],
        gauges: Dict[Tuple[str, TagTuple], float],
        timers: Dict[Tuple[str, TagTuple], Histogram],
        span_s: float,
    ) -> None:
        self._counters = counters
        self._gauges = gauges
        self._timers = timers
        self.span_s = span_s

    def counter(self, name: str, tags: Optional[Dict[str, str]] = None) -> int:
        if tags is not None:
            return self._counters.get((name, _tags_key(tags)), 0)
        return sum(v for (n, _), v in self._counters.items() if n == name)

    def gauge(
        self, name: str, tags: Optional[Dict[str, str]] = None,
        default: float = 0.0,
    ) -> float:
        if tags is not None:
            return self._gauges.get((name, _tags_key(tags)), default)
        vals = [v for (n, _), v in self._gauges.items() if n == name]
        return max(vals) if vals else default

    def timer_stats(
        self, name: str, tags: Optional[Dict[str, str]] = None,
        where: Optional[Callable[[TagTuple], bool]] = None,
    ) -> TimerStats:
        """Interval stats for ``name``. With ``tags``, one exact series;
        otherwise all series merged — optionally filtered by ``where``,
        a predicate over each series' tag tuple (lets a consumer merge
        "every series except …" without touching internals)."""
        agg = Histogram()
        if tags is not None:
            hist = self._timers.get((name, _tags_key(tags)))
            if hist is not None:
                agg.merge(hist)
        else:
            for (n, t), hist in self._timers.items():
                if n == name and (where is None or where(t)):
                    agg.merge(hist)
        return TimerStats(agg)

    def timer_tags(self, name: str) -> List[TagTuple]:
        """Tag tuples of every series of ``name`` active this interval."""
        return [t for (n, t), h in self._timers.items() if n == name and h.count]


class Window:
    """Interval-delta view over a cumulative ``Registry``.

    Registry histograms accumulate since process start — useless for
    control ("what is p99 *now*?"). A ``Window`` snapshots raw bucket
    counts on every ``advance()`` and returns the difference as a
    ``WindowReading``: exactly the samples recorded between the two
    snapshots, with real interval percentiles. One Window per consumer;
    advancing one never perturbs another (or the registry itself)."""

    def __init__(self, registry: Registry) -> None:
        self.registry = registry
        self._prev_counters: Dict[Tuple[str, TagTuple], int] = {}
        self._prev_timers: Dict[
            Tuple[str, TagTuple], Tuple[int, float, float, List[int]]
        ] = {}
        self._prev_at = time.monotonic()

    def advance(self) -> WindowReading:
        reg = self.registry
        with reg._lock:
            counters = dict(reg._counters)
            gauges = dict(reg._gauges)
            timers_raw = {
                key: (h.count, h.total, h.max, list(h.counts))
                for key, h in reg._timers.items()
            }
        now = time.monotonic()
        span = max(now - self._prev_at, 0.0)

        counter_deltas = {
            key: v - self._prev_counters.get(key, 0)
            for key, v in counters.items()
        }
        timer_deltas: Dict[Tuple[str, TagTuple], Histogram] = {}
        for key, (count, total, mx, buckets) in timers_raw.items():
            pcount, ptotal, _pmx, pbuckets = self._prev_timers.get(
                key, (0, 0.0, 0.0, None)
            )
            h = Histogram()
            h.count = count - pcount
            h.total = total - ptotal
            if pbuckets is None:
                h.counts = list(buckets)
            else:
                h.counts = [c - p for c, p in zip(buckets, pbuckets)]
            # the cumulative max may predate this interval; clamp to the
            # upper bound of the highest bucket that saw a delta sample
            # (never above the all-time max)
            top = 0.0
            for i in range(_NBUCKETS - 1, -1, -1):
                if h.counts[i]:
                    top = bucket_bounds(i)[1]
                    break
            h.max = min(mx, top)
            timer_deltas[key] = h

        self._prev_counters = counters
        self._prev_timers = timers_raw
        self._prev_at = now
        return WindowReading(counter_deltas, gauges, timer_deltas, span)


class Timer:
    def __init__(self, registry: Registry, name: str, tags: TagTuple) -> None:
        self._registry, self._name, self._tags = registry, name, tags
        self._start = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._registry.record(
            self._name, self._tags, time.perf_counter() - self._start
        )


class Scope:
    """A tag context; sub-scopes add tags (tally-style)."""

    def __init__(
        self, registry: Optional[Registry] = None,
        tags: Optional[Dict[str, str]] = None,
    ) -> None:
        self.registry = registry or Registry()
        self._tags = dict(tags or {})
        self._key = _tags_key(self._tags)

    def tagged(self, **tags: str) -> "Scope":
        merged = dict(self._tags)
        merged.update(tags)
        return Scope(self.registry, merged)

    def inc(self, name: str, delta: int = 1) -> None:
        self.registry.inc(name, self._key, delta)

    def gauge(self, name: str, value: float) -> None:
        self.registry.gauge(name, self._key, value)

    def timer(self, name: str) -> Timer:
        return Timer(self.registry, name, self._key)

    def record(self, name: str, seconds: float) -> None:
        self.registry.record(name, self._key, seconds)


NOOP = Scope()  # shared default; fine because Registry is thread-safe
