"""Shared utilities: hashing, clock, backoff, config, metrics, logging."""
