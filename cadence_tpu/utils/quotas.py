"""Token-bucket rate limiting (reference: common/quotas/, common/tokenbucket/).

A multi-policy limiter: a global RPS cap plus per-domain caps, the shape
the frontend and persistence layers apply
(/root/reference/common/quotas/ratelimiter.go). The overload control
plane (ISSUE 15) extends it beyond the frontend: the history and
matching engines consult the same limiter shape and shed with a
retryable ``ServiceBusyError`` carrying a ``retry_after_s`` hint, and
clients pace their retries through a ``RetryBudget`` — a token bucket
refilled by SUCCESSES, so rejected work backs off instead of
multiplying the overload (the retry-storm amplifier)."""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional


class TokenBucket:
    def __init__(
        self,
        rps: float,
        burst: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.rps = float(rps)
        # remember whether the caller sized the burst: a later
        # set_rate(rps) must not silently clobber an explicit burst
        # back to int(rps)
        self._explicit_burst = burst is not None
        self.burst = burst if burst is not None else max(1, int(rps))
        self._tokens = float(self.burst)
        self._last = clock()
        self._clock = clock
        self._lock = threading.Lock()

    def set_rate(self, rps: float, burst: Optional[int] = None) -> None:
        """Live rate change. A caller-supplied burst (here or at
        construction) is preserved; only a derived burst re-derives."""
        with self._lock:
            self.rps = float(rps)
            if burst is not None:
                self._explicit_burst = True
                self.burst = int(burst)
            elif not self._explicit_burst:
                self.burst = max(1, int(rps))
            self._tokens = min(self._tokens, float(self.burst))

    def allow(self, n: int = 1) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rps
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after_s(self, n: int = 1) -> float:
        """Seconds until ``n`` tokens accrue at the current rate — the
        shed response's retry-after hint. 0.0 when tokens are already
        available (or the bucket cannot refill: rps <= 0 hints one
        second rather than infinity)."""
        with self._lock:
            now = self._clock()
            tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rps
            )
            if tokens >= n:
                return 0.0
            if self.rps <= 0:
                return 1.0
            return (n - tokens) / self.rps


class MultiStageRateLimiter:
    """Global + per-domain token buckets; both must admit the request.

    The per-domain table is BOUNDED (``max_domains``, LRU-evicted): a
    churn of short-lived domain names — the overload shape a busy
    multi-tenant frontend actually sees — can no longer grow the bucket
    map without bound. An evicted domain that returns simply mints a
    fresh full bucket (strictly more permissive for one burst — safe)."""

    def __init__(
        self,
        global_rps: float,
        domain_rps: Callable[[str], float],
        clock: Callable[[], float] = time.monotonic,
        max_domains: int = 1024,
        global_burst: Optional[int] = None,
    ) -> None:
        if max_domains < 1:
            raise ValueError("quotas: max_domains must be >= 1")
        self._global = TokenBucket(
            global_rps, burst=global_burst, clock=clock
        )
        self._domain_rps = domain_rps
        self._domains: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._max_domains = int(max_domains)
        self._clock = clock
        self._lock = threading.Lock()

    def _domain_bucket(self, domain: str) -> TokenBucket:
        rps = self._domain_rps(domain)
        with self._lock:
            bucket = self._domains.get(domain)
            if bucket is None:
                bucket = TokenBucket(rps, clock=self._clock)
                self._domains[domain] = bucket
                while len(self._domains) > self._max_domains:
                    self._domains.popitem(last=False)
            else:
                self._domains.move_to_end(domain)
                if bucket.rps != rps:
                    # dynamic-config changes take effect live
                    bucket.set_rate(rps)
        return bucket

    def set_global_rate(self, rps: float) -> None:
        """Live retune of the GLOBAL stage (the autopilot's history/
        matching rps actuator). Per-domain stages already follow their
        ``domain_rps`` callable per call; the global bucket is sized
        once at construction, so a closed-loop controller needs this
        explicit hook."""
        self._global.set_rate(rps)

    @property
    def global_rps(self) -> float:
        return self._global.rps

    def allow(self, domain: str = "") -> bool:
        # DOMAIN bucket first (reference multiStageRateLimiter): a
        # throttled domain must not drain the global budget and starve
        # compliant domains
        if domain:
            if not self._domain_bucket(domain).allow():
                return False
        return self._global.allow()

    def retry_after_s(self, domain: str = "") -> float:
        """The shed hint: the longer of the domain's and the global
        bucket's refill horizon."""
        hint = self._global.retry_after_s()
        if domain:
            hint = max(hint, self._domain_bucket(domain).retry_after_s())
        return hint

    def domain_count(self) -> int:
        with self._lock:
            return len(self._domains)


class RetryBudget:
    """Success-refilled retry pacing (the retry-storm breaker).

    Every SUCCESS deposits ``ratio`` retry tokens (capped at ``cap``);
    every retry withdraws one. Under overload, successes dry up, the
    budget drains, and rejected work stops re-offering itself — total
    offered load converges to admitted load × (1 + ratio) instead of
    amplifying. ``initial`` seeds the bucket so cold clients can retry
    transient blips before their first success."""

    def __init__(
        self, ratio: float = 0.1, cap: float = 8.0, initial: float = 4.0,
    ) -> None:
        if ratio < 0 or cap <= 0:
            raise ValueError("retry budget: ratio >= 0, cap > 0 required")
        self.ratio = float(ratio)
        self.cap = float(cap)
        self._tokens = min(float(initial), self.cap)
        self._lock = threading.Lock()

    def record_success(self) -> None:
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def can_retry(self) -> bool:
        """Withdraw one retry token; False = the budget is exhausted
        and the caller must surface the error instead of re-offering."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def tokens(self) -> float:
        with self._lock:
            return self._tokens
