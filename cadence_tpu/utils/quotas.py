"""Token-bucket rate limiting (reference: common/quotas/, common/tokenbucket/).

A multi-policy limiter: a global RPS cap plus per-domain caps, the shape
the frontend and persistence layers apply
(/root/reference/common/quotas/ratelimiter.go)."""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class TokenBucket:
    def __init__(
        self,
        rps: float,
        burst: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.rps = float(rps)
        self.burst = burst if burst is not None else max(1, int(rps))
        self._tokens = float(self.burst)
        self._last = clock()
        self._clock = clock
        self._lock = threading.Lock()

    def set_rate(self, rps: float) -> None:
        with self._lock:
            self.rps = float(rps)
            self.burst = max(1, int(rps))
            self._tokens = min(self._tokens, float(self.burst))

    def allow(self, n: int = 1) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rps
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class MultiStageRateLimiter:
    """Global + per-domain token buckets; both must admit the request."""

    def __init__(
        self,
        global_rps: float,
        domain_rps: Callable[[str], float],
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._global = TokenBucket(global_rps, clock=clock)
        self._domain_rps = domain_rps
        self._domains: Dict[str, TokenBucket] = {}
        self._clock = clock
        self._lock = threading.Lock()

    def allow(self, domain: str = "") -> bool:
        # DOMAIN bucket first (reference multiStageRateLimiter): a
        # throttled domain must not drain the global budget and starve
        # compliant domains
        if domain:
            rps = self._domain_rps(domain)
            with self._lock:
                bucket = self._domains.get(domain)
                if bucket is None:
                    bucket = TokenBucket(rps, clock=self._clock)
                    self._domains[domain] = bucket
                elif bucket.rps != rps:
                    # dynamic-config changes take effect live
                    bucket.set_rate(rps)
            if not bucket.allow():
                return False
        return self._global.allow()
