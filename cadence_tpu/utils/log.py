"""Structured logging with typed tag vocabulary.

The reference wraps zap with ~1k LoC of typed tags
(/root/reference/common/log/tag/). Here: stdlib logging with a tag dict
carried by child loggers, rendered as key=value pairs — the same
grep-able discipline without the ceremony."""

from __future__ import annotations

import logging
import sys
from typing import Any, Dict, Optional

_FORMAT = "%(asctime)s %(levelname)s %(name)s %(message)s"
_configured = False


def _ensure_configured() -> None:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root = logging.getLogger("cadence_tpu")
        if not root.handlers:
            root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True


class Logger:
    def __init__(self, name: str = "cadence_tpu", tags: Optional[Dict[str, Any]] = None):
        _ensure_configured()
        self._log = logging.getLogger(name)
        self._tags = dict(tags or {})

    def with_tags(self, **tags: Any) -> "Logger":
        merged = dict(self._tags)
        merged.update(tags)
        return Logger(self._log.name, merged)

    def _fmt(self, msg: str, tags: Dict[str, Any]) -> str:
        merged = dict(self._tags)
        merged.update(tags)
        if merged:
            kv = " ".join(f"{k}={v}" for k, v in merged.items())
            return f"{msg} | {kv}"
        return msg

    def debug(self, msg: str, **tags: Any) -> None:
        self._log.debug(self._fmt(msg, tags))

    def info(self, msg: str, **tags: Any) -> None:
        self._log.info(self._fmt(msg, tags))

    def warn(self, msg: str, **tags: Any) -> None:
        self._log.warning(self._fmt(msg, tags))

    def error(self, msg: str, **tags: Any) -> None:
        self._log.error(self._fmt(msg, tags))

    def exception(self, msg: str, **tags: Any) -> None:
        """error + current exception traceback."""
        self._log.exception(self._fmt(msg, tags))


def get_logger(name: str = "cadence_tpu", **tags: Any) -> Logger:
    return Logger(name, tags)
