"""Dynamic config: hot-reloadable typed keys with constrained overrides.

The reference's dynamicconfig (/root/reference/common/service/
dynamicconfig/: 172 keys, file-watched YAML, per-domain / per-tasklist
filtered overrides) reduced to its essential contract:

  * a ``Client`` answers (key, filters) -> value;
  * typed property getters bind (client, key, default) into callables the
    runtime stores once and calls per use — so live file edits change
    behavior without restarts;
  * filters select the most specific matching override
    (domain+tasklist > domain > tasklist > unfiltered).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

# filter attribute names (reference: dynamicconfig/constants.go filters)
DOMAIN = "domainName"
TASKLIST = "taskListName"
SHARD_ID = "shardID"


class Client:
    def get_value(self, key: str, filters: Dict[str, Any]) -> Optional[Any]:
        raise NotImplementedError


class InMemoryClient(Client):
    """Programmatic overrides — the test fixture and the autopilot's
    override plane."""

    def __init__(self) -> None:
        self._values: Dict[str, List[Tuple[Dict[str, Any], Any]]] = {}
        self._lock = threading.Lock()

    def set_value(
        self, key: str, value: Any, filters: Optional[Dict[str, Any]] = None
    ) -> None:
        """Set an override; an entry with EQUAL filters is replaced in
        place, so a controller retuning the same key every epoch stays
        O(1) per key instead of growing the entry list unboundedly (and
        `_best_match` never sees the stale value)."""
        fdict = dict(filters or {})
        with self._lock:
            entries = self._values.setdefault(key, [])
            for i, (entry_filters, _) in enumerate(entries):
                if entry_filters == fdict:
                    entries[i] = (fdict, value)
                    return
            entries.append((fdict, value))

    def remove_value(
        self, key: str, filters: Optional[Dict[str, Any]] = None
    ) -> bool:
        """Remove the override with EXACTLY these filters (None/{} means
        the unfiltered entry). Returns True if an entry was removed."""
        fdict = dict(filters or {})
        with self._lock:
            entries = self._values.get(key)
            if not entries:
                return False
            for i, (entry_filters, _) in enumerate(entries):
                if entry_filters == fdict:
                    del entries[i]
                    if not entries:
                        del self._values[key]
                    return True
        return False

    def get_value(self, key: str, filters: Dict[str, Any]) -> Optional[Any]:
        with self._lock:
            entries = list(self._values.get(key, ()))
        return _best_match(entries, filters)


class FileBasedClient(Client):
    """JSON file polled for changes (reference: fileBasedClient.go).

    File format: {key: [{"filters": {...}, "value": ...}, ...], ...}
    """

    def __init__(self, path: str, poll_interval_s: float = 5.0) -> None:
        self.path = path
        self.poll_interval_s = poll_interval_s
        self._mtime = 0.0
        self._last_check = 0.0
        self._values: Dict[str, List[Tuple[Dict[str, Any], Any]]] = {}
        self._lock = threading.Lock()
        self._load()

    def _load(self) -> None:
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return
        if mtime == self._mtime:
            return
        try:
            with open(self.path) as f:
                raw = json.load(f)
            values = {
                key: [
                    (dict(e.get("filters", {})), e["value"])
                    for e in entries
                ]
                for key, entries in raw.items()
            }
        except Exception:
            # malformed / partially-written file: keep serving the last
            # good snapshot (reference fileBasedClient behavior)
            from cadence_tpu.utils.log import get_logger

            get_logger("cadence_tpu.dynamicconfig").exception(
                f"failed to load {self.path}; keeping previous values"
            )
            return
        with self._lock:
            self._values = values
            self._mtime = mtime

    def get_value(self, key: str, filters: Dict[str, Any]) -> Optional[Any]:
        now = time.monotonic()
        if now - self._last_check > self.poll_interval_s:
            self._last_check = now
            self._load()
        with self._lock:
            entries = list(self._values.get(key, ()))
        return _best_match(entries, filters)


class LayeredClient(Client):
    """Programmatic overrides layered over a base client.

    The capacity autopilot (and tests) write through ``overrides`` —
    an :class:`InMemoryClient` — while operator-managed values keep
    coming from the base (file) client. An override, when present for
    the key+filters, ALWAYS wins over the base; ``remove_value`` on the
    override layer falls back to the base value, which is the
    autopilot's revert-to-operator-config path."""

    def __init__(
        self, overrides: InMemoryClient, base: Optional[Client] = None
    ) -> None:
        self.overrides = overrides
        self.base = base

    def get_value(self, key: str, filters: Dict[str, Any]) -> Optional[Any]:
        v = self.overrides.get_value(key, filters)
        if v is not None:
            return v
        if self.base is not None:
            return self.base.get_value(key, filters)
        return None


def _best_match(
    entries: List[Tuple[Dict[str, Any], Any]], filters: Dict[str, Any]
) -> Optional[Any]:
    """Most-specific match wins: domain+tasklist > domain > tasklist >
    unfiltered; equal specificity resolves to the LAST entry so a
    later set_value overrides an earlier one."""
    best, best_score = None, -1
    for entry_filters, value in entries:
        if all(filters.get(k) == v for k, v in entry_filters.items()):
            score = 2 * ("domain" in entry_filters) + (
                "task_list" in entry_filters
            ) + len(entry_filters)
            if score >= best_score:
                best, best_score = value, score
    return best


class Collection:
    """Typed getters bound to a client (reference: dynamicconfig/config.go)."""

    def __init__(self, client: Optional[Client] = None) -> None:
        self.client = client or InMemoryClient()

    def _getter(self, key: str, default: Any, cast: Callable[[Any], Any]):
        def get(**filters: Any) -> Any:
            v = self.client.get_value(key, filters)
            return default if v is None else cast(v)

        return get

    def int_property(self, key: str, default: int) -> Callable[..., int]:
        return self._getter(key, default, int)

    def float_property(self, key: str, default: float) -> Callable[..., float]:
        return self._getter(key, default, float)

    def bool_property(self, key: str, default: bool) -> Callable[..., bool]:
        return self._getter(key, default, bool)

    def duration_property(
        self, key: str, default_ns: int
    ) -> Callable[..., int]:
        """Durations stored as seconds in config, returned as ns."""
        return self._getter(key, default_ns, lambda v: int(v * 1_000_000_000))

    def string_property(self, key: str, default: str) -> Callable[..., str]:
        return self._getter(key, default, str)

    def map_property(self, key: str, default: dict) -> Callable[..., dict]:
        return self._getter(key, default, dict)
