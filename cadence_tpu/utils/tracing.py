"""End-to-end request tracing: spans, trace contexts, flight recorder.

The missing third leg of the observability plane (metrics.py counts,
log.py narrates, nothing *connects*): a ``Tracer`` hands out ``Span``s
with monotonic timings and parent/child links, and a ``TraceContext``
(trace_id, span_id, sampled) small enough to ride every existing hop —
gRPC metadata on the cross-process paths (rpc/client.py injects,
rpc/server.py extracts), the thread itself on the in-process paths
(frontend → history → matching all run in the caller's thread, so a
thread-local "current span" is the propagation), and a bounded
workflow-keyed binding table for the asynchronous hops (queue task
processing and replication apply run on pump threads; the engine binds
``workflow_id → context`` at persist time and the pump joins the trace
by lookup).

Completed spans land in a bounded in-process flight recorder (a ring
buffer — old traces fall off, memory never grows), dumpable as
Chrome-trace-format JSON via ``GET /debug/pprof/traces``
(utils/pprof.py), the ``dump_traces`` admin verb, or
``Tracer.chrome_trace()`` directly — load the output in Perfetto /
``chrome://tracing``.

Cost discipline (the serving path must not pay for disabled
telemetry): nothing here creates implicit root traces. A root exists
only when (a) code explicitly enters ``tracer.trace(...)`` (tests, the
demo driver, the canary), or (b) an RPC server roots one at the
configured ``sample_rate`` (``telemetry:`` YAML section through
bootstrap). Every other entry point — ``span()``, ``annotate()``,
``bind()`` — first reads the thread-local current span and returns the
shared no-op immediately when there is none: the unsampled path is one
attribute lookup and a None check.
"""

from __future__ import annotations

import itertools
import json
import random
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from .metrics import NOOP, Scope

_WIRE_KEY = "x-cadence-trace"  # gRPC metadata key (lowercase required)


class TraceContext:
    """The propagated identity of a position in a trace: enough to
    parent a child span anywhere the context can be carried."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def to_wire(self) -> str:
        return f"{self.trace_id}:{self.span_id}:{int(self.sampled)}"

    @classmethod
    def from_wire(cls, value: str) -> Optional["TraceContext"]:
        """Parse the wire form; malformed input returns None (a bad
        header must never fail the RPC it rode in on)."""
        try:
            trace_id, span_id, sampled = str(value).split(":")
            if not trace_id or not span_id:
                return None
            return cls(trace_id, span_id, sampled == "1")
        except (ValueError, AttributeError):
            return None

    def __repr__(self) -> str:  # debugging aid only
        return f"TraceContext({self.to_wire()})"


class _NoopSpan:
    """Shared do-nothing span: what every tracing entry point returns
    on the unsampled path, so call sites never branch on None."""

    __slots__ = ()
    ctx = None
    trace_id = ""
    span_id = ""
    sampled = False

    def annotate(self, text: str) -> None:
        pass

    def set_tag(self, key: str, value) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def __bool__(self) -> bool:
        return False


NOOP_SPAN = _NoopSpan()

_span_counter = itertools.count(1)


def _new_span_id() -> str:
    # counter + thread id: unique within the process without an entropy
    # syscall per span (trace ids carry the global uniqueness)
    return f"{threading.get_ident() & 0xffff:x}.{next(_span_counter)}"


class Span:
    """One timed operation in a trace. Context-manager: entering makes
    it the thread's current span (children created on this thread nest
    under it), exiting finishes it into the flight recorder."""

    __slots__ = (
        "tracer", "name", "service", "trace_id", "span_id", "parent_id",
        "tags", "annotations", "thread", "start_us", "_t0", "dur_us",
        "_prev", "error",
    )

    def __init__(self, tracer: "Tracer", name: str, service: str,
                 trace_id: str, parent_id: str,
                 tags: Optional[Dict[str, Any]] = None) -> None:
        self.tracer = tracer
        self.name = name
        self.service = service or "app"
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.tags: Dict[str, Any] = dict(tags) if tags else {}
        self.annotations: List[Tuple[float, str]] = []
        self.thread = threading.current_thread().name
        # wall clock anchors the Chrome-trace timeline; the monotonic
        # clock owns every duration and annotation offset
        self.start_us = time.time() * 1e6
        self._t0 = time.perf_counter()
        self.dur_us: float = 0.0
        self._prev = None
        self.error: str = ""

    sampled = True

    @property
    def ctx(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, True)

    def annotate(self, text: str) -> None:
        """Timestamped breadcrumb (retries, fault injections, fallback
        decisions) — rendered as an instant event on the timeline."""
        self.annotations.append(
            ((time.perf_counter() - self._t0) * 1e6, str(text))
        )

    def set_tag(self, key: str, value) -> None:
        self.tags[key] = value

    def finish(self) -> None:
        if self.dur_us:
            return  # idempotent: a double finish must not double-record
        self.dur_us = max((time.perf_counter() - self._t0) * 1e6, 0.01)
        self.tracer._record(self)

    def __enter__(self) -> "Span":
        self._prev = self.tracer._activate(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.error = exc_type.__name__
            self.tags.setdefault("error", exc_type.__name__)
        self.tracer._deactivate(self._prev)
        self.finish()


class Tracer:
    """Span factory + thread-local context + flight recorder; one per
    process (module singleton ``TRACER``), thread-safe."""

    def __init__(self, sample_rate: float = 0.0, capacity: int = 4096,
                 bind_capacity: int = 2048, bind_ttl_s: float = 60.0,
                 metrics: Scope = NOOP,
                 seed: Optional[int] = None) -> None:
        self.sample_rate = float(sample_rate)
        self.capacity = int(capacity)
        self._bind_capacity = int(bind_capacity)
        self._bind_ttl_s = float(bind_ttl_s)
        self._metrics = metrics.tagged(layer="telemetry")
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.capacity)
        # key -> (context, bound-at monotonic time); LRU + TTL
        self._bindings: "OrderedDict[Any, Tuple[TraceContext, float]]" = (
            OrderedDict()
        )
        self._tls = threading.local()

    # -- configuration -------------------------------------------------

    def configure(self, sample_rate: Optional[float] = None,
                  capacity: Optional[int] = None,
                  metrics: Optional[Scope] = None) -> "Tracer":
        """Re-point the live tracer (bootstrap's ``telemetry:`` section
        and tests share the process singleton)."""
        with self._lock:
            if sample_rate is not None:
                self.sample_rate = float(sample_rate)
            if capacity is not None and int(capacity) != self.capacity:
                self.capacity = int(capacity)
                self._spans = deque(self._spans, maxlen=self.capacity)
            if metrics is not None:
                self._metrics = metrics.tagged(layer="telemetry")
        return self

    # -- context plumbing ----------------------------------------------

    def current(self) -> Optional[Span]:
        """The thread's active span (None on the unsampled path). THE
        hot-path check: one thread-local attribute read."""
        return getattr(self._tls, "span", None)

    def current_context(self) -> Optional[TraceContext]:
        span = getattr(self._tls, "span", None)
        return span.ctx if span is not None else None

    def _activate(self, span: Optional[Span]) -> Optional[Span]:
        prev = getattr(self._tls, "span", None)
        self._tls.span = span
        return prev

    def _deactivate(self, prev: Optional[Span]) -> None:
        self._tls.span = prev

    # -- span creation -------------------------------------------------

    def trace(self, name: str, sampled: Optional[bool] = None,
              service: str = "app", **tags):
        """Root a new trace. ``sampled=None`` rolls ``sample_rate``;
        tests and the demo pass ``sampled=True`` explicitly. Returns the
        shared no-op when the roll loses — callers always get a span."""
        if sampled is None:
            sampled = (
                self.sample_rate > 0.0
                and self._rng.random() < self.sample_rate
            )
        if not sampled:
            return NOOP_SPAN
        self._metrics.inc("traces_sampled")
        return Span(
            self, name, service, uuid.uuid4().hex[:16], "", tags=tags
        )

    def span(self, name: str, service: str = "",
             parent: Optional[object] = None, **tags):
        """Child span under ``parent`` (a Span or TraceContext) or the
        thread's current span. No parent → no-op: children never root
        traces implicitly."""
        if parent is None:
            parent = getattr(self._tls, "span", None)
            if parent is None:
                return NOOP_SPAN
        ctx = parent.ctx if isinstance(parent, Span) else parent
        if ctx is None or not ctx.sampled:
            return NOOP_SPAN
        return Span(
            self, name, service, ctx.trace_id, ctx.span_id, tags=tags
        )

    def annotate(self, text: str) -> None:
        """Breadcrumb on the current span, if any (the fault injector's
        and retry loops' one-liner)."""
        span = getattr(self._tls, "span", None)
        if span is not None:
            span.annotate(text)

    # -- workflow-keyed binding (async hop joining) --------------------

    def bind(self, key, ctx: Optional[TraceContext] = None) -> None:
        """Associate ``key`` (e.g. a workflow id) with ``ctx`` (default:
        the current span's context) so pump threads can join the trace.
        Bounded LRU with a TTL — a binding outliving its request cannot
        keep pumping spans into a long-dead trace (a cron workflow's
        timers would otherwise join one ancient sampled request
        forever), and an abandoned binding ages out, never leaks."""
        if ctx is None:
            span = getattr(self._tls, "span", None)
            if span is None:
                return
            ctx = span.ctx
        with self._lock:
            self._bindings.pop(key, None)
            self._bindings[key] = (ctx, time.monotonic())
            while len(self._bindings) > self._bind_capacity:
                self._bindings.popitem(last=False)

    def lookup(self, key) -> Optional[TraceContext]:
        if not self._bindings:  # len() is atomic: lock-free fast path
            return None
        with self._lock:
            entry = self._bindings.get(key)
            if entry is None:
                return None
            ctx, bound_at = entry
            if time.monotonic() - bound_at > self._bind_ttl_s:
                del self._bindings[key]
                return None
            return ctx

    # -- flight recorder ----------------------------------------------

    def _record(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) == self.capacity:
                self._metrics.inc("spans_dropped")
            self._spans.append(span)
        self._metrics.inc("spans_recorded")

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def traces(self) -> Dict[str, List[Span]]:
        """Finished spans grouped by trace, oldest trace first."""
        out: Dict[str, List[Span]] = {}
        for s in self.spans():
            out.setdefault(s.trace_id, []).append(s)
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._bindings.clear()

    # -- export --------------------------------------------------------

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict:
        """Chrome-trace-format JSON (dict): spans as complete ("X")
        events, annotations as instant ("i") events, one pid per
        service with process_name metadata — drop the output straight
        into Perfetto or chrome://tracing."""
        spans = [
            s for s in self.spans()
            if trace_id is None or s.trace_id == trace_id
        ]
        pids: Dict[str, int] = {}
        events: List[Dict] = []
        for s in spans:
            pids.setdefault(s.service, len(pids) + 1)
        for service, pid in sorted(pids.items(), key=lambda kv: kv[1]):
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": service},
            })
        for s in spans:
            pid = pids[s.service]
            args = {
                "trace_id": s.trace_id, "span_id": s.span_id,
                "parent_id": s.parent_id,
            }
            args.update({k: str(v) for k, v in s.tags.items()})
            events.append({
                "name": s.name, "ph": "X", "ts": round(s.start_us, 1),
                "dur": round(s.dur_us, 1), "pid": pid, "tid": s.thread,
                "args": args,
            })
            for off_us, text in s.annotations:
                events.append({
                    "name": text, "ph": "i", "s": "t",
                    "ts": round(s.start_us + off_us, 1),
                    "pid": pid, "tid": s.thread,
                    "args": {"span_id": s.span_id},
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def chrome_trace_json(self, trace_id: Optional[str] = None) -> str:
        return json.dumps(self.chrome_trace(trace_id), indent=1)


# the process tracer every layer shares (bootstrap configures it from
# the telemetry: YAML section; tests reconfigure + clear per test)
TRACER = Tracer()


def current_span() -> Optional[Span]:
    return TRACER.current()


def annotate(text: str) -> None:
    TRACER.annotate(text)


def configure(sample_rate: Optional[float] = None,
              capacity: Optional[int] = None,
              metrics: Optional[Scope] = None) -> Tracer:
    return TRACER.configure(
        sample_rate=sample_rate, capacity=capacity, metrics=metrics
    )


# -- wire helpers (rpc/client.py + rpc/server.py) -----------------------


def inject_metadata(metadata=None):
    """gRPC metadata tuple carrying the current context, or the input
    unchanged when there is nothing to propagate."""
    ctx = TRACER.current_context()
    if ctx is None:
        return metadata
    return tuple(metadata or ()) + ((_WIRE_KEY, ctx.to_wire()),)


def extract_metadata(metadata) -> Optional[TraceContext]:
    """TraceContext from incoming gRPC metadata, or None."""
    if not metadata:
        return None
    for key, value in metadata:
        if key == _WIRE_KEY:
            return TraceContext.from_wire(value)
    return None
