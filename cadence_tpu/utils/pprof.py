"""Diagnostic/profiling HTTP surface.

Reference: common/pprof.go starts Go's net/http/pprof endpoint per
service (config Service.PProf.Port). The Python/JAX equivalents served
here, all stdlib, no deps:

  GET /debug/pprof/            index
  GET /debug/pprof/stack       every thread's current stack (the
                               goroutine-profile analog)
  GET /debug/pprof/profile?seconds=N&hz=H
                               statistical CPU profile: samples all
                               thread stacks at H hz for N seconds and
                               returns collapsed stacks ("frame;frame N"
                               lines — feed straight to flamegraph.pl)
  GET /debug/pprof/heap?topn=N tracemalloc top allocation sites
                               (tracemalloc starts on first call)
  GET /debug/pprof/traces[?trace_id=ID]
                               the tracing flight recorder
                               (utils/tracing.py ring buffer) as
                               Chrome-trace-format JSON — load in
                               Perfetto / chrome://tracing; trace_id
                               filters to one request's trace
  POST /debug/pprof/device/start?dir=D
  POST /debug/pprof/device/stop
                               bracket a jax.profiler trace (XLA/TPU
                               device timeline, viewable in
                               tensorboard/xprof) — the device-side
                               story Go pprof has no equivalent for

The sampler is safe to run in production: it reads
``sys._current_frames`` from a daemon thread, never stops the world.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from cadence_tpu.utils.log import get_logger


def thread_stacks() -> str:
    """Every live thread's stack, most recent call last."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(
            f"--- thread {names.get(ident, '?')} (id {ident}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    return "\n".join(out)


def sample_cpu(seconds: float = 5.0, hz: float = 100.0) -> str:
    """Collapsed-stack statistical profile of all threads.

    Lines are ``frame;frame;...;frame count`` with the root first —
    flamegraph.pl / speedscope both ingest this directly.
    """
    me = threading.get_ident()
    counts: Counter = Counter()
    interval = 1.0 / max(hz, 1.0)
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} ({code.co_filename}:{f.f_lineno})")
                f = f.f_back
            counts[";".join(reversed(stack))] += 1
        time.sleep(interval)
    return "\n".join(f"{k} {v}" for k, v in counts.most_common())


def heap_top(topn: int = 30) -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return (
            "tracemalloc started; allocations are tracked from now — "
            "call again for a snapshot"
        )
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:topn]
    total = sum(s.size for s in snap.statistics("filename"))
    lines = [f"total tracked: {total / 1e6:.1f} MB"]
    lines += [str(s) for s in stats]
    return "\n".join(lines)


class _Handler(BaseHTTPRequestHandler):
    server_version = "cadence-tpu-pprof"

    def log_message(self, fmt, *args):  # route to our logger, not stderr
        self.server._log.info("pprof " + fmt % args)

    def _reply(self, code: int, body: str,
               content_type: str = "text/plain; charset=utf-8") -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _route(self) -> Tuple[str, dict]:
        u = urlparse(self.path)
        return u.path.rstrip("/"), parse_qs(u.query)

    def do_GET(self) -> None:  # noqa: N802 (stdlib casing)
        path, q = self._route()
        try:
            if path in ("", "/debug/pprof"):
                self._reply(200, __doc__ or "")
            elif path == "/debug/pprof/stack":
                self._reply(200, thread_stacks())
            elif path == "/debug/pprof/profile":
                seconds = float(q.get("seconds", ["5"])[0])
                hz = float(q.get("hz", ["100"])[0])
                # clamp like seconds: an absurd hz would busy-spin a
                # core walking every thread's stack for the whole window
                self._reply(200, sample_cpu(
                    min(seconds, 120.0), min(hz, 1000.0)
                ))
            elif path == "/debug/pprof/heap":
                self._reply(200, heap_top(int(q.get("topn", ["30"])[0])))
            elif path == "/debug/pprof/traces":
                from cadence_tpu.utils.tracing import TRACER

                trace_id = q.get("trace_id", [None])[0]
                self._reply(
                    200, TRACER.chrome_trace_json(trace_id),
                    content_type="application/json",
                )
            else:
                self._reply(404, f"unknown pprof path {path}\n")
        except Exception as e:  # diagnostics must not kill the server
            self._reply(500, f"{type(e).__name__}: {e}\n")

    def do_POST(self) -> None:  # noqa: N802
        path, q = self._route()
        try:
            if path == "/debug/pprof/device/start":
                import jax

                trace_dir = q.get("dir", ["/tmp/cadence-tpu-trace"])[0]
                jax.profiler.start_trace(trace_dir)
                self._reply(200, f"device trace started -> {trace_dir}\n")
            elif path == "/debug/pprof/device/stop":
                import jax

                jax.profiler.stop_trace()
                self._reply(200, "device trace stopped\n")
            else:
                self._reply(404, f"unknown pprof path {path}\n")
        except Exception as e:
            self._reply(500, f"{type(e).__name__}: {e}\n")


class PProfServer:
    """The per-process diagnostics endpoint (common/pprof.go Start)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1") -> None:
        self._log = get_logger("cadence_tpu.pprof")
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd._log = self._log
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "PProfServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pprof", daemon=True
        )
        self._thread.start()
        self._log.info(f"pprof listening on {self.address}")
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5.0)
