"""Tracked lock primitives for the runtime concurrency sanitizer.

The dynamic half of the Pass 3 lock analysis (the static half lives in
``cadence_tpu/analysis/lock_order.py``). The runtime constructs its
locks through this module's factory:

    self._lock = locks.make_lock("ShardContext._lock")

**Disabled path** (the default, and every production/tier-1 run that
is not a sanitizer test): ``make_lock``/``make_rlock`` return the raw
``threading`` primitive after ONE module-global check — no wrapper, no
frame inspection, no per-acquire work. ``make_guarded`` returns its
container argument unchanged. This mirrors the
``wrap_bundle(faults=..., effects=...)`` contract: nothing is
installed unless a chaos/sanitizer harness asks for it.

**Sanitizer mode**: ``wrap_locks(tracker)`` installs a process-wide
tracker (``testing/race_witness.RaceWitness``) and the factory starts
returning ``TrackedLock``/``TrackedRLock`` wrappers that record

* a per-thread **acquisition stack** (which tracked locks this thread
  holds, with the acquiring ``module:Class.method`` site) — the raw
  material for the runtime lock-order graph and its inversion check;
* **held durations** (max per lock name, for the overhead/stall docs);
* **guarded-field accesses** — ``make_guarded(container, field,
  guard)`` wraps the declared hot shared dicts/lists in proxies that
  report every read/write together with whether the declared guard was
  held on the calling thread (the Eraser-style lockset input);
* **blocking-while-locked events** — ``note_blocking`` is called by
  the sanitizer's persistence probe and by the patched
  ``time.sleep``/``Queue.get``/``Thread.join`` entry points.

Lock naming. A tracked lock's full name is
``<module relpath>:<short name>`` (module inferred from the
construction site), e.g. ``cadence_tpu/runtime/shard.py:
ShardContext._lock`` — byte-compatible with the static pass's
``_lock_id`` for self-attribute locks, so the runtime-observed graph
and the static graph speak the same identifiers.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import List, Tuple

_tracker = None  # the installed RaceWitness (or None = disabled)

_held = threading.local()  # .stack: List[_Held] per thread


class _Held:
    __slots__ = ("lock", "site", "t0", "reentrant")

    def __init__(self, lock, site, t0, reentrant):
        self.lock = lock
        self.site = site
        self.t0 = t0
        self.reentrant = reentrant


def wrap_locks(tracker):
    """Install the process-wide lock tracker (sanitizer mode ON).
    Mirrors ``wrap_bundle(faults=...)``: only a test harness calls
    this; everything constructed afterwards through the factory is
    tracked. Returns the tracker for chaining."""
    global _tracker
    _tracker = tracker
    return tracker


def unwrap_locks() -> None:
    """Remove the tracker (sanitizer mode OFF). Wrappers constructed
    while tracking was on keep working — they just stop reporting."""
    global _tracker
    _tracker = None


def tracking_enabled() -> bool:
    return _tracker is not None


def _stack() -> List[_Held]:
    try:
        return _held.stack
    except AttributeError:
        s = _held.stack = []
        return s


def held_locks() -> Tuple[str, ...]:
    """Names of tracked locks the CURRENT thread holds (innermost
    last); always () when the sanitizer is disabled."""
    if _tracker is None:
        return ()
    return tuple(e.lock.name for e in _stack() if not e.reentrant)


def innermost_held():
    """The most recently acquired non-reentrant hold on this thread
    (a ``_Held`` record), or None."""
    for e in reversed(_stack()):
        if not e.reentrant:
            return e
    return None


# --------------------------------------------------------------------------
# acquisition-site capture
# --------------------------------------------------------------------------

_THIS_FILE = os.path.abspath(__file__)
_UNKNOWN_SITE = ("<unknown>", "", "", 0)


def _relpath(filename: str) -> str:
    """Repo-relative path matching the static pass's module ids
    ("cadence_tpu/runtime/shard.py"); absolute path when the file is
    outside the package (tests, fixtures)."""
    norm = filename.replace(os.sep, "/")
    idx = norm.rfind("cadence_tpu/")
    if idx >= 0:
        return norm[idx:]
    return norm


def call_site(skip_self: bool = True) -> Tuple[str, str, str, int]:
    """(module relpath, class name, function name, lineno) of the
    nearest frame outside this module (and outside threading.py —
    Condition.wait re-acquires through the wrapper)."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _THIS_FILE and not fn.endswith(
            "threading.py"
        ):
            klass = ""
            zelf = f.f_locals.get("self")
            if zelf is not None:
                klass = type(zelf).__name__
            return (_relpath(fn), klass, f.f_code.co_name, f.f_lineno)
        f = f.f_back
    return _UNKNOWN_SITE


def site_anchor(site: Tuple[str, str, str, int]) -> str:
    """"module:Class.method" (or "module:method" for free functions) —
    the prefix the static pass uses in its finding anchors."""
    mod, klass, func, _ = site
    qual = f"{klass}.{func}" if klass else func
    return f"{mod}:{qual}"


# --------------------------------------------------------------------------
# tracked primitives
# --------------------------------------------------------------------------

# monotonic time source; swapped out never (tests read the counter)
from time import monotonic as _now

_constructed = 0  # TrackedLock/TrackedRLock instances ever built —
                  # the disabled-path overhead guard asserts this
                  # stays 0 across a full untracked run


def constructed_count() -> int:
    return _constructed


class TrackedLock:
    """Drop-in ``threading.Lock`` recording acquisitions/releases to
    the installed tracker. Attribute access falls through to the inner
    primitive so ``threading.Condition`` can be constructed over it.

    Known limit (deliberate): holds are tracked per-thread, so the
    cross-thread handoff ``threading.Lock`` technically permits
    (acquire on thread A, release on thread B) would leave A's stack
    stale — the release silently finds no entry. The runtime never
    does this (every factory call site is a scoped ``with`` block, the
    one shape the static Pass 3 can prove things about); a handoff
    pattern would need an owner registry, not a thread-local stack."""

    _reentrant = False

    def __init__(self, name: str, inner=None) -> None:
        global _constructed
        _constructed += 1
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()

    # -- core protocol -------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            # try-locks cannot deadlock, so they contribute no
            # acquisition-ORDER edge (the static pass exempts them the
            # same way) — but the hold itself is real: guarded-field
            # checks and blocking attribution still see it
            self._on_acquired(edge=bool(blocking))
        return ok

    def release(self) -> None:
        self._on_release()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    # -- tracking ------------------------------------------------------

    def _on_acquired(self, edge: bool = True) -> None:
        t = _tracker
        stack = _stack()
        reentrant = self._reentrant and any(
            e.lock is self for e in stack
        )
        site = call_site() if t is not None else _UNKNOWN_SITE
        entry = _Held(self, site, _now(), reentrant)
        if t is not None and not reentrant:
            prior = innermost_held()
            stack.append(entry)
            t.on_acquire(self, entry, prior if edge else None)
        else:
            stack.append(entry)

    def _on_release(self) -> None:
        stack = _stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].lock is self:
                entry = stack.pop(i)
                t = _tracker
                if t is not None and not entry.reentrant:
                    t.on_release(self, entry, _now() - entry.t0)
                return

    def _drop_all(self) -> int:
        """Pop every hold of this lock from the thread's stack
        (Condition._release_save on an RLock fully releases)."""
        stack = _stack()
        n = 0
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].lock is self:
                stack.pop(i)
                n += 1
        return n

    def _repush(self, n: int) -> None:
        stack = _stack()
        for i in range(n):
            # restore after a Condition.wait: re-entry, no new edges
            stack.append(_Held(self, _UNKNOWN_SITE, _now(), i > 0))


class TrackedRLock(TrackedLock):
    _reentrant = True

    def __init__(self, name: str, inner=None) -> None:
        super().__init__(
            name, inner if inner is not None else threading.RLock()
        )

    # Condition-protocol support: these must go through the wrapper,
    # or a Condition built over the inner RLock's own methods would
    # desync the held stack while parked in wait().
    def _release_save(self):
        n = self._drop_all()
        return (self._inner._release_save(), n)

    def _acquire_restore(self, saved):
        state, n = saved
        self._inner._acquire_restore(state)
        self._repush(max(n, 1))

    def _is_owned(self):
        return self._inner._is_owned()


class TrackedCondition(threading.Condition):
    """``threading.Condition`` over a tracked lock, with wait counted
    as a release-while-parked (NOT a blocking violation — waiting on
    the condition you hold releases it, same exemption as the static
    pass)."""

    def __init__(self, lock=None, name: str = "") -> None:
        if lock is None:
            lock = TrackedRLock(name or "condition")
        self.name = name or getattr(lock, "name", "condition")
        super().__init__(lock)


# --------------------------------------------------------------------------
# factory — the tree-wide construction entry points
# --------------------------------------------------------------------------


def _full_name(short: str) -> str:
    """Prefix the caller's module relpath so the runtime name matches
    the static pass's lock ids ("cadence_tpu/runtime/shard.py:
    ShardContext._lock")."""
    f = sys._getframe(2)
    return f"{_relpath(f.f_code.co_filename)}:{short}"


def make_lock(name: str):
    """A mutex. Disabled: a raw ``threading.Lock`` (one global check,
    nothing else). Sanitizer mode: a ``TrackedLock`` whose full name
    is ``<caller module>:<name>``."""
    if _tracker is None:
        return threading.Lock()
    return TrackedLock(_full_name(name))


def make_rlock(name: str):
    if _tracker is None:
        return threading.RLock()
    return TrackedRLock(_full_name(name))


def make_condition(lock=None, name: str = "condition"):
    """A condition variable; over ``lock`` when given (tracked or
    plain), else over its own (tracked, in sanitizer mode) lock."""
    if _tracker is None:
        return threading.Condition(lock)
    if lock is None:
        lock = TrackedRLock(_full_name(name))
    return TrackedCondition(lock, name=_full_name(name))


# --------------------------------------------------------------------------
# guarded-field proxies (Eraser-style lockset input)
# --------------------------------------------------------------------------


def _guard_event(field: str, guard, writing: bool) -> None:
    t = _tracker
    if t is None:
        return
    held = any(e.lock is guard for e in _stack())
    t.on_guarded_access(field, held, writing,
                        None if held else call_site())


class GuardedDict(dict):
    """Dict proxy reporting every access with the guard-held bit. Only
    ever constructed in sanitizer mode."""

    def __init__(self, field: str, guard, initial=None,
                 default_factory=None) -> None:
        super().__init__(initial or {})
        self._field = field
        self._guard = guard
        self._default_factory = default_factory

    # -- writes --------------------------------------------------------

    def __setitem__(self, k, v):
        _guard_event(self._field, self._guard, True)
        super().__setitem__(k, v)

    def __delitem__(self, k):
        _guard_event(self._field, self._guard, True)
        super().__delitem__(k)

    def pop(self, *a):
        _guard_event(self._field, self._guard, True)
        return super().pop(*a)

    def popitem(self):
        _guard_event(self._field, self._guard, True)
        return super().popitem()

    def clear(self):
        _guard_event(self._field, self._guard, True)
        super().clear()

    def update(self, *a, **kw):
        _guard_event(self._field, self._guard, True)
        super().update(*a, **kw)

    def setdefault(self, k, default=None):
        _guard_event(self._field, self._guard, True)
        return super().setdefault(k, default)

    def __missing__(self, k):
        if self._default_factory is None:
            raise KeyError(k)
        v = self._default_factory()
        super().__setitem__(k, v)
        return v

    def __ior__(self, other):
        _guard_event(self._field, self._guard, True)
        super().update(other)
        return self

    # -- reads ---------------------------------------------------------

    def __getitem__(self, k):
        _guard_event(self._field, self._guard, False)
        # raw dict probe: the instrumented __contains__ would fire a
        # second guard event per read on the metrics hot path
        if self._default_factory is not None and not dict.__contains__(
            self, k
        ):
            return self.__missing__(k)
        return super().__getitem__(k)

    def get(self, k, default=None):
        _guard_event(self._field, self._guard, False)
        return super().get(k, default)

    def __contains__(self, k):
        _guard_event(self._field, self._guard, False)
        return super().__contains__(k)

    def __iter__(self):
        _guard_event(self._field, self._guard, False)
        return super().__iter__()

    def keys(self):
        _guard_event(self._field, self._guard, False)
        return super().keys()

    def values(self):
        _guard_event(self._field, self._guard, False)
        return super().values()

    def items(self):
        _guard_event(self._field, self._guard, False)
        return super().items()

    def __len__(self):
        _guard_event(self._field, self._guard, False)
        return super().__len__()


class GuardedList(list):
    """List proxy reporting every access with the guard-held bit."""

    def __init__(self, field: str, guard, initial=None) -> None:
        super().__init__(initial or [])
        self._field = field
        self._guard = guard

    def append(self, v):
        _guard_event(self._field, self._guard, True)
        super().append(v)

    def extend(self, it):
        _guard_event(self._field, self._guard, True)
        super().extend(it)

    def insert(self, i, v):
        _guard_event(self._field, self._guard, True)
        super().insert(i, v)

    def remove(self, v):
        _guard_event(self._field, self._guard, True)
        super().remove(v)

    def pop(self, *a):
        _guard_event(self._field, self._guard, True)
        return super().pop(*a)

    def clear(self):
        _guard_event(self._field, self._guard, True)
        super().clear()

    def __setitem__(self, i, v):
        _guard_event(self._field, self._guard, True)
        super().__setitem__(i, v)

    def __delitem__(self, i):
        _guard_event(self._field, self._guard, True)
        super().__delitem__(i)

    def __iadd__(self, other):
        _guard_event(self._field, self._guard, True)
        super().extend(other)
        return self

    def __imul__(self, n):
        _guard_event(self._field, self._guard, True)
        list.__imul__(self, n)
        return self

    def sort(self, *a, **kw):
        _guard_event(self._field, self._guard, True)
        super().sort(*a, **kw)

    def reverse(self):
        _guard_event(self._field, self._guard, True)
        super().reverse()

    def __getitem__(self, i):
        _guard_event(self._field, self._guard, False)
        return super().__getitem__(i)

    def __iter__(self):
        _guard_event(self._field, self._guard, False)
        return super().__iter__()

    def __len__(self):
        _guard_event(self._field, self._guard, False)
        return super().__len__()

    def __contains__(self, v):
        _guard_event(self._field, self._guard, False)
        return super().__contains__(v)


def make_guarded(container, field: str, guard):
    """Declare ``container`` (a dict or list) guarded by ``guard``.

    Disabled: returns ``container`` unchanged (zero cost, zero type
    change). Sanitizer mode: returns a recording proxy and registers
    the field with the tracker — every subsequent access reports
    (field, guard-held?, read/write) for the GUARDED-FIELD-RACE rule.
    ``defaultdict`` inputs keep their default factory."""
    t = _tracker
    if t is None:
        return container
    full = _full_name(field)
    t.on_guard_registered(full, getattr(guard, "name", str(guard)))
    if isinstance(container, dict):
        factory = getattr(container, "default_factory", None)
        return GuardedDict(full, guard, container,
                           default_factory=factory)
    if isinstance(container, list):
        return GuardedList(full, guard, container)
    raise TypeError(
        f"make_guarded: unsupported container {type(container).__name__}"
    )


# --------------------------------------------------------------------------
# blocking-op reporting (the RUNTIME-LOCK-BLOCKING feed)
# --------------------------------------------------------------------------


def note_blocking(kind: str, detail: str) -> None:
    """Report a blocking operation (store I/O, sleep, join, blocking
    queue op) if the calling thread holds any tracked lock. Called by
    the sanitizer's persistence probe and the patched stdlib entry
    points; one global check + one thread-local read when nothing is
    held."""
    t = _tracker
    if t is None:
        return
    entry = innermost_held()
    if entry is None:
        return
    t.on_blocking(entry, kind, detail)
