"""Exponential retry with jitter + retry-policy interval math.

Two distinct things share the name in the reference and here too:

  * ``RetryPolicy`` / ``next_backoff_interval`` — the *workflow/activity*
    retry semantics (/root/reference/service/history/retry.go): given a
    RetryPolicy and attempt count, when does the next attempt start, and
    does the error/expiration terminate retrying.
  * ``Retry`` / ``ExponentialRetryPolicy`` — host-side operation retries
    (/root/reference/common/backoff/retry.go): persistence calls, RPC.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Sequence, TypeVar

from .clock import SECOND

NO_INTERVAL = -1  # stop retrying


@dataclasses.dataclass
class RetryPolicy:
    """Workflow/activity retry policy (reference idl RetryPolicy;
    validation mirrors common/util.go ValidateRetryPolicy)."""

    initial_interval_seconds: int = 1
    backoff_coefficient: float = 2.0
    maximum_interval_seconds: int = 0      # 0 = uncapped
    maximum_attempts: int = 0              # 0 = unlimited
    expiration_seconds: int = 0            # 0 = no expiry
    non_retriable_errors: Sequence[str] = ()

    def validate(self) -> None:
        validate_retry_policy(self)


def validate_retry_policy(policy) -> None:
    """Reject malformed user retry policies before they reach the FSM.

    Mirrors ValidateRetryPolicy (/root/reference/common/util.go:357-384);
    raises ValueError (callers map to BadRequest / decision failure).
    A None policy is valid (no retry). Accepts either retry-policy
    shape (core.events.RetryPolicy uses expiration_interval_seconds,
    this module's uses expiration_seconds)."""
    if policy is None:
        return
    # wire-decoded policies can carry explicit nulls; treat them as the
    # reference's thrift Get* accessors do (nil -> zero value) so they
    # fail validation as BadRequest, not as a server-side TypeError
    def _n(v):
        return 0 if v is None else v

    initial = _n(policy.initial_interval_seconds)
    coefficient = _n(policy.backoff_coefficient)
    max_interval = _n(policy.maximum_interval_seconds)
    max_attempts = _n(policy.maximum_attempts)
    expiration = _n(getattr(policy, "expiration_interval_seconds",
                            getattr(policy, "expiration_seconds", 0)))
    if initial <= 0:
        raise ValueError(
            "InitialIntervalInSeconds must be greater than 0 on retry policy")
    if coefficient < 1:
        raise ValueError(
            "BackoffCoefficient cannot be less than 1 on retry policy")
    if max_interval < 0:
        raise ValueError(
            "MaximumIntervalInSeconds cannot be less than 0 on retry policy")
    if max_interval > 0 and max_interval < initial:
        raise ValueError("MaximumIntervalInSeconds cannot be less than "
                         "InitialIntervalInSeconds on retry policy")
    if max_attempts < 0:
        raise ValueError(
            "MaximumAttempts cannot be less than 0 on retry policy")
    if expiration < 0:
        raise ValueError(
            "ExpirationIntervalInSeconds cannot be less than 0 on retry policy")
    if max_attempts == 0 and expiration == 0:
        raise ValueError(
            "MaximumAttempts and ExpirationIntervalInSeconds are both 0; "
            "at least one must be specified on retry policy")


def next_backoff_interval_seconds(
    policy: RetryPolicy,
    attempt: int,
    expiration_ts_ns: int,
    now_ns: int,
    error_reason: str = "",
) -> int:
    """Seconds until the next attempt, or NO_INTERVAL to stop.

    ``attempt`` is 0-based (the attempt that just failed). Mirrors
    getBackoffInterval (/root/reference/service/history/retry.go)."""
    if policy.maximum_attempts == 0 and policy.expiration_seconds == 0:
        return NO_INTERVAL
    if policy.maximum_attempts > 0 and attempt >= policy.maximum_attempts - 1:
        return NO_INTERVAL
    if error_reason and error_reason in tuple(policy.non_retriable_errors):
        return NO_INTERVAL
    # guard the exponentiation: coefficient ** attempt overflows a
    # float near attempt ~1000, crashing the retry path instead of
    # returning the capped interval. Exact power below the guard so
    # small intervals stay bit-exact (2.0**3 == 8, not exp-log 7.999…)
    import math

    if policy.initial_interval_seconds <= 0:
        # unvalidated policies default to 0 (core/events.RetryPolicy);
        # math.log below would raise — preserve the stop semantics
        return NO_INTERVAL
    if policy.backoff_coefficient <= 1.0:
        interval = float(policy.initial_interval_seconds)
    elif (
        math.log(policy.initial_interval_seconds)
        + attempt * math.log(policy.backoff_coefficient)
    ) > 30:  # e^30 s ≈ 340k years — beyond any cap or expiration
        interval = float(1 << 40)
    else:
        interval = policy.initial_interval_seconds * (
            policy.backoff_coefficient ** attempt
        )
    if policy.maximum_interval_seconds:
        interval = min(interval, policy.maximum_interval_seconds)
    interval = int(interval)
    if interval <= 0:
        return NO_INTERVAL
    if expiration_ts_ns and now_ns + interval * SECOND > expiration_ts_ns:
        return NO_INTERVAL
    return interval


@dataclasses.dataclass
class ExponentialRetryPolicy:
    """Host-operation retry schedule (common/backoff/retrypolicy.go)."""

    initial_interval_s: float = 0.05
    backoff_coefficient: float = 2.0
    maximum_interval_s: float = 10.0
    expiration_interval_s: float = 60.0    # 0 = none
    maximum_attempts: int = 0              # 0 = unlimited
    jitter: float = 0.2

    def compute_next_delay(self, attempt: int, elapsed_s: float) -> float:
        """Delay in seconds before attempt ``attempt`` (1-based), or < 0."""
        if self.maximum_attempts and attempt >= self.maximum_attempts:
            return -1.0
        if self.expiration_interval_s and elapsed_s >= self.expiration_interval_s:
            return -1.0
        d = self.initial_interval_s * (self.backoff_coefficient ** (attempt - 1))
        d = min(d, self.maximum_interval_s)
        if self.jitter:
            d *= 1 + random.uniform(-self.jitter, self.jitter)
        return d


class BackoffLadder:
    """Error-backoff ladder for pump loops (one shared implementation
    for the replication pump, the serving tick pump, and the autopilot
    epoch loop — they each grew their own copy before this).

    Contract:

    * ``failure()`` returns the delay to sleep after a FAILED cycle —
      the current rung, jittered down by up to ``jitter`` — and doubles
      the rung, capped at ``cap_s``;
    * ``success()`` resets the ladder to ``base_s`` so a healed
      dependency resumes at full cadence immediately;
    * jitter is multiplicative-down (``d * (1 - jitter * rng())``) so
      concurrent loops sharing one dead dependency don't retry in
      phase, and the returned delay never exceeds the cap.
    """

    def __init__(
        self,
        base_s: float,
        cap_s: float,
        jitter: float = 0.0,
        rng: Optional[random.Random] = None,
    ) -> None:
        if base_s <= 0:
            raise ValueError("backoff ladder: base_s must be > 0")
        if cap_s < base_s:
            raise ValueError("backoff ladder: cap_s must be >= base_s")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("backoff ladder: jitter must be in [0, 1)")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.jitter = float(jitter)
        self._rng = rng if rng is not None else random.Random()
        self._delay = self.base_s
        self.failures = 0

    @property
    def current_s(self) -> float:
        """The rung the next ``failure()`` will sleep (unjittered)."""
        return self._delay

    def failure(self) -> float:
        """Record a failed cycle; return the (jittered) sleep delay."""
        self.failures += 1
        d = self._delay
        self._delay = min(self._delay * 2.0, self.cap_s)
        if self.jitter:
            d *= 1.0 - self.jitter * self._rng.random()
        return d

    def success(self) -> None:
        """Reset: the next failure starts back at ``base_s``."""
        self._delay = self.base_s


T = TypeVar("T")


class NonRetriableError(Exception):
    """Wrap an error to break out of Retry immediately."""


def retry(
    op: Callable[[], T],
    policy: Optional[ExponentialRetryPolicy] = None,
    is_retriable: Callable[[Exception], bool] = lambda e: True,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Run ``op`` with exponential backoff until success/exhaustion."""
    policy = policy or ExponentialRetryPolicy()
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return op()
        except NonRetriableError as e:
            raise (e.__cause__ or e)
        except Exception as e:  # noqa: BLE001 — predicate decides
            if not is_retriable(e):
                raise
            delay = policy.compute_next_delay(
                attempt, time.monotonic() - start
            )
            if delay < 0:
                raise
            sleep(delay)
