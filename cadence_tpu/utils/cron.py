"""Cron schedule parsing for workflow cron restarts.

Reference: the reference validates and evaluates ``cronSchedule`` with
robfig/cron (common/util.go ValidateCronSchedule; the backoff
computation in service/history/mutableStateBuilder.go
GetCronBackoffDuration). This build implements the same surface
natively: the standard 5-field spec ``minute hour day-of-month month
day-of-week`` (``*``, lists, ranges, ``/step``) plus robfig's
``@every <N>(s|m|h)`` fixed-interval form, which the canary uses for
sub-minute probe cadence.

All evaluation is UTC, matching the reference.
"""

from __future__ import annotations

import calendar
import re
import time
from typing import Optional, Set

_EVERY_RE = re.compile(r"@every\s+(\d+)(s|m|h)$")

_FIELD_RANGES = (
    (0, 59),   # minute
    (0, 23),   # hour
    (1, 31),   # day of month
    (1, 12),   # month
    (0, 6),    # day of week (0 = Sunday)
)


def _parse_field(field: str, lo: int, hi: int) -> Optional[Set[int]]:
    """One cron field → the set of matching values, or None on error."""
    out: Set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            if not step_s.isdigit() or int(step_s) <= 0:
                return None
            step = int(step_s)
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, _, b = part.partition("-")
            if not (a.isdigit() and b.isdigit()):
                return None
            start, end = int(a), int(b)
        elif part.isdigit():
            start = int(part)
            # a bare value with a step ("3/5") ranges to the max,
            # following the de-facto cron convention
            end = hi if step > 1 else start
        else:
            return None
        if start < lo or end > hi or start > end:
            return None
        out.update(range(start, end + 1, step))
    return out


class CronSchedule:
    """A parsed 5-field cron spec or @every interval."""

    def __init__(self, spec: str) -> None:
        spec = spec.strip()
        self.spec = spec
        self.every_seconds = 0
        self.fields = None
        m = _EVERY_RE.match(spec)
        if m:
            n = int(m.group(1))
            self.every_seconds = n * {"s": 1, "m": 60, "h": 3600}[m.group(2)]
            if self.every_seconds <= 0:
                raise ValueError(f"invalid @every interval in {spec!r}")
            return
        parts = spec.split()
        if len(parts) != 5:
            raise ValueError(
                f"cron spec {spec!r}: want 5 fields or '@every <dur>'"
            )
        fields = []
        for part, (lo, hi) in zip(parts, _FIELD_RANGES):
            vals = _parse_field(part, lo, hi)
            if vals is None:
                raise ValueError(f"cron spec {spec!r}: bad field {part!r}")
            fields.append(vals)
        self.fields = fields
        # dom/dow OR rule: when both are restricted, either may match
        self.dom_star = parts[2] == "*"
        self.dow_star = parts[4] == "*"

    def _day_matches(self, tm: time.struct_time) -> bool:
        _, _, dom, month, dow = self.fields
        if tm.tm_mon not in month:
            return False
        dom_ok = tm.tm_mday in dom
        # cron encodes Sunday as 0; struct_tm wday has Monday == 0
        dow_ok = ((tm.tm_wday + 1) % 7) in dow
        if self.dom_star or self.dow_star:
            return dom_ok and dow_ok
        # both restricted: either matches (standard cron OR rule)
        return dom_ok or dow_ok

    def next_delay_seconds(self, now_s: float, anchor_s: float = None) -> int:
        """Whole seconds from ``now_s`` (epoch) until the next fire; the
        reference's GetCronBackoffDuration equivalent. Always > 0.

        ``anchor_s`` is the run's execution-start time: '@every N'
        fires stay aligned to anchor + k*N (the reference steps
        schedule.Next from start past close, backoff/cron.go:56-63)
        instead of drifting later by each run's own duration. Field
        specs are wall-clock anchored already, so anchor_s is moot there.

        Scans day-by-day (≤ ~1830 iterations over a 5-year horizon, the
        same horizon robfig/cron uses) so sparse specs like a leap-day
        '0 0 29 2 *' resolve without a minute-by-minute year walk.
        """
        if self.every_seconds:
            if anchor_s is not None and anchor_s <= now_s:
                k = int((now_s - anchor_s) // self.every_seconds) + 1
                import math

                return max(1, int(
                    math.ceil(anchor_s + k * self.every_seconds - now_s)))
            return self.every_seconds
        minute, hour, _, _, _ = self.fields
        minutes = sorted(minute)
        hours = sorted(hour)
        t = (int(now_s) // 60 + 1) * 60  # next whole minute
        tm = time.gmtime(t)
        # midnight of the starting day
        day0 = t - tm.tm_hour * 3600 - tm.tm_min * 60 - tm.tm_sec
        for day in range(366 * 5 + 1):
            day_t = day0 + day * 86400
            day_tm = time.gmtime(day_t)
            if not self._day_matches(day_tm):
                continue
            for h in hours:
                for m in minutes:
                    fire = day_t + h * 3600 + m * 60
                    if fire >= t:
                        return max(1, fire - int(now_s))
        raise ValueError(f"cron spec {self.spec!r} never fires")


def validate_cron_schedule(spec: str) -> None:
    """Raise ValueError on a bad spec (frontend request validation)."""
    if spec:
        CronSchedule(spec)


def next_cron_delay_seconds(
    spec: str, now_s: float, anchor_s: float = None,
) -> int:
    """Seconds until the next cron fire, or 0 when spec is empty/bad."""
    if not spec:
        return 0
    try:
        return CronSchedule(spec).next_delay_seconds(now_s, anchor_s)
    except ValueError:
        return 0
