"""TimeSource abstraction — real and fake clocks.

Mirrors the reference's clock.TimeSource
(/root/reference/common/clock/time_source.go): every runtime component
takes a TimeSource so tests can drive timer queues deterministically.
All times are int nanoseconds since epoch (the unit the event model and
tensor packer already use)."""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Tuple

SECOND = 1_000_000_000
MILLISECOND = 1_000_000


class TimeSource:
    def now(self) -> int:
        """Nanoseconds since epoch."""
        raise NotImplementedError

    def sleep(self, duration_ns: int) -> None:
        raise NotImplementedError


class RealTimeSource(TimeSource):
    def now(self) -> int:
        return time.time_ns()

    def sleep(self, duration_ns: int) -> None:
        if duration_ns > 0:
            time.sleep(duration_ns / SECOND)


class FakeTimeSource(TimeSource):
    """Manually-advanced clock; wakes sleepers whose deadline passed."""

    def __init__(self, start_ns: int = 1_700_000_000 * SECOND) -> None:
        self._now = start_ns
        self._cond = threading.Condition()

    def now(self) -> int:
        with self._cond:
            return self._now

    def sleep(self, duration_ns: int) -> None:
        deadline = self.now() + duration_ns
        with self._cond:
            while self._now < deadline:
                self._cond.wait(timeout=1.0)

    def advance(self, duration_ns: int) -> None:
        with self._cond:
            self._now += duration_ns
            self._cond.notify_all()

    def set(self, now_ns: int) -> None:
        with self._cond:
            self._now = max(self._now, now_ns)
            self._cond.notify_all()
