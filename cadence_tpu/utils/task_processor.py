"""Keyed sequential task processing.

Reference: common/task/sequentialTaskProcessor.go — tasks that share a
key (a workflow run, a shard, a partition) must execute in submission
order, while distinct keys spread over a fixed worker pool. The
reference backs its replication task processing with this; here the
replication consumers (runtime/replication/processor.py) do the same.

Design: one dict of per-key FIFO deques. The first submit for an idle
key claims it and schedules a drainer on the pool; the drainer runs
that key's tasks in order until the deque empties, then releases the
key. A task that raises is logged and dropped — ordering of the
SURVIVING tasks is preserved, and the caller can wait on a per-batch
barrier via :meth:`flush`.
"""

from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Deque, Dict, Hashable, Optional

from cadence_tpu.utils.log import get_logger


class KeyedSequentialProcessor:
    def __init__(
        self, worker_count: int = 4, name: str = "keyed",
        on_error: Optional[Callable[[Hashable, BaseException], None]] = None,
    ) -> None:
        self._pool = ThreadPoolExecutor(
            max_workers=worker_count, thread_name_prefix=f"{name}-seq"
        )
        self._lock = threading.Lock()
        self._queues: Dict[Hashable, Deque[Callable[[], None]]] = {}
        self._idle = threading.Condition(self._lock)
        self._pending = 0
        self._log = get_logger(f"cadence_tpu.task.{name}")
        self._on_error = on_error
        self._shutdown = False

    def submit(self, key: Hashable, fn: Callable[[], None]) -> None:
        """Run ``fn`` after every previously submitted task of ``key``;
        tasks of other keys run concurrently."""
        with self._lock:
            if self._shutdown:
                raise RuntimeError("processor is shut down")
            self._pending += 1
            q = self._queues.get(key)
            if q is not None:
                q.append(fn)
                return
            self._queues[key] = deque([fn])
            # under the lock: keeps the shutdown check and the pool
            # submission atomic vs shutdown() (pool.submit never blocks
            # on task execution, so holding the lock here is safe)
            try:
                self._pool.submit(self._drain_key, key)
            except BaseException:
                self._pending -= 1
                del self._queues[key]
                raise

    def _drain_key(self, key: Hashable) -> None:
        while True:
            with self._lock:
                q = self._queues[key]
                if not q:
                    del self._queues[key]
                    return
                fn = q.popleft()
            try:
                fn()
            except Exception as e:
                if self._on_error is not None:
                    try:
                        self._on_error(key, e)
                    except Exception:
                        self._log.exception("on_error callback failed")
                else:
                    self._log.exception(f"task for key {key!r} raised")
            except BaseException:
                # SystemExit/KeyboardInterrupt reaching a worker would
                # otherwise leave the key claimed with a drainer-less
                # queue: that key's tasks silently stop applying and
                # flush() never returns. Drop the key's queue (its
                # pending count included), then let the executor
                # surface it.
                with self._lock:
                    dropped = self._queues.pop(key, None)
                    self._pending -= len(dropped) if dropped else 0
                raise
            finally:
                with self._lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.notify_all()

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Block until every task submitted so far has finished."""
        with self._lock:
            return self._idle.wait_for(
                lambda: self._pending == 0, timeout=timeout_s
            )

    def pending(self) -> int:
        with self._lock:
            return self._pending

    @property
    def is_shutdown(self) -> bool:
        with self._lock:
            return self._shutdown

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._shutdown = True
        self._pool.shutdown(wait=wait)
