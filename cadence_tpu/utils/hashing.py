"""Stable 32-bit string hashing.

Used for (a) workflowID → shard routing (the reference uses
farm.Fingerprint32, /root/reference/common/util.go:249-251 — we use FNV-1a,
any stable uniform 32-bit hash serves the contract) and (b) string →
int32 slot keys during tensor packing (activity IDs, timer IDs), since
on-device transitions never need the string itself.
"""

from __future__ import annotations

_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619
_MASK32 = 0xFFFFFFFF


def fnv1a32(s: str) -> int:
    """FNV-1a over utf-8 bytes, full uint32 range."""
    h = _FNV_OFFSET
    for byte in s.encode("utf-8"):
        h ^= byte
        h = (h * _FNV_PRIME) & _MASK32
    return h


def hash31(s: str) -> int:
    """Non-negative int31 hash — safe to store in an int32 tensor."""
    return fnv1a32(s) & 0x7FFFFFFF


def shard_for_workflow(workflow_id: str, num_shards: int) -> int:
    """workflowID → shard (reference: common/util.go:249-251)."""
    return fnv1a32(workflow_id) % num_shards
