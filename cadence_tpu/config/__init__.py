"""Static server configuration (YAML) + service bootstrap.

Reference: common/service/config/config.go (the YAML config structs:
persistence, ringpop, per-service rpc/metrics, clusterMetadata,
archival, dynamicconfig) and cmd/server/server.go:207-219 (the
--services switch assembling only the requested services in one
process). See config/development.yaml for a sample.
"""

from .static import (
    ChaosConfig,
    ClusterConfig,
    ConfigError,
    ClusterEntry,
    PersistenceConfig,
    RingConfig,
    ServerConfig,
    ServiceConfig,
    load_config,
    load_config_dict,
)
from .bootstrap import RunningServer, start_services

__all__ = [
    "ChaosConfig",
    "ClusterConfig",
    "ConfigError",
    "ClusterEntry",
    "PersistenceConfig",
    "RingConfig",
    "ServerConfig",
    "ServiceConfig",
    "RunningServer",
    "load_config",
    "load_config_dict",
    "start_services",
]
