"""YAML config structs + loader.

Reference: common/service/config/config.go — the static (per-env YAML)
half of the config system; the hot-reload half is
utils/dynamicconfig.py. Unknown keys are rejected so a typo'd config
fails at boot, matching the reference's strict yaml decoding.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

SERVICES = ("frontend", "history", "matching", "worker")


class ConfigError(ValueError):
    pass


@dataclasses.dataclass
class PersistenceConfig:
    """ref config.go Persistence: defaultStore + numHistoryShards; the
    datastore plugins here are 'memory' and 'sqlite'."""

    default_store: str = "memory"        # memory | sqlite
    sqlite_path: str = ""                # required for sqlite
    num_history_shards: int = 4
    # True (dev/onebox): bring the schema to current at boot.
    # False (production): boot REFUSES to start unless the database is
    # already at this build's schema version — the operator runs
    # `cadence-tpu schema update` explicitly (ref cmd/server/cadence.go:66)
    auto_setup_schema: bool = True

    def validate(self) -> None:
        if self.default_store not in ("memory", "sqlite"):
            raise ConfigError(
                f"persistence.default_store: unknown store "
                f"'{self.default_store}'"
            )
        if self.default_store == "sqlite" and not self.sqlite_path:
            raise ConfigError("persistence.sqlite_path required for sqlite")
        if self.num_history_shards < 1:
            raise ConfigError("persistence.num_history_shards must be >= 1")


@dataclasses.dataclass
class ServiceConfig:
    """ref config.go Service{RPC, Metrics, PProf} — the rpc bind
    address doubles as the host's ring identity."""

    rpc_address: str = "127.0.0.1:0"
    # ref config.go Service.PProf.Port: 0 = diagnostics endpoint off
    pprof_port: int = 0


@dataclasses.dataclass
class RingConfig:
    """ref config.go Ringpop (bootstrapHosts): static host lists per
    service ring; identities are dial addresses. The failure detector
    (membership.FailureDetector, SWIM stand-in) probes ring peers and
    evicts dead hosts; interval 0 disables it."""

    bootstrap_hosts: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict
    )
    probe_interval_seconds: float = 1.0
    failure_threshold: int = 3


@dataclasses.dataclass
class ClusterEntry:
    initial_failover_version: int = 0
    enabled: bool = True
    rpc_address: str = ""


@dataclasses.dataclass
class ClusterConfig:
    """ref config.go ClusterMetadata."""

    enable_global_domain: bool = False
    failover_version_increment: int = 10
    master_cluster_name: str = ""
    current_cluster_name: str = ""
    cluster_info: Dict[str, ClusterEntry] = dataclasses.field(
        default_factory=dict
    )

    def validate(self) -> None:
        if not self.cluster_info:
            return
        for name in (self.master_cluster_name, self.current_cluster_name):
            if name and name not in self.cluster_info:
                raise ConfigError(
                    f"clusterMetadata: cluster '{name}' not in cluster_info"
                )


@dataclasses.dataclass
class ChaosConfig:
    """Deterministic fault injection (testing/faults.py), config-armed.

    ``rules`` are FaultRule field dicts (site/method patterns, shard
    pin, probability, after_calls/max_faults window, action, error,
    latency_s). Same seed + same workload → same fault sequence. OFF by
    default, and when off the fault decorator is never even installed —
    a production config pays nothing for this section existing."""

    enabled: bool = False
    seed: int = 0
    rules: List[Dict] = dataclasses.field(default_factory=list)

    def validate(self) -> None:
        if not self.enabled and not self.rules:
            return
        try:
            self.build_schedule(force=True)
        except (ValueError, TypeError) as e:
            raise ConfigError(f"chaos.rules: {e}")

    def build_schedule(self, metrics=None, force: bool = False):
        """The FaultSchedule this section describes, or None when
        disabled (``force`` builds regardless — validation)."""
        if not self.enabled and not force:
            return None
        from cadence_tpu.testing.faults import FaultSchedule
        from cadence_tpu.utils.metrics import NOOP

        return FaultSchedule.from_dicts(
            self.rules, seed=self.seed, metrics=metrics or NOOP
        )


@dataclasses.dataclass
class QueuesConfig:
    """Parallel queue execution (runtime/queues/parallel.py).

    ``parallelism`` > 0 replaces the per-queue sequential pump threads
    with one shared ParallelQueueExecutor draining every owned shard's
    transfer/timer queues in conflict-keyed waves of at most that many
    concurrent groups. 0 (the default) keeps the sequential pumps —
    the gate is OFF unless a config opts in. ``matrixPath`` names the
    commutativity-matrix artifact (``scripts/run_lint.sh`` regenerates
    it); empty uses the live in-process footprint table. A stale or
    missing artifact degrades loudly to sequential scheduling
    (``parqueue_matrix_stale``)."""

    parallelism: int = 0
    batch_size: int = 64
    poll_interval_ms: int = 50
    matrix_path: str = ""

    def validate(self) -> None:
        if self.parallelism < 0:
            raise ConfigError("queues.parallelism must be >= 0")
        if self.batch_size <= 0:
            raise ConfigError("queues.batchSize must be > 0")
        if self.poll_interval_ms <= 0:
            raise ConfigError("queues.pollIntervalMs must be > 0")

    def build_executor(self, metrics=None):
        """The ParallelQueueExecutor this section describes, or None
        when the gate is off (sequential pumps)."""
        if self.parallelism <= 0:
            return None
        from cadence_tpu.runtime.queues.parallel import (
            ParallelQueueExecutor,
        )

        return ParallelQueueExecutor(
            parallelism=self.parallelism,
            batch_size=self.batch_size,
            poll_interval_s=self.poll_interval_ms / 1000.0,
            matrix_path=self.matrix_path or None,
            metrics=metrics,
        )


@dataclasses.dataclass
class CheckpointConfig:
    """Checkpointed incremental replay (cadence_tpu/checkpoint/).

    When enabled, every history shard's state rebuilder resumes replays
    from the nearest durable snapshot and writes fresh ones —
    ``everyEvents`` sets the snapshot cadence (a new one only when the
    run tip advanced that many events), ``keepLast`` the per-run-tree
    retention. The store rides the persistence bundle (memory or
    sqlite, matching the configured datastore), so chaos rules on
    ``persistence.checkpoint`` exercise the full-replay fallback. OFF
    by default: a disabled section builds nothing."""

    enabled: bool = False
    every_events: int = 256
    keep_last: int = 2

    def validate(self) -> None:
        try:
            self._policy()
        except ValueError as e:
            raise ConfigError(f"checkpoint: {e}")

    def _policy(self):
        from cadence_tpu.checkpoint import CheckpointPolicy

        policy = CheckpointPolicy(
            every_events=self.every_events, keep_last=self.keep_last
        )
        policy.validate()
        return policy

    def build_manager(self, store=None):
        """The CheckpointManager this section describes, or None when
        disabled. ``store``: the persistence bundle's checkpoint store
        (falls back to a fresh in-memory store)."""
        if not self.enabled:
            return None
        from cadence_tpu.checkpoint import (
            CheckpointManager,
            MemoryCheckpointStore,
        )

        return CheckpointManager(
            store if store is not None else MemoryCheckpointStore(),
            policy=self._policy(),
        )


@dataclasses.dataclass
class ServingConfig:
    """Continuous-batching serving engine (cadence_tpu/serving/).

    When enabled, the history service keeps hot workflows' state rows
    resident in a fixed-``lanes`` device tensor: every persisted event
    batch marks the lane behind (O(1) on the persist path), the next
    serving tick composes just the Δ suffix through the assoc affine
    algebra, and serving reads answer from the resident row with no
    replay. ``idleTicks`` is the LRU eviction horizon (a lane untouched
    that many ticks flushes back through the checkpoint plane and its
    slot is recycled for the admission queue). OFF by default: a
    disabled section builds nothing and the persist path pays nothing.
    """

    enabled: bool = False
    lanes: int = 64
    idle_ticks: int = 256
    # overload control plane (ISSUE 15): the fair-admission refill —
    # per-domain base weights (missing domains use defaultWeight),
    # a per-domain refill quota (tokens/sec + burst; 0 = unmetered),
    # the deadline-aging boost (priority per refill round parked —
    # the starvation-free guarantee), and the age at which an aged bid
    # bypasses its domain quota entirely
    domain_weights: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )
    default_weight: float = 1.0
    quota_rps: float = 0.0
    quota_burst: int = 0
    aging_boost: float = 1.0
    starvation_recycles: int = 8
    # the background tick pump's cadence (ms); 0 disables the pump and
    # ticks ride reads/appends as before — write-heavy lanes then have
    # no staleness bound. NOTE: with a pump, ``idleTicks`` acquires a
    # wall-clock meaning — an untouched lane evicts after roughly
    # idleTicks × tickIntervalMs, so size the pair together
    tick_interval_ms: float = 0.0

    def validate(self) -> None:
        # validation is INLINE (mirroring AdmissionPolicy.validate) on
        # purpose: importing cadence_tpu.serving here would pull jax
        # into every process that merely loads a config — including
        # frontend/matching-only hosts that never build an engine
        if self.lanes < 1:
            raise ConfigError("serving.lanes must be >= 1")
        if self.idle_ticks < 1:
            raise ConfigError("serving.idleTicks must be >= 1")
        if self.tick_interval_ms < 0:
            raise ConfigError("serving.tickIntervalMs must be >= 0")
        if self.default_weight <= 0:
            raise ConfigError("serving.defaultWeight must be > 0")
        for dom, w in self.domain_weights.items():
            if w <= 0:
                raise ConfigError(
                    f"serving.domainWeights['{dom}'] must be > 0"
                )
        if self.quota_rps < 0 or self.quota_burst < 0:
            raise ConfigError("serving: negative quota")
        if self.aging_boost <= 0:
            raise ConfigError("serving.agingBoost must be > 0")
        if self.starvation_recycles < 1:
            raise ConfigError(
                "serving.starvationRecycles must be >= 1"
            )

    def _admission_policy(self):
        from cadence_tpu.serving import AdmissionPolicy

        policy = AdmissionPolicy(
            domain_weights=dict(self.domain_weights),
            default_weight=self.default_weight,
            quota_rps=self.quota_rps,
            quota_burst=self.quota_burst,
            aging_boost=self.aging_boost,
            starvation_recycles=self.starvation_recycles,
        )
        policy.validate()
        return policy

    def build_engine(self, checkpoints=None, history=None, metrics=None):
        """The ResidentEngine this section describes, or None when
        disabled. ``checkpoints``/``history``: the host's
        CheckpointManager (eviction flush + resume seeding; may be
        None) and the persistence bundle's history manager (admission
        reads + the persist-feed catch-up)."""
        if not self.enabled:
            return None
        from cadence_tpu.serving import ResidentEngine

        return ResidentEngine(
            lanes=self.lanes, idle_ticks=self.idle_ticks,
            checkpoints=checkpoints, history=history, metrics=metrics,
            admission=self._admission_policy(),
            tick_interval_s=self.tick_interval_ms / 1e3,
        )


@dataclasses.dataclass
class ReshardingConfig:
    """Elastic resharding (runtime/resharding.py).

    ``drainTimeoutSeconds`` bounds the fence-drain step of a handoff —
    a shard whose queues cannot quiesce in time rolls the whole
    reconfiguration back. ``checkpointFlush`` ships ReplayCheckpoint
    snapshots to the new owner (suffix-only replay); off, the new owner
    cold-rebuilds from the execution store (still correct, just cold).
    Enabled by default: the coordinator only runs on explicit admin
    verbs, so an idle section costs nothing."""

    enabled: bool = True
    drain_timeout_s: float = 10.0
    checkpoint_flush: bool = True

    def validate(self) -> None:
        if self.drain_timeout_s <= 0:
            raise ConfigError(
                "resharding.drainTimeoutSeconds must be > 0"
            )


@dataclasses.dataclass
class ReplicationConfig:
    """Bandwidth-adaptive geo-replication (runtime/replication/
    transport.py).

    ``adaptive`` gates the whole transport: off, the consumer is the
    pre-adaptive pure event-stream puller. ``hysteresis``/``minDwell``
    damp the event-vs-snapshot mode controller (a switch requires the
    challenger to win the cost model by the factor, that many decisions
    in a row); ``minGapEvents`` floors the gap a snapshot may ever ship
    for; ``snapshotBytesPrior`` seeds the cost model before the first
    observed snapshot transfer. ``backoffMaxSeconds`` caps the pump's
    jittered exponential retry backoff on failed cycles."""

    adaptive: bool = True
    hysteresis: float = 1.5
    min_dwell: int = 2
    min_gap_events: int = 32
    snapshot_bytes_prior: float = 64 * 1024.0
    backoff_max_s: float = 5.0

    def validate(self) -> None:
        if self.hysteresis < 1.0:
            raise ConfigError("replication.hysteresis must be >= 1.0")
        if self.min_dwell < 1:
            raise ConfigError("replication.minDwell must be >= 1")
        if self.min_gap_events < 1:
            raise ConfigError("replication.minGapEvents must be >= 1")
        if self.snapshot_bytes_prior <= 0:
            raise ConfigError(
                "replication.snapshotBytesPrior must be > 0"
            )
        if self.backoff_max_s <= 0:
            raise ConfigError(
                "replication.backoffMaxSeconds must be > 0"
            )


@dataclasses.dataclass
class AutopilotConfig:
    """Capacity autopilot (runtime/autopilot.py) — closed-loop control
    from admission rates to shard topology.

    Off by default: the controller only ever runs when an operator
    turns the section on. ``targetP99Ms``/``targetShedFrac`` are the
    setpoints pressure is measured against; ``hysteresis``/``minDwell``
    damp the overload gate (challenger-must-win, like replication's
    mode controller); ``maxStepFrac`` bounds how far any rate moves per
    epoch; ``headroomFrac`` is the margin rates keep above observed
    load when healthy. ``cooldownEpochs``/``reshardCooldownEpochs``
    space actuations per plane; ``guardrailWindowEpochs``/
    ``guardrailRegression``/``freezeEpochs`` shape the do-no-harm
    freeze (p99 regressing past the factor after our own recent actions
    reverts to last-known-good and stops actuating). Shard heuristics:
    a shard is hot when its queue depth is ≥ ``hotShardDepth`` AND
    ``hotShardFactor`` × the mean; a pair is mergeable when both sit
    ≤ ``coldShardFrac`` × the mean. ``backoffMaxSeconds`` caps both the
    epoch loop's error backoff and the reshard-failure proposal block
    (a failed plan is never hot-retried)."""

    enabled: bool = False
    epoch_interval_s: float = 5.0
    target_p99_ms: float = 250.0
    target_shed_frac: float = 0.05
    max_step_frac: float = 0.25
    headroom_frac: float = 0.5
    ewma_alpha: float = 0.4
    hysteresis: float = 1.25
    min_dwell: int = 2
    cooldown_epochs: int = 2
    reshard_cooldown_epochs: int = 4
    max_plans_per_epoch: int = 2
    min_rps: float = 10.0
    max_rps: float = 1e6
    min_shards: int = 1
    max_shards: int = 64
    hot_shard_depth: int = 64
    hot_shard_factor: float = 4.0
    cold_shard_frac: float = 0.25
    guardrail_window: int = 3
    guardrail_regression: float = 1.5
    freeze_epochs: int = 4
    backoff_max_s: float = 60.0

    def validate(self) -> None:
        if self.epoch_interval_s <= 0:
            raise ConfigError(
                "autopilot.epochIntervalSeconds must be > 0"
            )
        if self.target_p99_ms <= 0:
            raise ConfigError("autopilot.targetP99Ms must be > 0")
        if not 0.0 < self.target_shed_frac <= 1.0:
            raise ConfigError(
                "autopilot.targetShedFrac must be in (0, 1]"
            )
        if not 0.0 < self.max_step_frac < 1.0:
            raise ConfigError(
                "autopilot.maxStepFrac must be in (0, 1)"
            )
        if self.headroom_frac < 0:
            raise ConfigError("autopilot.headroomFrac must be >= 0")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ConfigError("autopilot.ewmaAlpha must be in (0, 1]")
        if self.hysteresis < 1.0:
            raise ConfigError("autopilot.hysteresis must be >= 1.0")
        if self.min_dwell < 1:
            raise ConfigError("autopilot.minDwell must be >= 1")
        if self.cooldown_epochs < 0 or self.reshard_cooldown_epochs < 0:
            raise ConfigError("autopilot: negative cooldown")
        if self.max_plans_per_epoch < 1:
            raise ConfigError(
                "autopilot.maxPlansPerEpoch must be >= 1"
            )
        if not 0 < self.min_rps <= self.max_rps:
            raise ConfigError(
                "autopilot: need 0 < minRps <= maxRps"
            )
        if not 1 <= self.min_shards <= self.max_shards:
            raise ConfigError(
                "autopilot: need 1 <= minShards <= maxShards"
            )
        if self.hot_shard_depth < 1:
            raise ConfigError("autopilot.hotShardDepth must be >= 1")
        if self.hot_shard_factor < 1.0:
            raise ConfigError(
                "autopilot.hotShardFactor must be >= 1.0"
            )
        if not 0.0 <= self.cold_shard_frac < 1.0:
            raise ConfigError(
                "autopilot.coldShardFrac must be in [0, 1)"
            )
        if self.guardrail_window < 1:
            raise ConfigError(
                "autopilot.guardrailWindowEpochs must be >= 1"
            )
        if self.guardrail_regression <= 1.0:
            raise ConfigError(
                "autopilot.guardrailRegression must be > 1.0"
            )
        if self.freeze_epochs < 1:
            raise ConfigError("autopilot.freezeEpochs must be >= 1")
        if self.backoff_max_s <= 0:
            raise ConfigError(
                "autopilot.backoffMaxSeconds must be > 0"
            )


@dataclasses.dataclass
class TelemetryConfig:
    """Unified telemetry plane (utils/tracing.py + utils/metrics.py).

    ``sampleRate`` is the probability an RPC endpoint roots a new trace
    for a request that arrived without one (0.0, the default, disables
    implicit roots entirely — explicitly started traces still record);
    ``traceCapacity`` bounds the flight-recorder ring buffer (spans);
    ``maxSeries`` caps per-registry metric-series cardinality (overflow
    collapses into the ``overflow="true"`` sink and bumps
    ``metrics_dropped_series``). The unsampled path stays a thread-local
    read — the bench ``telemetry_overhead`` guard pins it at ≤3%."""

    sample_rate: float = 0.0
    trace_capacity: int = 4096
    max_series: int = 8192

    def validate(self) -> None:
        if not 0.0 <= self.sample_rate <= 1.0:
            raise ConfigError("telemetry.sampleRate must be in [0, 1]")
        if self.trace_capacity < 1:
            raise ConfigError("telemetry.traceCapacity must be >= 1")
        if self.max_series < 1:
            raise ConfigError("telemetry.maxSeries must be >= 1")

    def apply(self, metrics=None):
        """Configure the process tracer from this section; returns it."""
        from cadence_tpu.utils.tracing import configure

        return configure(
            sample_rate=self.sample_rate, capacity=self.trace_capacity,
            metrics=metrics,
        )


@dataclasses.dataclass
class ServerConfig:
    persistence: PersistenceConfig = dataclasses.field(
        default_factory=PersistenceConfig
    )
    services: Dict[str, ServiceConfig] = dataclasses.field(
        default_factory=lambda: {s: ServiceConfig() for s in SERVICES}
    )
    ring: RingConfig = dataclasses.field(default_factory=RingConfig)
    cluster: ClusterConfig = dataclasses.field(default_factory=ClusterConfig)
    chaos: ChaosConfig = dataclasses.field(default_factory=ChaosConfig)
    checkpoint: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig
    )
    serving: ServingConfig = dataclasses.field(
        default_factory=ServingConfig
    )
    resharding: ReshardingConfig = dataclasses.field(
        default_factory=ReshardingConfig
    )
    replication: ReplicationConfig = dataclasses.field(
        default_factory=ReplicationConfig
    )
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    autopilot: AutopilotConfig = dataclasses.field(
        default_factory=AutopilotConfig
    )
    queues: QueuesConfig = dataclasses.field(default_factory=QueuesConfig)
    dynamicconfig_path: str = ""
    archival_dir: str = ""

    def validate(self) -> None:
        self.persistence.validate()
        self.cluster.validate()
        self.chaos.validate()
        self.checkpoint.validate()
        self.serving.validate()
        self.resharding.validate()
        self.replication.validate()
        self.telemetry.validate()
        self.autopilot.validate()
        self.queues.validate()
        for name in self.services:
            if name not in SERVICES:
                raise ConfigError(f"services: unknown service '{name}'")

    def build_cluster_metadata(self):
        """ClusterMetadata from the config, or None (single cluster)."""
        if not self.cluster.cluster_info:
            return None
        from cadence_tpu.cluster import ClusterInformation, ClusterMetadata

        return ClusterMetadata(
            enable_global_domain=self.cluster.enable_global_domain,
            failover_version_increment=(
                self.cluster.failover_version_increment
            ),
            master_cluster_name=self.cluster.master_cluster_name,
            current_cluster_name=self.cluster.current_cluster_name,
            cluster_info={
                name: ClusterInformation(
                    initial_failover_version=e.initial_failover_version,
                    enabled=e.enabled,
                    rpc_address=e.rpc_address,
                )
                for name, e in self.cluster.cluster_info.items()
            },
        )


def _take(d: dict, allowed: Dict[str, object], where: str) -> dict:
    out = {}
    for k, v in d.items():
        if k not in allowed:
            raise ConfigError(f"{where}: unknown key '{k}'")
        out[allowed[k]] = v  # type: ignore[index]
    return out


def load_config_dict(raw: dict) -> ServerConfig:
    import copy

    cfg = ServerConfig()
    # deep copy: parsing pops nested keys and must not mutate the
    # caller's dict (a shared dict may build several hosts' configs)
    raw = copy.deepcopy(raw or {})

    p = raw.pop("persistence", None)
    if p:
        cfg.persistence = PersistenceConfig(**_take(p, {
            "defaultStore": "default_store",
            "sqlitePath": "sqlite_path",
            "numHistoryShards": "num_history_shards",
            "autoSetupSchema": "auto_setup_schema",
        }, "persistence"))

    services = raw.pop("services", None)
    if services is not None:
        cfg.services = {}
        for name, sc in (services or {}).items():
            cfg.services[name] = ServiceConfig(**_take(sc or {}, {
                "rpcAddress": "rpc_address",
                "pprofPort": "pprof_port",
            }, f"services.{name}"))

    ring = raw.pop("ring", None)
    if ring:
        cfg.ring = RingConfig(**_take(ring, {
            "bootstrapHosts": "bootstrap_hosts",
            "probeIntervalSeconds": "probe_interval_seconds",
            "failureThreshold": "failure_threshold",
        }, "ring"))

    cm = raw.pop("clusterMetadata", None)
    if cm:
        info = cm.pop("clusterInformation", {}) or {}
        cfg.cluster = ClusterConfig(**_take(cm, {
            "enableGlobalDomain": "enable_global_domain",
            "failoverVersionIncrement": "failover_version_increment",
            "masterClusterName": "master_cluster_name",
            "currentClusterName": "current_cluster_name",
        }, "clusterMetadata"))
        cfg.cluster.cluster_info = {
            name: ClusterEntry(**_take(e or {}, {
                "initialFailoverVersion": "initial_failover_version",
                "enabled": "enabled",
                "rpcAddress": "rpc_address",
            }, f"clusterMetadata.clusterInformation.{name}"))
            for name, e in info.items()
        }

    chaos = raw.pop("chaos", None)
    if chaos:
        cfg.chaos = ChaosConfig(**_take(chaos, {
            "enabled": "enabled",
            "seed": "seed",
            "rules": "rules",
        }, "chaos"))

    ckpt = raw.pop("checkpoint", None)
    if ckpt:
        cfg.checkpoint = CheckpointConfig(**_take(ckpt, {
            "enabled": "enabled",
            "everyEvents": "every_events",
            "keepLast": "keep_last",
        }, "checkpoint"))

    srv = raw.pop("serving", None)
    if srv:
        cfg.serving = ServingConfig(**_take(srv, {
            "enabled": "enabled",
            "lanes": "lanes",
            "idleTicks": "idle_ticks",
            "domainWeights": "domain_weights",
            "defaultWeight": "default_weight",
            "quotaRps": "quota_rps",
            "quotaBurst": "quota_burst",
            "agingBoost": "aging_boost",
            "starvationRecycles": "starvation_recycles",
            "tickIntervalMs": "tick_interval_ms",
        }, "serving"))

    rsh = raw.pop("resharding", None)
    if rsh:
        cfg.resharding = ReshardingConfig(**_take(rsh, {
            "enabled": "enabled",
            "drainTimeoutSeconds": "drain_timeout_s",
            "checkpointFlush": "checkpoint_flush",
        }, "resharding"))

    repl = raw.pop("replication", None)
    if repl:
        cfg.replication = ReplicationConfig(**_take(repl, {
            "adaptive": "adaptive",
            "hysteresis": "hysteresis",
            "minDwell": "min_dwell",
            "minGapEvents": "min_gap_events",
            "snapshotBytesPrior": "snapshot_bytes_prior",
            "backoffMaxSeconds": "backoff_max_s",
        }, "replication"))

    tel = raw.pop("telemetry", None)
    if tel:
        cfg.telemetry = TelemetryConfig(**_take(tel, {
            "sampleRate": "sample_rate",
            "traceCapacity": "trace_capacity",
            "maxSeries": "max_series",
        }, "telemetry"))

    ap = raw.pop("autopilot", None)
    if ap:
        cfg.autopilot = AutopilotConfig(**_take(ap, {
            "enabled": "enabled",
            "epochIntervalSeconds": "epoch_interval_s",
            "targetP99Ms": "target_p99_ms",
            "targetShedFrac": "target_shed_frac",
            "maxStepFrac": "max_step_frac",
            "headroomFrac": "headroom_frac",
            "ewmaAlpha": "ewma_alpha",
            "hysteresis": "hysteresis",
            "minDwell": "min_dwell",
            "cooldownEpochs": "cooldown_epochs",
            "reshardCooldownEpochs": "reshard_cooldown_epochs",
            "maxPlansPerEpoch": "max_plans_per_epoch",
            "minRps": "min_rps",
            "maxRps": "max_rps",
            "minShards": "min_shards",
            "maxShards": "max_shards",
            "hotShardDepth": "hot_shard_depth",
            "hotShardFactor": "hot_shard_factor",
            "coldShardFrac": "cold_shard_frac",
            "guardrailWindowEpochs": "guardrail_window",
            "guardrailRegression": "guardrail_regression",
            "freezeEpochs": "freeze_epochs",
            "backoffMaxSeconds": "backoff_max_s",
        }, "autopilot"))

    q = raw.pop("queues", None)
    if q:
        cfg.queues = QueuesConfig(**_take(q, {
            "parallelism": "parallelism",
            "batchSize": "batch_size",
            "pollIntervalMs": "poll_interval_ms",
            "matrixPath": "matrix_path",
        }, "queues"))

    dc = raw.pop("dynamicConfig", None)
    if dc:
        cfg.dynamicconfig_path = (dc or {}).get("filepath", "")

    arch = raw.pop("archival", None)
    if arch:
        cfg.archival_dir = (arch or {}).get("dir", "")

    if raw:
        raise ConfigError(f"unknown top-level keys: {sorted(raw)}")
    cfg.validate()
    return cfg


def load_config(path: str) -> ServerConfig:
    import yaml

    with open(path) as f:
        return load_config_dict(yaml.safe_load(f) or {})
