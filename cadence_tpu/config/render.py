"""Config template rendering — the docker entrypoint's substitution.

Reference: docker/start-cadence.sh renders docker/config_template.yaml
with dockerize's env templating. Here ``${VAR}`` placeholders are
replaced from the environment; ``*_SEEDS`` variables hold comma lists
of host:port peers and render as quoted YAML flow-sequence items
(unquoted ``host:port`` inside ``[...]`` would parse as a map).

Used by docker/entrypoint.sh (``python -m cadence_tpu.config.render``)
and by the tests that pin the container contract.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Mapping


def render_template(text: str, env: Mapping[str, str]) -> str:
    def value(m: "re.Match[str]") -> str:
        name = m.group(1)
        v = env.get(name, "")
        if name.endswith("_SEEDS"):
            return ", ".join(
                '"%s"' % s.strip() for s in v.split(",") if s.strip()
            )
        return v

    return re.sub(r"\$\{(\w+)\}", value, text)


def main(argv=None) -> None:
    src, dst = (argv or sys.argv[1:])[:2]
    with open(src) as f:
        rendered = render_template(f.read(), os.environ)
    with open(dst, "w") as f:
        f.write(rendered)


if __name__ == "__main__":
    main()
