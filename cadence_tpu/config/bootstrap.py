"""Service bootstrap: config + --services → a running host.

Reference: cmd/server/server.go:207-219 — one process starts only the
requested services; every service resolves its peers through the ring
(bootstrap hosts from config) and the cross-process gRPC plane
(rpc/server.py, client/routed.py). A host running only `frontend`
reaches remote history/matching hosts exactly as the reference's
stateless frontends do.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from .static import SERVICES, ConfigError, ServerConfig


@dataclasses.dataclass
class RunningServer:
    config: ServerConfig
    services: List[str]
    persistence: object
    domains: object
    monitor: object
    frontend: object = None
    admin: object = None
    history: object = None
    matching: object = None
    worker: object = None
    domain_handler: object = None
    history_client: object = None
    matching_client: object = None
    rpc_servers: Dict[str, object] = dataclasses.field(default_factory=dict)
    pprof: object = None
    failure_detector: object = None
    bus: object = None
    # the armed FaultSchedule when the config's chaos section is
    # enabled (operators flip it via faults.arm()/disarm()); None
    # otherwise. `metrics` is the shared Scope whose registry holds
    # faults_injected + the injected-error counters for that run
    faults: object = None
    metrics: object = None
    # CheckpointManager when the checkpoint section is enabled
    checkpoints: object = None
    # serving.ResidentEngine when the serving section is enabled
    # (history hosts only); drained by HistoryService.stop()
    serving: object = None
    # runtime.autopilot.CapacityController when the autopilot section
    # is enabled (history hosts only); stopped by HistoryService.stop()
    autopilot: object = None
    # the programmatic dynamicconfig override layer (InMemoryClient)
    # the autopilot writes rates through; always built so tests and
    # operators can inject overrides live even with autopilot off
    dyncfg_overrides: object = None

    @property
    def addresses(self) -> Dict[str, str]:
        return {name: s.address for name, s in self.rpc_servers.items()}

    def stop(self) -> None:
        if self.failure_detector is not None:
            self.failure_detector.stop()
        if self.pprof is not None:
            self.pprof.stop()
        for s in self.rpc_servers.values():
            s.stop()
        if self.worker is not None:
            self.worker.stop()
        if self.history is not None:
            self.history.stop()
        if self.matching is not None:
            self.matching.shutdown()
        for client in (self.history_client, self.matching_client):
            close = getattr(client, "close", None)
            if close:
                close()


def _build_persistence(cfg: ServerConfig):
    if cfg.persistence.default_store == "sqlite":
        from cadence_tpu.runtime.persistence.sqlite import (
            create_sqlite_bundle,
        )

        return create_sqlite_bundle(
            cfg.persistence.sqlite_path,
            auto_setup=cfg.persistence.auto_setup_schema,
        )
    from cadence_tpu.runtime.persistence.memory import create_memory_bundle

    return create_memory_bundle()


def start_services(
    cfg: ServerConfig,
    services: Optional[List[str]] = None,
    persistence=None,
) -> RunningServer:
    """Assemble and start the requested services (default: all)."""
    from cadence_tpu.client import (
        RoutedHistoryClient,
        RoutedMatchingClient,
    )
    from cadence_tpu.frontend import (
        AdminHandler,
        DomainHandler,
        WorkflowHandler,
    )
    from cadence_tpu.matching import MatchingEngine
    from cadence_tpu.runtime.domains import DomainCache
    from cadence_tpu.runtime.membership import Monitor
    from cadence_tpu.runtime.service import HistoryService
    from cadence_tpu.rpc.server import (
        FrontendRPCServer,
        HistoryRPCServer,
        MatchingRPCServer,
    )

    services = list(services or SERVICES)
    for s in services:
        if s not in SERVICES:
            raise ConfigError(f"unknown service '{s}'")

    persistence = persistence or _build_persistence(cfg)

    # telemetry section first: one metrics scope per host (registry
    # series cap from telemetry.maxSeries) shared by every service
    # plane, and the process tracer configured before any handler is
    # instrumented (utils/tracing.py; sampleRate 0 = no implicit roots).
    # The persistence bundle is always metrics-wrapped now — per-store
    # histogram latencies and the persistence hop of request traces are
    # a production surface, not a chaos-only one.
    from cadence_tpu.runtime.persistence.decorators import wrap_bundle
    from cadence_tpu.utils.metrics import Registry, Scope

    metrics = Scope(Registry(max_series=cfg.telemetry.max_series))
    cfg.telemetry.apply(metrics=metrics)

    # chaos section: fault-inject the whole persistence bundle before
    # anything else sees it, so every service plane on this host runs
    # against the same deterministic fault stream. The schedule, the
    # persistence decorators, and the history service share ONE metrics
    # scope so faults_injected and the injected-error counters land in
    # the same registry operators already read (metrics_defs.py
    # FAULT_METRICS promise)
    faults = None
    if cfg.chaos.enabled:
        faults = cfg.chaos.build_schedule(metrics=metrics)
    persistence = wrap_bundle(persistence, metrics=metrics, faults=faults)

    # checkpoint section: incremental-replay snapshots over the
    # bundle's checkpoint store. Built AFTER the chaos wrap, so a
    # chaos config's persistence.checkpoint rules fault-inject every
    # snapshot read/write this host performs (fallback: full replay)
    checkpoints = cfg.checkpoint.build_manager(
        store=getattr(persistence, "checkpoint", None)
    )

    # serving section: the continuous-batching resident engine over
    # the (chaos-wrapped) history manager + checkpoint plane — history
    # hosts only, since only they see the persist feed
    serving = None
    if "history" in services:
        serving = cfg.serving.build_engine(
            checkpoints=checkpoints,
            history=getattr(persistence, "history", None),
            metrics=metrics,
        )

    domains = DomainCache(persistence.metadata)
    cluster_metadata = cfg.build_cluster_metadata()

    # dynamic config: a programmatic override layer (the autopilot's
    # rate actuator — and the operator's live-injection surface) over
    # the file-watched base when configured (ref cmd/server wiring of
    # dynamicconfig fileBasedClient)
    from cadence_tpu.utils.dynamicconfig import (
        Collection,
        FileBasedClient,
        InMemoryClient,
        LayeredClient,
    )

    dyncfg_overrides = InMemoryClient()
    dyncfg = Collection(LayeredClient(
        dyncfg_overrides,
        FileBasedClient(cfg.dynamicconfig_path)
        if cfg.dynamicconfig_path else None,
    ))

    # the host's ring identity per service is its rpc bind address;
    # bootstrap hosts from config pre-populate the rings so a partial
    # host set still routes to its peers
    def addr(service: str) -> str:
        sc = cfg.services.get(service)
        return sc.rpc_address if sc else "127.0.0.1:0"

    monitor = Monitor(self_identity=addr("history"))
    for service, hosts in cfg.ring.bootstrap_hosts.items():
        monitor.resolver(service).set_hosts(list(hosts))
    for service in services:
        monitor.join(service, addr(service))

    # failure detection (SWIM stand-in): probe ring peers, evict the
    # dead, let the shard controller rebalance (ref rpMonitor.go:44)
    failure_detector = None
    if cfg.ring.probe_interval_seconds > 0:
        from cadence_tpu.rpc.client import grpc_ping
        from cadence_tpu.runtime.membership import FailureDetector

        failure_detector = FailureDetector(
            monitor, grpc_ping,
            own_identities={addr(s) for s in services},
            probe_interval_s=cfg.ring.probe_interval_seconds,
            failure_threshold=cfg.ring.failure_threshold,
        ).start()

    from cadence_tpu.messaging import MessageBus

    out = RunningServer(
        config=cfg, services=services, persistence=persistence,
        domains=domains, monitor=monitor,
        failure_detector=failure_detector,
        # messaging plane exists only where the worker runs: a bus on a
        # frontend/history-only host would make `admin dlq` report an
        # always-empty queue instead of "no message bus on this host"
        bus=MessageBus() if "worker" in services else None,
        faults=faults,
        metrics=metrics,
        checkpoints=checkpoints,
        serving=serving,
        dyncfg_overrides=dyncfg_overrides,
    )
    # one diagnostics endpoint per process (common/pprof.go Start):
    # first configured service's port wins, bound on that service's
    # rpc host (a container binding rpc on 0.0.0.0 wants pprof there
    # too). Diagnostics are non-essential: a bind failure logs and the
    # service plane boots without them, as the reference does.
    for s in services:
        sc = cfg.services.get(s)
        if sc is not None and sc.pprof_port:
            from cadence_tpu.utils.log import get_logger
            from cadence_tpu.utils.pprof import PProfServer

            host = sc.rpc_address.rsplit(":", 1)[0] or "127.0.0.1"
            try:
                out.pprof = PProfServer(
                    port=sc.pprof_port, host=host
                ).start()
            except OSError as e:
                get_logger("cadence_tpu.pprof").warn(
                    f"pprof endpoint {host}:{sc.pprof_port} failed to "
                    f"bind ({e}); continuing without diagnostics"
                )
            break
    out.domain_handler = DomainHandler(
        persistence.metadata, cluster_metadata
    )

    # overload control (ISSUE 15): service-level limiters beyond the
    # frontend's. Domain rates read the dynamicconfig property per
    # call, so a file-watched edit takes effect live; the defaults are
    # effectively-unlimited (the limiter then never sheds)
    from cadence_tpu.utils.quotas import MultiStageRateLimiter

    history_domain_rps = dyncfg.float_property(
        "history.domainRps", 100000.0
    )
    history_limiter = MultiStageRateLimiter(
        global_rps=dyncfg.float_property("history.rps", 100000.0)(),
        domain_rps=lambda _d: history_domain_rps(),
    )
    matching_domain_rps = dyncfg.float_property(
        "matching.domainRps", 100000.0
    )
    matching_limiter = MultiStageRateLimiter(
        global_rps=dyncfg.float_property("matching.rps", 100000.0)(),
        domain_rps=lambda _d: matching_domain_rps(),
    )

    history = None
    if "history" in services:
        # parallel queue execution (config `queues:` section): one
        # shared conflict-keyed wave executor per host, or None when
        # queues.parallelism is 0 (sequential per-queue pumps). A stale
        # matrix artifact degrades the executor loudly to sequential —
        # it never blocks boot.
        queue_executor = cfg.queues.build_executor(metrics=metrics)
        history = HistoryService(
            cfg.persistence.num_history_shards, persistence, domains,
            monitor, cluster_metadata=cluster_metadata,
            # pass the property itself: the file-watched client then
            # serves runtime edits, not a boot-time snapshot
            rebuild_chunk_size=dyncfg.int_property(
                "history.rebuildChunkSize", 0
            ),
            faults=faults,
            metrics=metrics,
            checkpoints=checkpoints,
            serving=serving,
            rate_limiter=history_limiter,
            queue_executor=queue_executor,
        )
        # admin reshard verbs read the section off the service
        history.resharding_config = cfg.resharding
        # adaptive geo-replication knobs for the pull processors
        # (consumed by enable_replication_from / _build_shard)
        history.replication_config = cfg.replication
        out.history = history

        # capacity autopilot (config `autopilot:` section): closed-loop
        # retuning of the limiters above + reshard proposals through
        # the host's shared coordinator. Built BEFORE history.start()
        # (which starts its epoch loop); only the membership-elected
        # host actuates, so every history host wires one identically
        if cfg.autopilot.enabled:
            from cadence_tpu.runtime.autopilot import (
                KEY_HISTORY_DOMAIN_RPS,
                KEY_HISTORY_RPS,
                KEY_MATCHING_RPS,
                KEY_SERVING_QUOTA_RPS,
                CapacityController,
            )

            rate_hooks = {
                KEY_HISTORY_RPS: history_limiter.set_global_rate,
                KEY_MATCHING_RPS: matching_limiter.set_global_rate,
            }
            initial_rates = {
                KEY_HISTORY_RPS: history_limiter.global_rps,
                KEY_MATCHING_RPS: matching_limiter.global_rps,
                # domain rps needs no hook: the limiters re-read the
                # dynamicconfig property per call, and the override
                # layer shadows the file live
                KEY_HISTORY_DOMAIN_RPS: history_domain_rps(),
            }
            if serving is not None and serving.admission_quota_rps() > 0:
                # an unmetered quota (0) stays unmetered: minting a
                # finite cap where the operator set none is a semantic
                # change, not a retune
                rate_hooks[KEY_SERVING_QUOTA_RPS] = (
                    serving.retune_admission
                )
                initial_rates[KEY_SERVING_QUOTA_RPS] = (
                    serving.admission_quota_rps()
                )
            out.autopilot = history.autopilot = CapacityController(
                cfg.autopilot,
                registry=metrics.registry,
                overrides=dyncfg_overrides,
                rate_hooks=rate_hooks,
                initial_rates=initial_rates,
                resharder=(
                    history.reshard_coordinator
                    if cfg.resharding.enabled else None
                ),
                history=history,
                monitor=monitor,
                metrics=metrics,
            )

    hc = RoutedHistoryClient(
        monitor,
        history.controller if history else None,
        num_shards=cfg.persistence.num_history_shards,
        # the host scope: retry_budget_exhausted (layer=client) — the
        # retry-storm breaker firing — must land in the registry
        # operators actually scrape, not NOOP
        metrics=metrics,
    )
    out.history_client = hc

    matching = None
    if "matching" in services:
        matching = MatchingEngine(
            persistence.task, hc, config=dyncfg, metrics=metrics,
            rate_limiter=matching_limiter,
        )
        out.matching = matching
    mc = RoutedMatchingClient(
        monitor, matching, local_identity=addr("matching")
    )
    out.matching_client = mc

    if history is not None:
        history.wire(mc, hc)
        history.start()
        out.rpc_servers["history"] = HistoryRPCServer(
            history, address=addr("history")
        ).start()
    if matching is not None:
        out.rpc_servers["matching"] = MatchingRPCServer(
            matching, address=addr("matching")
        ).start()

    if "frontend" in services:
        visibility = None
        if persistence.visibility is not None:
            from cadence_tpu.visibility import AdvancedVisibilityStore

            visibility = AdvancedVisibilityStore(persistence.visibility)
        out.frontend = WorkflowHandler(
            out.domain_handler, domains, hc, mc, visibility=visibility,
            metrics=metrics,
        )
        out.admin = (
            AdminHandler(history, domains, bus=out.bus)
            if history is not None else None
        )
        out.rpc_servers["frontend"] = FrontendRPCServer(
            out.frontend, out.admin, address=addr("frontend")
        ).start()

    if "worker" in services:
        from cadence_tpu.worker.service import WorkerService

        worker_frontend = out.frontend
        if worker_frontend is None:
            # worker-only host: drive system workflows through a REMOTE
            # frontend (the reference's worker runs against the public
            # API, service/worker/service.go)
            fe_addr = addr("frontend")
            if fe_addr.endswith(":0"):
                raise ConfigError(
                    "worker without a local frontend needs "
                    "services.frontend.rpcAddress pointing at a "
                    "frontend host"
                )
            from cadence_tpu.rpc.client import RemoteFrontend

            worker_frontend = RemoteFrontend(fe_addr)
        out.worker = WorkerService(
            worker_frontend, persistence,
            num_shards=cfg.persistence.num_history_shards,
            bus=out.bus,
            domain_handler=out.domain_handler,
            history_service=history,
        )
        out.worker.start()

    return out
