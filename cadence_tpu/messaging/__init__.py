"""Cross-cluster messaging plane.

Reference: common/messaging/ (kafkaClient.go / kafkaConsumer.go /
kafkaProducer.go) — topic pub/sub with consumer groups, per-message
ack/nack, bounded redelivery, and a dead-letter topic. The TPU build
replaces the Kafka cluster with an in-process broker (the host plane is
gRPC/in-proc; cross-"cluster" traffic in tests rides the same broker the
way host/xdc wires two oneboxes to one Kafka).
"""

from .bus import Message, MessageBus, Consumer, Producer

__all__ = ["Message", "MessageBus", "Consumer", "Producer"]
