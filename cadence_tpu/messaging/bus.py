"""In-process message broker with consumer groups, retry and DLQ.

Reference semantics (common/messaging/kafkaConsumer.go,
service/worker/replicator — retry topic + DLQ wiring in
common/messaging/kafkaClient.go NewConsumer):

- a ``Producer`` appends messages to a topic log;
- each consumer group tracks its own offset into the log;
- a delivered message must be ``ack``-ed or ``nack``-ed; nack re-enqueues
  it until ``max_redelivery`` is exhausted, after which it lands on the
  topic's DLQ (``<topic>-dlq``), matching the reference's
  retry-topic/DLQ-topic pair. Delivery is at-least-once for consumers
  that honor the ack/nack protocol; a consumer that drops a message
  without acking loses it (there is no rebalance-driven redelivery).

The broker is deliberately process-local: the runtime's host plane keeps
queue state on the host and only ships packed tensors to the device, so
"Kafka" here is a contract (at-least-once, per-group offsets, DLQ), not
a daemon.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from cadence_tpu.utils.log import get_logger

_log = get_logger("cadence_tpu.messaging")


@dataclasses.dataclass
class Message:
    topic: str
    key: str
    value: Any
    offset: int = -1
    partition: int = 0
    redelivery_count: int = 0
    # why the last handler attempt failed (set by Consumer.drain) —
    # rides into the DLQ so dead letters carry their diagnosis
    last_error: str = ""


class _TopicLog:
    def __init__(self, name: str) -> None:
        self.name = name
        self.messages: List[Message] = []
        # monotonic id source: DLQ purge/merge REMOVE entries, so
        # len(messages) would recycle ids of surviving dead letters and
        # corrupt the operator watermark verbs; main topics are
        # append-only (next_offset == len there)
        self.next_offset = 0

    def append(self, msg: Message) -> Message:
        # caller holds the bus lock
        msg = dataclasses.replace(msg, offset=self.next_offset)
        self.next_offset += 1
        self.messages.append(msg)
        return msg


class _GroupState:
    def __init__(self) -> None:
        self.offset = 0
        # nacked messages awaiting redelivery, served before the log tail
        self.redelivery: List[Message] = []


class MessageBus:
    """Topic registry + per-(topic, group) offsets."""

    DLQ_SUFFIX = "-dlq"

    def __init__(self, max_redelivery: int = 3) -> None:
        self._lock = threading.Condition()
        self._topics: Dict[str, _TopicLog] = {}
        self._groups: Dict[tuple, _GroupState] = {}
        self._max_redelivery = max_redelivery
        self._closed = False

    # -- broker internals --------------------------------------------------

    def _topic(self, name: str) -> _TopicLog:
        log = self._topics.get(name)
        if log is None:
            log = self._topics[name] = _TopicLog(name)
        return log

    def _group(self, topic: str, group: str) -> _GroupState:
        key = (topic, group)
        st = self._groups.get(key)
        if st is None:
            st = self._groups[key] = _GroupState()
        return st

    def publish(self, topic: str, key: str, value: Any) -> int:
        with self._lock:
            log = self._topic(topic)
            msg = log.append(
                Message(topic=topic, key=key, value=value, offset=0)
            )
            self._lock.notify_all()
            return msg.offset

    def topic_size(self, topic: str) -> int:
        with self._lock:
            return len(self._topic(topic).messages)

    def dlq_messages(self, topic: str) -> List[Message]:
        with self._lock:
            return list(self._topic(topic + self.DLQ_SUFFIX).messages)

    # -- DLQ operator verbs (reference tools/cli/adminDLQCommands.go:
    # GetDLQMessages / PurgeDLQMessages / MergeDLQMessages with a
    # lastMessageID watermark; offsets are this bus's message ids) -----

    def dlq_read(
        self, topic: str, last_offset: int = -1, count: int = 0,
    ) -> List[Message]:
        """Dead letters with offset <= last_offset (-1 = all), capped at
        ``count`` (0 = uncapped)."""
        with self._lock:
            msgs = [
                m for m in self._topic(topic + self.DLQ_SUFFIX).messages
                if last_offset < 0 or m.offset <= last_offset
            ]
        return msgs[:count] if count else msgs

    def dlq_purge(self, topic: str, last_offset: int = -1) -> int:
        """Drop dead letters up to the watermark; returns count dropped."""
        with self._lock:
            dlq = self._topic(topic + self.DLQ_SUFFIX)
            keep = [
                m for m in dlq.messages
                if last_offset >= 0 and m.offset > last_offset
            ]
            dropped = len(dlq.messages) - len(keep)
            dlq.messages[:] = keep
        return dropped

    def dlq_merge(self, topic: str, last_offset: int = -1) -> int:
        """Re-drive dead letters up to the watermark back onto the main
        topic (fresh offsets, redelivery count reset) and drop them from
        the DLQ; returns count merged."""
        with self._lock:
            dlq = self._topic(topic + self.DLQ_SUFFIX)
            keep: List[Message] = []
            merged: List[Message] = []
            for m in dlq.messages:
                if last_offset < 0 or m.offset <= last_offset:
                    merged.append(m)
                else:
                    keep.append(m)
            dlq.messages[:] = keep
            log = self._topic(topic)
            for m in merged:
                log.append(dataclasses.replace(
                    m, topic=topic, redelivery_count=0,
                ))
            self._lock.notify_all()
        return len(merged)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    # -- consumer protocol -------------------------------------------------

    def _poll(
        self, topic: str, group: str, timeout: Optional[float]
    ) -> Optional[Message]:
        deadline = None
        with self._lock:
            while True:
                if self._closed:
                    return None
                st = self._group(topic, group)
                if st.redelivery:
                    msg = st.redelivery.pop(0)
                else:
                    log = self._topic(topic)
                    if st.offset < len(log.messages):
                        src = log.messages[st.offset]
                        st.offset += 1
                        msg = dataclasses.replace(src)
                    else:
                        if timeout is not None and timeout <= 0:
                            return None
                        if deadline is None and timeout is not None:
                            deadline = time.monotonic() + timeout
                        if deadline is not None:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                return None
                            self._lock.wait(remaining)
                        else:
                            self._lock.wait()
                        continue
                return msg

    def _ack(self, group_key: tuple, msg: Message) -> None:
        pass  # offsets advance at delivery; ack is a protocol no-op here

    def _nack(self, group_key: tuple, msg: Message) -> None:
        topic, group = group_key
        with self._lock:
            st = self._groups[group_key]
            msg.redelivery_count += 1
            if msg.redelivery_count > self._max_redelivery:
                dlq = self._topic(topic + self.DLQ_SUFFIX)
                dlq.append(dataclasses.replace(
                    msg, topic=topic + self.DLQ_SUFFIX
                ))
            else:
                st.redelivery.append(msg)
            self._lock.notify_all()

    def new_consumer(self, topic: str, group: str) -> "Consumer":
        return Consumer(self, topic, group)

    def new_producer(self, topic: str) -> "Producer":
        return Producer(self, topic)


class Producer:
    def __init__(self, bus: MessageBus, topic: str) -> None:
        self._bus = bus
        self._topic = topic

    def publish(self, key: str, value: Any) -> int:
        return self._bus.publish(self._topic, key, value)


class Consumer:
    """Pull consumer; every message must be acked or nacked."""

    def __init__(self, bus: MessageBus, topic: str, group: str) -> None:
        self._bus = bus
        self._key = (topic, group)
        self._topic = topic
        self._group = group

    def poll(self, timeout: Optional[float] = 0.0) -> Optional[Message]:
        return self._bus._poll(self._topic, self._group, timeout)

    def ack(self, msg: Message) -> None:
        self._bus._ack(self._key, msg)

    def nack(self, msg: Message) -> None:
        self._bus._nack(self._key, msg)

    def drain(
        self,
        handler: Callable[[Message], None],
        *,
        limit: Optional[int] = None,
    ) -> int:
        """Synchronously process the current backlog; handler exceptions
        nack the message. Returns number of messages handled OK."""
        handled = 0
        seen = 0
        while limit is None or seen < limit:
            msg = self.poll(timeout=0.0)
            if msg is None:
                break
            seen += 1
            try:
                handler(msg)
            except Exception as e:
                # keep the WHY: the DLQ entry and the log both carry
                # the failure, or a poisoned message dead-letters with
                # zero diagnostics
                msg.last_error = f"{type(e).__name__}: {e}"
                _log.exception(
                    f"handler failed for {msg.topic!r} message "
                    f"{getattr(msg, 'offset', '?')}"
                )
                self.nack(msg)
            else:
                self.ack(msg)
                handled += 1
        return handled
