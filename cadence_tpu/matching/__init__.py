"""Matching service: task-list queues with synchronous rendezvous dispatch.

TPU-native rebuild of the reference matching service
(/root/reference/service/matching/): the host-side control plane that
rendezvouses task producers (history transfer queue) with task consumers
(worker pollers). There is no tensor analog — this stays a host
subsystem, designed around Python threading primitives instead of Go
channels.
"""

from .engine import MatchingEngine, PollRequest
from .matcher import TaskMatcher
from .task_list import InternalTask, TaskListID, TaskListManager

__all__ = [
    "MatchingEngine",
    "PollRequest",
    "TaskMatcher",
    "InternalTask",
    "TaskListID",
    "TaskListManager",
]
