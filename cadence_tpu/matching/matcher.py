"""TaskMatcher: zero-buffer rendezvous of task producers and pollers.

Reference: /root/reference/service/matching/matcher.go:86-348 — producers
(Offer/MustOffer) and consumers (Poll) meet on unbuffered channels with a
rate limiter in between. Here the rendezvous is a deque of waiting poller
slots guarded by one lock: a producer hands its task directly to a
waiting slot (sync match) or, for MustOffer, parks until a slot arrives.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from cadence_tpu.utils.locks import make_lock
from cadence_tpu.utils.quotas import TokenBucket


class _PollSlot:
    """One waiting poller; fulfilled at most once."""

    __slots__ = ("cv", "task", "done", "cancelled")

    def __init__(self, lock: threading.Lock) -> None:
        self.cv = threading.Condition(lock)
        self.task = None
        self.done = False
        self.cancelled = False

    def fulfill(self, task) -> None:
        self.task = task
        self.done = True
        self.cv.notify()


class TaskMatcher:
    def __init__(
        self,
        rate_limiter: Optional[TokenBucket] = None,
        forward_offer: Optional[Callable[[object, float], bool]] = None,
        forward_poll: Optional[Callable[[float], object]] = None,
    ) -> None:
        self._lock = make_lock("TaskMatcher._lock")
        self._slots: deque[_PollSlot] = deque()
        self._limiter = rate_limiter
        # forwarder hooks (child partition → parent partition); see
        # forwarder.go:123-281. Either may be None for the root partition.
        self._forward_offer = forward_offer
        self._forward_poll = forward_poll
        self._shutdown = threading.Event()

    # -- producer side -------------------------------------------------

    def _try_handoff(self, task) -> bool:
        """Hand task to a waiting poller. Caller holds the lock."""
        while self._slots:
            slot = self._slots.popleft()
            if slot.cancelled:
                continue
            slot.fulfill(task)
            return True
        return False

    def offer(self, task, timeout: float = 0.0) -> bool:
        """Sync match: succeed only if a poller takes the task now (or
        within ``timeout``). Reference matcher.Offer. ``timeout`` is ONE
        budget across the local and forwarded attempts — not one each."""
        if self._limiter is not None and not self._limiter.allow():
            return False
        deadline = time.monotonic() + timeout
        with self._lock:
            if self._try_handoff(task):
                return True
        if self._forward_offer is not None and self._forward_offer(
            task, max(0.0, deadline - time.monotonic())
        ):
            return True
        while time.monotonic() < deadline and not self._shutdown.is_set():
            with self._lock:
                if self._try_handoff(task):
                    return True
            time.sleep(min(0.005, timeout))
        return False

    def must_offer(self, task, poll_interval: float = 0.02) -> bool:
        """Backlog dispatch: block until some poller takes the task (or
        shutdown). Reference matcher.MustOffer."""
        while not self._shutdown.is_set():
            with self._lock:
                if self._try_handoff(task):
                    return True
            if self._forward_offer is not None and self._forward_offer(
                task, poll_interval
            ):
                return True
            time.sleep(poll_interval)
        return False

    # -- consumer side -------------------------------------------------

    def poll(self, timeout: float):
        """Wait up to ``timeout`` seconds for a task; None on timeout or
        shutdown. Reference matcher.Poll.

        With a forwarder, the budget is SPLIT: half parked on the local
        slot list, the remainder parked on the parent partition — a
        zero-budget forward could never match (the parent-side slot
        would be created and cancelled inside one lock hold, invisible
        to any producer). The reference selects on both channels
        simultaneously; the sequential split is the single-lock
        equivalent and bounds added dispatch latency at timeout/2."""
        deadline = time.monotonic() + timeout
        local_budget = (
            timeout if self._forward_poll is None else timeout / 2
        )
        slot = _PollSlot(self._lock)
        with self._lock:
            self._slots.append(slot)
            local_deadline = time.monotonic() + local_budget
            while not slot.done and not self._shutdown.is_set():
                remaining = local_deadline - time.monotonic()
                if remaining <= 0:
                    break
                slot.cv.wait(remaining)
            if slot.done:
                return slot.task
            slot.cancelled = True
            # remove now (O(active pollers)): abandoned slots must not
            # accumulate on an idle task list that is long-polled
            try:
                self._slots.remove(slot)
            except ValueError:
                pass  # a producer already popped it mid-handoff scan
        # local miss: park the remaining budget on the parent partition
        # (matcher polls the parent when the local backlog is dry)
        if self._forward_poll is not None and not self._shutdown.is_set():
            return self._forward_poll(
                max(0.0, deadline - time.monotonic())
            )
        return None

    def poller_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots if not s.cancelled)

    def interrupt_all(self) -> None:
        """Wake every waiting poller empty-handed (CancelOutstandingPoll)."""
        with self._lock:
            while self._slots:
                slot = self._slots.popleft()
                if not slot.cancelled:
                    slot.fulfill(None)

    @property
    def is_shutdown(self) -> bool:
        return self._shutdown.is_set()

    def shutdown(self) -> None:
        self._shutdown.set()
        self.interrupt_all()
