"""Forwarder: child partition → parent partition task/poll forwarding.

Reference: /root/reference/service/matching/forwarder.go:123-281 — in a
scalable task list, partitions form a tree (degree ``forwarder_tree_degree``)
rooted at the unpartitioned name; children forward unmatched adds and idle
polls toward the root, each direction behind a token bucket.
"""

from __future__ import annotations

from typing import Optional

from cadence_tpu.utils.quotas import TokenBucket

from .task_list import TaskListID

TREE_DEGREE = 20


def parent_partition_name(tl_id: TaskListID, degree: int = TREE_DEGREE) -> Optional[str]:
    """Name of the parent partition, or None at the root."""
    if not tl_id.is_partition:
        return None
    p = tl_id.partition
    parent = (p - 1) // degree if p > 0 else 0
    return TaskListID.partition_name(tl_id.base_name, parent)


class Forwarder:
    def __init__(
        self,
        tl_id: TaskListID,
        engine,  # MatchingEngine; resolves the parent manager lazily
        forward_task_rps: float = 10.0,
        forward_poll_rps: float = 10.0,
    ) -> None:
        self.id = tl_id
        self._engine = engine
        self._parent = parent_partition_name(tl_id)
        self._task_tokens = TokenBucket(rps=forward_task_rps, burst=int(forward_task_rps))
        self._poll_tokens = TokenBucket(rps=forward_poll_rps, burst=int(forward_poll_rps))

    @property
    def enabled(self) -> bool:
        return self._parent is not None

    def _parent_mgr(self):
        return self._engine._get_manager(
            TaskListID(self.id.domain_id, self._parent, self.id.task_type)
        )

    def forward_offer(self, task, timeout: float) -> bool:
        if not self.enabled or not self._task_tokens.allow():
            return False
        return self._parent_mgr().matcher.offer(task, timeout)

    def forward_poll(self, timeout: float):
        if not self.enabled or not self._poll_tokens.allow():
            return None
        return self._parent_mgr().matcher.poll(timeout)
