"""Recent-poller identity cache for DescribeTaskList.

Reference: /root/reference/service/matching/pollerHistory.go.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List


class PollerHistory:
    def __init__(self, ttl_s: float = 300.0, max_size: int = 1000) -> None:
        self._lock = threading.Lock()
        self._pollers: Dict[str, float] = {}  # identity → last access (monotonic)
        self._ttl = ttl_s
        self._max = max_size

    def record(self, identity: str) -> None:
        if not identity:
            return
        now = time.monotonic()
        with self._lock:
            self._pollers[identity] = now
            if len(self._pollers) > self._max:
                oldest = min(self._pollers, key=self._pollers.get)
                del self._pollers[oldest]

    def get(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            expired = [k for k, t in self._pollers.items() if now - t > self._ttl]
            for k in expired:
                del self._pollers[k]
            return [
                {"identity": k, "last_access_time_s_ago": now - t}
                for k, t in sorted(self._pollers.items())
            ]
