"""MatchingEngine: task-list manager registry + Add/Poll task RPCs.

Reference: /root/reference/service/matching/matchingEngine.go:118-683 —
AddDecisionTask/AddActivityTask persist-or-sync-match through a
taskListManager; PollForDecisionTask/PollForActivityTask rendezvous with
the matcher then call back into history (RecordDecisionTaskStarted /
RecordActivityTaskStarted) to materialize the Started event before
returning the task to the worker.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
import uuid
from typing import Dict, Optional

from cadence_tpu.runtime.api import (
    EntityNotExistsServiceError,
    PollForActivityTaskResponse,
    PollForDecisionTaskResponse,
    ServiceBusyError,
)
from cadence_tpu.runtime.persistence.interfaces import TaskManager
from cadence_tpu.runtime.persistence.records import TaskInfo
from cadence_tpu.utils.clock import RealTimeSource, TimeSource
from cadence_tpu.utils.dynamicconfig import Collection
from cadence_tpu.utils.locks import make_guarded, make_lock
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP, Scope

from .forwarder import Forwarder
from .matcher import TaskMatcher
from .poller_history import PollerHistory
from .task_list import (
    TASK_TYPE_ACTIVITY,
    TASK_TYPE_DECISION,
    InternalTask,
    TaskListID,
    TaskListManager,
)


@dataclasses.dataclass
class PollRequest:
    domain_id: str
    task_list: str
    identity: str = ""
    timeout_s: float = 1.0


class MatchingEngine:
    def __init__(
        self,
        task_manager: TaskManager,
        history_client,  # record_decision_task_started / record_activity_task_started
        config: Optional[Collection] = None,
        time_source: Optional[TimeSource] = None,
        metrics: Scope = NOOP,
        poll_request_id_fn=None,
        rate_limiter=None,
    ) -> None:
        self._store = task_manager
        self._history = history_client
        self._time = time_source or RealTimeSource()
        # poll-delivery nonce for the started-event dedup handshake.
        # Default: a fresh uuid per dequeued task. Injectable (called
        # with the TaskInfo) so deterministic harnesses — the chaos
        # suite's byte-identical differential replay — can derive it
        # from the task instead of entropy.
        self._poll_request_id_fn = poll_request_id_fn
        self._log = get_logger("cadence_tpu.matching")
        self.metrics = metrics.tagged(service="matching")
        # per-API requests/latency/errors (ref common/metrics/defs.go
        # matching scopes)
        from cadence_tpu.utils.metrics_defs import (
            MATCHING_OPS,
            instrument_methods,
        )

        instrument_methods(self, self.metrics, MATCHING_OPS)
        self._lock = make_lock("MatchingEngine._lock")
        self._managers: Dict[tuple, TaskListManager] = make_guarded(
            {}, "MatchingEngine._managers", self._lock
        )
        self._creating: Dict[tuple, object] = make_guarded(
            {}, "MatchingEngine._creating", self._lock
        )
        self._pollers: Dict[tuple, PollerHistory] = {}
        cfg = config or Collection()
        self._n_write_partitions = cfg.int_property(
            "matching.numTasklistWritePartitions", 1
        )
        self._n_read_partitions = cfg.int_property(
            "matching.numTasklistReadPartitions", 1
        )
        self._tasklist_rps = cfg.float_property("matching.rps", 100000.0)
        # overload control (ISSUE 15): a MultiStageRateLimiter over
        # task ADDS (polls stay unmetered — a parked poller is the
        # backpressure, not the overload). None (the default) is one
        # attribute read per add
        self.rate_limiter = rate_limiter
        # in-flight sync queries: query_id → (event, result slot)
        self._query_lock = make_lock("MatchingEngine._query_lock")
        self._pending_queries: Dict[str, tuple] = make_guarded(
            {}, "MatchingEngine._pending_queries", self._query_lock
        )

    # -- manager registry ----------------------------------------------

    def _get_manager(self, tl_id: TaskListID) -> TaskListManager:
        key = tl_id.key()
        with self._lock:
            mgr = self._managers.get(key)
            if mgr is not None:
                return mgr
            # per-key creation lock: construction leases from the store
            # (blocking I/O) and starts threads — it must run outside
            # the engine lock, but TWO racing constructors would both
            # take store leases, fencing each other's rangeID and
            # churning the lease on every creation race (ADVICE r4).
            # Serializing per key means the loser never constructs.
            creating_lock = self._creating.setdefault(
                key, make_lock("MatchingEngine.creating_lock")
            )
        with creating_lock:
            with self._lock:
                mgr = self._managers.get(key)
            if mgr is not None:
                return mgr
            forwarder = Forwarder(tl_id, self)
            from cadence_tpu.utils.quotas import TokenBucket

            matcher = TaskMatcher(
                # matching.rps dynamic config, read at manager creation
                # (reference taskListManager rate limiter)
                rate_limiter=TokenBucket(self._tasklist_rps()),
                forward_offer=(
                    forwarder.forward_offer if forwarder.enabled else None
                ),
                forward_poll=(
                    forwarder.forward_poll if forwarder.enabled else None
                ),
            )
            fresh = TaskListManager(
                tl_id, self._store, matcher, time_source=self._time
            )
            with self._lock:
                # NOTE: the _creating entry is deliberately never popped
                # — a racer still parked on this lock object must
                # re-check through the SAME lock after an unload/
                # re-create cycle, or two constructors can race again.
                # Cardinality is bounded by distinct task lists, same
                # as _pollers.
                self._managers[key] = fresh
            return fresh

    def _pick_partition(self, domain_id: str, name: str, write: bool) -> str:
        if TaskListID("", name, 0).is_partition:
            return name  # already partition-addressed
        n = (
            self._n_write_partitions(domain=domain_id, task_list=name)
            if write
            else self._n_read_partitions(domain=domain_id, task_list=name)
        )
        if n <= 1:
            return name
        return TaskListID.partition_name(name, random.randrange(n))

    # -- add (called by history transfer queue) ------------------------

    def _add_task(
        self, domain_id: str, name: str, task_type: int, info: TaskInfo
    ) -> bool:
        lim = self.rate_limiter
        if lim is not None and not lim.allow(domain_id):
            # retryable shed: the queue processor's at-least-once
            # retry re-offers the task after the hint — coordinated
            # backpressure instead of unbounded task-list growth
            hint = getattr(lim, "retry_after_s", None)
            raise ServiceBusyError(
                f"matching overloaded (domain {domain_id})",
                retry_after_s=hint(domain_id) if hint else 0.0,
            )
        part = self._pick_partition(domain_id, name, write=True)
        mgr = self._get_manager(TaskListID(domain_id, part, task_type))
        return mgr.add_task(info)

    def add_decision_task(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        task_list: str,
        schedule_id: int,
        schedule_to_start_timeout_seconds: int = 0,
    ) -> bool:
        return self._add_task(
            domain_id, task_list, TASK_TYPE_DECISION,
            TaskInfo(
                domain_id=domain_id, workflow_id=workflow_id, run_id=run_id,
                task_id=0, schedule_id=schedule_id,
                schedule_to_start_timeout_seconds=schedule_to_start_timeout_seconds,
            ),
        )

    def add_activity_task(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        task_list: str,
        schedule_id: int,
        schedule_to_start_timeout_seconds: int = 0,
    ) -> bool:
        return self._add_task(
            domain_id, task_list, TASK_TYPE_ACTIVITY,
            TaskInfo(
                domain_id=domain_id, workflow_id=workflow_id, run_id=run_id,
                task_id=0, schedule_id=schedule_id,
                schedule_to_start_timeout_seconds=schedule_to_start_timeout_seconds,
            ),
        )

    # -- poll (called by workers via frontend) -------------------------

    def _poll_loop(self, req: PollRequest, task_type: int):
        """Poll → record-started → respond; stale tasks are acked and the
        poll continues until the deadline (matchingEngine.getTask loop)."""
        part = self._pick_partition(req.domain_id, req.task_list, write=False)
        tl_id = TaskListID(req.domain_id, part, task_type)
        mgr = self._get_manager(tl_id)
        self._poller_history(tl_id).record(req.identity)
        deadline = time.monotonic() + req.timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None, None
            task: Optional[InternalTask] = mgr.get_task(remaining)
            if task is None:
                if mgr.matcher.is_shutdown:
                    # unload/shutdown raced this long poll: get_task now
                    # returns instantly — re-looping would busy-spin at
                    # full speed for the rest of the poll deadline
                    return None, None
                continue  # interrupted or forwarded miss; re-check deadline
            info = task.info
            if task.query is not None:
                # sync query task: no started event, no history write
                task.finish(None)
                return task, {"query": task.query}
            request_id = (
                self._poll_request_id_fn(info)
                if self._poll_request_id_fn is not None
                else str(uuid.uuid4())
            )
            try:
                if task_type == TASK_TYPE_DECISION:
                    resp = self._history.record_decision_task_started(
                        info.domain_id, info.workflow_id, info.run_id,
                        info.schedule_id, request_id, req.identity,
                    )
                else:
                    resp = self._history.record_activity_task_started(
                        info.domain_id, info.workflow_id, info.run_id,
                        info.schedule_id, request_id, req.identity,
                    )
            except EntityNotExistsServiceError as e:
                task.finish(e)  # stale task (already started/completed)
                continue
            except Exception as e:  # transient history failure
                task.finish(e)
                if task.sync:
                    # a sync-matched task was never persisted; dropping
                    # it here would strand the workflow until a timeout
                    # fires — put it on the backlog for redelivery
                    try:
                        mgr.add_task(info)
                    except Exception:
                        self._log.exception(
                            "failed to re-enqueue sync-matched task "
                            f"{info.workflow_id}/{info.schedule_id}"
                        )
                raise
            task.finish(None)
            return task, resp

    def poll_for_decision_task(
        self, req: PollRequest
    ) -> Optional[PollForDecisionTaskResponse]:
        task, resp = self._poll_loop(req, TASK_TYPE_DECISION)
        if task is None:
            return None
        if "query" in resp:
            q = resp["query"]
            return PollForDecisionTaskResponse(
                task_token={"query_id": q["query_id"]},
                workflow_id=task.info.workflow_id,
                run_id=task.info.run_id,
                workflow_type="",
                previous_started_event_id=0,
                started_event_id=0,
                attempt=0,
                history=[],
                query=q,
            )
        return PollForDecisionTaskResponse(
            task_token=resp["task_token"],
            workflow_id=task.info.workflow_id,
            run_id=task.info.run_id,
            workflow_type=resp["workflow_type"],
            previous_started_event_id=resp["previous_started_event_id"],
            started_event_id=resp["started_event_id"],
            attempt=resp["attempt"],
            history=resp["history"],
            queries=resp.get("queries") or {},
        )

    def poll_for_activity_task(
        self, req: PollRequest
    ) -> Optional[PollForActivityTaskResponse]:
        task, resp = self._poll_loop(req, TASK_TYPE_ACTIVITY)
        if task is None:
            return None
        scheduled = resp["scheduled_event"]
        attrs = scheduled.attributes if scheduled is not None else {}
        return PollForActivityTaskResponse(
            task_token=resp["task_token"],
            workflow_id=task.info.workflow_id,
            run_id=task.info.run_id,
            activity_id=resp["activity_id"],
            activity_type=attrs.get("activity_type", ""),
            input=attrs.get("input", b""),
            scheduled_timestamp=resp["scheduled_time"],
            started_timestamp=resp["started_time"],
            schedule_to_close_timeout_seconds=resp[
                "schedule_to_close_timeout_seconds"
            ],
            start_to_close_timeout_seconds=resp["start_to_close_timeout_seconds"],
            heartbeat_timeout_seconds=resp["heartbeat_timeout_seconds"],
            attempt=resp["attempt"],
            heartbeat_details=resp["heartbeat_details"],
        )

    # -- sync query (matcher OfferQuery / RespondQueryTaskCompleted) ----

    def query_workflow(
        self,
        domain_id: str,
        task_list: str,
        workflow_id: str,
        run_id: str,
        query_type: str,
        query_args: bytes = b"",
        timeout_s: float = 10.0,
    ) -> bytes:
        """Dispatch a query task to a live poller and wait for its
        answer (reference matchingEngine.QueryWorkflow — queries are
        never persisted; no poller in time → query fails)."""
        from cadence_tpu.runtime.api import QueryFailedError

        query_id = str(uuid.uuid4())
        info = TaskInfo(
            domain_id=domain_id, workflow_id=workflow_id, run_id=run_id,
            task_id=-1, schedule_id=-1,
        )
        task = InternalTask(info, finish=None, sync=True)
        task.query = {
            "query_id": query_id,
            "query_type": query_type,
            "query_args": query_args,
        }
        done = threading.Event()
        slot: dict = {}
        with self._query_lock:
            self._pending_queries[query_id] = (done, slot)
        try:
            # try every partition (pollers may be parked on any sibling —
            # a single random pick would miss them)
            n_parts = max(1, self._n_read_partitions(
                domain=domain_id, task_list=task_list
            ))
            names = [
                TaskListID.partition_name(task_list, i)
                for i in range(n_parts)
            ] if not TaskListID("", task_list, 0).is_partition else [task_list]
            # ONE budget end to end: the offer phase spends at most
            # half, and the answer wait gets whatever remains — the
            # caller's timeout_s is a hard deadline, not per phase
            overall = time.monotonic() + timeout_s
            deadline = time.monotonic() + timeout_s / 2
            offered = False
            while not offered:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                per_try = max(0.05, remaining / (2 * len(names)))
                for part in names:
                    mgr = self._get_manager(
                        TaskListID(domain_id, part, TASK_TYPE_DECISION)
                    )
                    if mgr.matcher.offer(task, timeout=min(per_try, max(
                        0.0, deadline - time.monotonic()
                    ))):
                        offered = True
                        break
            if not offered:
                raise QueryFailedError(
                    f"no poller on task list {task_list} to answer query"
                )
            if not done.wait(max(0.0, overall - time.monotonic())):
                raise QueryFailedError("query timed out")
            if slot.get("error"):
                raise QueryFailedError(slot["error"])
            return slot.get("result") or b""
        finally:
            with self._query_lock:
                self._pending_queries.pop(query_id, None)

    def respond_query_task_completed(
        self, query_id: str, result: bytes = b"", error: str = ""
    ) -> None:
        with self._query_lock:
            entry = self._pending_queries.get(query_id)
        if entry is None:
            return  # query already timed out / completed
        done, slot = entry
        slot["result"] = result
        slot["error"] = error
        done.set()

    # -- admin ----------------------------------------------------------

    def _poller_history(self, tl_id: TaskListID) -> PollerHistory:
        with self._lock:
            ph = self._pollers.get(tl_id.key())
            if ph is None:
                ph = self._pollers[tl_id.key()] = PollerHistory()
            return ph

    def describe_task_list(
        self, domain_id: str, name: str, task_type: int
    ) -> dict:
        tl_id = TaskListID(domain_id, name, task_type)
        with self._lock:
            mgr = self._managers.get(tl_id.key())
        out = mgr.describe() if mgr else {"task_list": name, "task_type": task_type}
        out["pollers"] = self._poller_history(tl_id).get()
        return out

    def list_task_list_partitions(
        self, domain_id: str, name: str
    ) -> dict:
        """Partition names for a scalable task list (reference
        matchingEngine ListTaskListPartitions): the union of read and
        write partitioning, per task type."""
        n = max(
            self._n_read_partitions(domain=domain_id, task_list=name),
            self._n_write_partitions(domain=domain_id, task_list=name),
            1,
        )
        partitions = [
            {"name": TaskListID.partition_name(name, i), "partition": i}
            for i in range(n)
        ]
        return {
            "decision_task_list_partitions": partitions,
            "activity_task_list_partitions": [dict(p) for p in partitions],
        }

    def cancel_outstanding_polls(
        self, domain_id: str, name: str, task_type: int
    ) -> None:
        with self._lock:
            mgr = self._managers.get(TaskListID(domain_id, name, task_type).key())
        if mgr is not None:
            mgr.matcher.interrupt_all()

    def unload_idle_task_lists(self) -> int:
        """GC managers idle past their TTL (taskListManager idle unload).

        stop() joins the writer thread and does store I/O — it runs
        OUTSIDE the engine lock, or one stalled task list turns a
        periodic sweep into an engine-wide matching outage."""
        stopping = []
        with self._lock:
            for key, mgr in list(self._managers.items()):
                if mgr.idle_since_s() > mgr.idle_ttl_s:
                    del self._managers[key]
                    stopping.append(mgr)
        for mgr in stopping:
            mgr.stop()
        return len(stopping)

    def shutdown(self) -> None:
        with self._lock:
            managers = list(self._managers.values())
            self._managers.clear()
        for mgr in managers:
            mgr.stop()
