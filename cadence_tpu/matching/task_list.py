"""TaskListManager: per-task-list daemon — lease, backlog pump, GC.

Reference: /root/reference/service/matching/taskListManager.go:120-565
(lease + taskID block allocation), taskReader.go (backlog pump),
taskWriter.go (batched appends with block fencing), ackManager.go,
taskGC.go. One manager owns one (domain, name, task_type) queue:
producers sync-match through the TaskMatcher when a poller is waiting,
otherwise the task is persisted and later dispatched by the reader pump.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from cadence_tpu.runtime.persistence.errors import ConditionFailedError
from cadence_tpu.runtime.queues.ack import QueueAckManager
from cadence_tpu.runtime.persistence.interfaces import TaskManager
from cadence_tpu.runtime.persistence.records import TaskInfo, TaskListInfo
from cadence_tpu.utils.clock import RealTimeSource, TimeSource
from cadence_tpu.utils.locks import make_guarded, make_lock
from cadence_tpu.utils.log import get_logger

# taskID block leased per rangeID bump (reference rangeSize=100k)
RANGE_SIZE = 100_000

TASK_TYPE_DECISION = 0
TASK_TYPE_ACTIVITY = 1


class TaskListID:
    """(domain_id, name, task_type) triple, partition-aware.

    Scalable task lists name partitions ``/__cadence_sys/{base}/{n}``
    (reference taskListID parsing, forwarder.go).
    """

    PARTITION_PREFIX = "/__cadence_sys/"

    def __init__(self, domain_id: str, name: str, task_type: int) -> None:
        self.domain_id = domain_id
        self.name = name
        self.task_type = task_type

    @property
    def is_partition(self) -> bool:
        return self.name.startswith(self.PARTITION_PREFIX)

    @property
    def base_name(self) -> str:
        if not self.is_partition:
            return self.name
        rest = self.name[len(self.PARTITION_PREFIX):]
        base, _, _ = rest.rpartition("/")
        return base

    @property
    def partition(self) -> int:
        if not self.is_partition:
            return 0
        _, _, n = self.name.rpartition("/")
        try:
            return int(n)
        except ValueError:
            return 0

    @classmethod
    def partition_name(cls, base: str, n: int) -> str:
        return base if n == 0 else f"{cls.PARTITION_PREFIX}{base}/{n}"

    def key(self) -> Tuple[str, str, int]:
        return (self.domain_id, self.name, self.task_type)

    def __repr__(self) -> str:
        return f"TaskListID({self.domain_id!r}, {self.name!r}, {self.task_type})"


class InternalTask:
    """A dispatched task: persisted backlog entry or ephemeral sync match."""

    __slots__ = ("info", "_finish", "finished", "sync", "started_response", "query")

    def __init__(
        self, info: TaskInfo, finish: Optional[Callable[[Optional[Exception]], None]],
        sync: bool = False,
    ) -> None:
        self.info = info
        self._finish = finish
        self.finished = False
        self.sync = sync
        self.started_response = None
        self.query = None  # sync query task payload (matcher.OfferQuery)

    def finish(self, error: Optional[Exception] = None) -> None:
        if self.finished:
            return
        self.finished = True
        if self._finish is not None:
            self._finish(error)


class _AppendRequest:
    """One producer's pending write, parked on the writer thread."""

    __slots__ = ("info", "done", "error")

    def __init__(self, info: TaskInfo) -> None:
        self.info = info
        self.done = threading.Event()
        self.error: Optional[Exception] = None


class TaskWriter:
    """Batched backlog appends (reference taskWriter.go:appendTasks).

    Producers park on a request queue; one writer thread drains up to
    ``MAX_BATCH`` requests, allocates their task ids inside the leased
    block, and persists them with ONE create_tasks call — under a task
    storm the store sees O(storm/batch) writes instead of O(storm),
    and the rangeID fencing condition is checked once per batch.
    """

    MAX_BATCH = 100

    def __init__(self, mgr: "TaskListManager") -> None:
        self._mgr = mgr
        self._lock = make_lock("TaskWriter._lock")
        self._queue: List[_AppendRequest] = make_guarded(
            [], "TaskWriter._queue", self._lock
        )
        self._signal = threading.Event()
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._write_pump,
            name=f"taskWriter-{mgr.id.name}",
            daemon=True,
        )
        self._thread.start()

    def append(self, info: TaskInfo, timeout_s: float = 30.0) -> None:
        """Park until the batch containing ``info`` is persisted."""
        req = _AppendRequest(info)
        with self._lock:
            if self._stopped.is_set():
                raise RuntimeError("task writer stopped")
            self._queue.append(req)
        self._signal.set()
        req.done.wait(timeout=timeout_s)
        if not req.done.is_set():
            # withdraw before raising: leaving the request queued means
            # it may persist AFTER the caller retries, guaranteeing a
            # duplicate backlog task on slow-store stalls (ADVICE r4).
            with self._lock:
                try:
                    self._queue.remove(req)
                    withdrawn = True
                except ValueError:
                    withdrawn = False  # already drained into a batch
            if withdrawn:
                raise TimeoutError("task append timed out")
            # in-flight persist: it will resolve; give it a short grace
            req.done.wait(timeout=5.0)
            if not req.done.is_set():
                raise TimeoutError(
                    "task append timed out (write in flight; the task "
                    "may still persist)"
                )
        if req.error is not None:
            raise req.error

    def _write_pump(self) -> None:
        mgr = self._mgr
        while True:
            self._signal.wait(timeout=0.1)
            self._signal.clear()
            if self._stopped.is_set():
                # emptiness must be read under the lock: append() also
                # checks _stopped under it, so either the request is
                # already queued here (drained below) or its producer
                # saw _stopped and raised — an append can no longer
                # slip between an off-lock check and the pump's exit
                # (found by the sanitizer's GUARDED-FIELD-RACE)
                with self._lock:
                    empty = not self._queue
                if empty:
                    return
            while True:
                with self._lock:
                    batch = self._queue[: self.MAX_BATCH]
                    del self._queue[: len(batch)]
                if not batch:
                    break
                try:
                    self._persist(batch)
                except Exception as e:  # surface to every parked producer
                    for req in batch:
                        req.error = e
                finally:
                    for req in batch:
                        req.done.set()
                mgr._backlog_signal.set()

    def _persist(self, batch: List[_AppendRequest]) -> None:
        mgr = self._mgr
        now = mgr._time.now()
        with mgr._write_lock:
            for req in batch:
                info = req.info
                info.task_id = mgr._allocate_task_id()
                mgr._last_written_id = info.task_id
                if info.created_time == 0:
                    info.created_time = now
                if (
                    info.schedule_to_start_timeout_seconds > 0
                    and info.expiry_time == 0
                ):
                    info.expiry_time = info.created_time + int(
                        info.schedule_to_start_timeout_seconds * 1e9
                    )
            infos = [r.info for r in batch]
            try:
                mgr._store.create_tasks(mgr._info, infos)
            except ConditionFailedError:
                # lost the lease (another owner); re-lease, re-id, retry
                # once — the whole batch moves to the new block
                mgr._release()
                for req in batch:
                    req.info.task_id = mgr._allocate_task_id()
                    mgr._last_written_id = req.info.task_id
                mgr._store.create_tasks(mgr._info, infos)

    def stop(self) -> None:
        self._stopped.set()
        self._signal.set()
        self._thread.join(timeout=5.0)
        with self._lock:
            drained = self._queue[:]
            self._queue.clear()
        for req in drained:
            req.error = RuntimeError("task writer stopped")
            req.done.set()


class TaskGC:
    """Throttled backlog GC (reference taskGC.go).

    Completed tasks are only acked in memory; the store rows below the
    ack level are range-deleted when enough completions accumulate or
    the GC interval elapses — not on every completion, which would turn
    each task into an extra store round-trip.
    """

    THRESHOLD = 100
    INTERVAL_S = 1.0

    def __init__(self, mgr: "TaskListManager") -> None:
        self._mgr = mgr
        self._since_gc = 0
        self._last_gc = mgr._time.now()
        self._last_deleted_level = mgr._ack.ack_level

    def run_now(self, ack_level: int) -> None:
        mgr = self._mgr
        if ack_level > self._last_deleted_level:
            # ack_level itself is completed; the store deletes < level
            mgr._store.complete_tasks_less_than(
                mgr.id.domain_id, mgr.id.name, mgr.id.task_type,
                ack_level + 1,
            )
            self._last_deleted_level = ack_level
        # _write_lock: the writer thread swaps mgr._info on block
        # rollover; persisting a stale range_id would self-fence
        with mgr._write_lock:
            mgr._info.ack_level = ack_level
            try:
                mgr._store.update_task_list(mgr._info)
            except ConditionFailedError:
                pass  # lease moved; new owner persists its own ack level
        self._since_gc = 0
        self._last_gc = mgr._time.now()

    def maybe_run(self, ack_level: int) -> None:
        self._since_gc += 1
        due = (
            self._since_gc >= self.THRESHOLD
            or self._mgr._time.now() - self._last_gc
            >= self.INTERVAL_S * 1e9
        )
        if due:
            self.run_now(ack_level)


class TaskListManager:
    def __init__(
        self,
        task_list_id: TaskListID,
        task_manager: TaskManager,
        matcher,
        time_source: Optional[TimeSource] = None,
        idle_tasklist_ttl_s: float = 300.0,
        max_sync_match_wait_s: float = 0.2,
    ) -> None:
        self.id = task_list_id
        self._store = task_manager
        self.matcher = matcher
        self._time = time_source or RealTimeSource()
        self._log = get_logger(
            "cadence_tpu.matching.tasklist", task_list=task_list_id.name
        )
        self._write_lock = make_lock("TaskListManager._write_lock")
        self._info = self._lease()
        # leased block: (rangeID-1)*RANGE_SIZE+1 .. rangeID*RANGE_SIZE
        self._next_task_id = (self._info.range_id - 1) * RANGE_SIZE + 1
        self._max_task_id = self._info.range_id * RANGE_SIZE
        self._ack = QueueAckManager(self._info.ack_level)
        # highest task id persisted by THIS manager's writer; read_level
        # lags it while the reader pump is behind (backlog signal). A
        # restart starts at 0: pre-existing rows surface via read_level
        # within one pump interval
        self._last_written_id = 0
        self._backlog_signal = threading.Event()
        self._stopped = threading.Event()
        self._last_activity = self._time.now()
        self._max_sync_wait = max_sync_match_wait_s
        self.idle_ttl_s = idle_tasklist_ttl_s
        self._writer = TaskWriter(self)
        self._gc = TaskGC(self)
        self._reader = threading.Thread(
            target=self._read_pump, name=f"taskReader-{task_list_id.name}",
            daemon=True,
        )
        self._reader.start()

    # -- lease / block allocation (taskWriter block fencing) ------------

    def _lease(self) -> TaskListInfo:
        return self._store.lease_task_list(
            self.id.domain_id, self.id.name, self.id.task_type
        )

    def _release(self) -> None:
        # caller holds _write_lock: take a fresh lease + taskID block
        self._info = self._lease()
        self._next_task_id = (self._info.range_id - 1) * RANGE_SIZE + 1
        self._max_task_id = self._info.range_id * RANGE_SIZE

    def _allocate_task_id(self) -> int:
        # caller holds _write_lock
        if self._next_task_id > self._max_task_id:
            self._release()
        tid = self._next_task_id
        self._next_task_id += 1
        return tid

    # -- producer -------------------------------------------------------

    def add_task(self, info: TaskInfo) -> bool:
        """Sync-match if a poller waits and no backlog; else persist via
        the batched writer.

        Returns True when the task was sync-matched (never persisted).
        Reference taskListManager.AddTask: backlog present ⇒ skip sync
        match to preserve dispatch order.
        """
        self._touch()
        if not self._has_backlog():
            task = InternalTask(info, finish=None, sync=True)
            if self.matcher.offer(task, timeout=self._max_sync_wait):
                return True
        self._writer.append(info)
        return False

    # -- consumer -------------------------------------------------------

    def get_task(self, timeout: float) -> Optional[InternalTask]:
        self._touch()
        return self.matcher.poll(timeout)

    # -- backlog pump (taskReader) --------------------------------------

    def _has_backlog(self) -> bool:
        # three signals: read-but-unfinished span, in-flight tasks, and
        # PERSISTED-but-unread writes (the writer may be ahead of the
        # reader pump — sync-matching a fresh task past them would
        # break FIFO dispatch)
        return (
            self._ack.read_level > self._ack.ack_level
            or bool(self._outstanding_count())
            or self._last_written_id > self._ack.read_level
        )

    def _outstanding_count(self) -> int:
        return self._ack.outstanding()

    def _read_pump(self) -> None:
        while not self._stopped.is_set():
            self._backlog_signal.wait(timeout=0.1)
            self._backlog_signal.clear()
            if self._stopped.is_set():
                return
            while True:
                batch = self._store.get_tasks(
                    self.id.domain_id, self.id.name, self.id.task_type,
                    read_level=self._ack.read_level,
                    max_read_level=self._max_task_id,
                    batch_size=64,
                )
                if not batch:
                    break
                now = self._time.now()
                for info in batch:
                    self._ack.add(info.task_id)
                    if info.expiry_time and info.expiry_time < now:
                        self._complete(info.task_id)  # expired: ack + GC
                        continue
                    task = InternalTask(
                        info,
                        finish=lambda err, tid=info.task_id: self._on_finish(
                            tid, err
                        ),
                    )
                    if not self.matcher.must_offer(task):
                        return  # shutdown

    def _on_finish(self, task_id: int, error: Optional[Exception]) -> None:
        # both success and a stale-task error ack the task; a transient
        # error would re-deliver in the reference, we ack-and-log
        if error is not None:
            self._log.info(f"task {task_id} finished with error: {error}")
        self._complete(task_id)

    def _complete(self, task_id: int) -> None:
        # in-memory ack only; the throttled TaskGC range-deletes the
        # store rows + persists the ack level (reference taskGC.go)
        self._ack.complete(task_id)
        ack = self._ack.update_ack_level()
        try:
            self._gc.maybe_run(ack)
        except Exception:
            # GC is best-effort cleanup on the task-FINISH path, which
            # runs AFTER record_*_task_started succeeded — letting a
            # transient store error unwind here would destroy the poll
            # response for an already-started task (the worker never
            # sees it; the workflow stalls to its task timeout). Rows
            # stay until the next due GC pass.
            self._log.exception("task GC failed; deferring cleanup")

    # -- lifecycle ------------------------------------------------------

    def _touch(self) -> None:
        self._last_activity = self._time.now()

    def idle_since_s(self) -> float:
        return (self._time.now() - self._last_activity) / 1e9

    def describe(self) -> dict:
        return {
            "task_list": self.id.name,
            "task_type": self.id.task_type,
            "range_id": self._info.range_id,
            "ack_level": self._ack.ack_level,
            "read_level": self._ack.read_level,
            "backlog_hint": self._outstanding_count(),
        }

    def stop(self) -> None:
        self._stopped.set()
        self._backlog_signal.set()
        self._writer.stop()
        self.matcher.shutdown()
        # final GC pass so a clean shutdown leaves no acked rows behind;
        # best-effort — stop() runs under the engine lock during idle
        # unload, and a store error must not abort that sweep
        try:
            self._gc.run_now(self._ack.update_ack_level())
        except Exception:
            self._log.exception("final task GC failed on stop")
