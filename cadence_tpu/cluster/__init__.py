"""Multi-cluster topology metadata.

Reference: common/cluster/metadata.go (failover version arithmetic,
master/current cluster, per-cluster info).
"""

from .metadata import ClusterInformation, ClusterMetadata, TEST_CLUSTER_METADATA

__all__ = ["ClusterInformation", "ClusterMetadata", "TEST_CLUSTER_METADATA"]
