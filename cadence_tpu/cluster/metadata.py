"""Cluster topology + failover-version arithmetic.

Reference: common/cluster/metadata.go — every cluster owns a distinct
``initial_failover_version``; a domain's failover version moves in steps
of ``failover_version_increment`` and
``version % increment == cluster_initial_version`` identifies the owning
cluster (metadata.go GetNextFailoverVersion /
ClusterNameForFailoverVersion). The empty version (-24) means "no
version" (common/constants.go EmptyVersion).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from cadence_tpu.core.ids import EMPTY_VERSION


@dataclasses.dataclass(frozen=True)
class ClusterInformation:
    """Per-cluster static info (config.ClusterInformation)."""

    enabled: bool = True
    initial_failover_version: int = 0
    rpc_name: str = ""
    rpc_address: str = ""


class ClusterMetadata:
    """Answers "which cluster does failover version V belong to?" and
    "what is the next failover version for cluster C?"."""

    def __init__(
        self,
        *,
        enable_global_domain: bool = True,
        failover_version_increment: int = 10,
        master_cluster_name: str = "active",
        current_cluster_name: str = "active",
        cluster_info: Optional[Dict[str, ClusterInformation]] = None,
    ) -> None:
        if cluster_info is None:
            cluster_info = {"active": ClusterInformation(initial_failover_version=0)}
        if master_cluster_name not in cluster_info:
            raise ValueError(f"master cluster {master_cluster_name!r} not in cluster_info")
        if current_cluster_name not in cluster_info:
            raise ValueError(f"current cluster {current_cluster_name!r} not in cluster_info")
        versions = {}
        for name, info in cluster_info.items():
            if not 0 <= info.initial_failover_version < failover_version_increment:
                raise ValueError(
                    f"cluster {name}: initial version {info.initial_failover_version} "
                    f"outside [0, {failover_version_increment})"
                )
            if info.initial_failover_version in versions:
                raise ValueError(
                    f"clusters {versions[info.initial_failover_version]!r} and {name!r} "
                    "share an initial failover version"
                )
            versions[info.initial_failover_version] = name
        self._enable_global_domain = enable_global_domain
        self._increment = failover_version_increment
        self._master = master_cluster_name
        self._current = current_cluster_name
        self._info = dict(cluster_info)
        self._version_to_cluster = versions

    # -- identity ---------------------------------------------------------

    @property
    def is_global_domain_enabled(self) -> bool:
        return self._enable_global_domain

    @property
    def is_master_cluster(self) -> bool:
        return self._master == self._current

    @property
    def master_cluster_name(self) -> str:
        return self._master

    @property
    def current_cluster_name(self) -> str:
        return self._current

    @property
    def failover_version_increment(self) -> int:
        return self._increment

    def all_cluster_info(self) -> Dict[str, ClusterInformation]:
        return dict(self._info)

    def enabled_remote_clusters(self) -> list:
        return [
            name
            for name, info in self._info.items()
            if info.enabled and name != self._current
        ]

    # -- failover version arithmetic --------------------------------------

    def next_failover_version(self, cluster: str, current_version: int) -> int:
        """Smallest version >= current_version owned by ``cluster``
        (metadata.go GetNextFailoverVersion)."""
        info = self._info.get(cluster)
        if info is None:
            raise ValueError(f"unknown cluster {cluster!r}")
        # Sentinel inputs (e.g. EMPTY_VERSION = -24) land in cycle 0,
        # i.e. the cluster's initial failover version. This deliberately
        # deviates from the reference (whose truncating arithmetic can
        # return a negative version like -19 for -24, which no cluster
        # owns): a negative version means "no failover has happened", so
        # the next version owned by `cluster` is its cycle-0 version.
        current_version = max(current_version, 0)
        failed_version = info.initial_failover_version + (
            current_version // self._increment
        ) * self._increment
        if failed_version < current_version:
            failed_version += self._increment
        return failed_version

    def is_version_from_same_cluster(self, v1: int, v2: int) -> bool:
        return (v1 - v2) % self._increment == 0

    def cluster_name_for_failover_version(self, version: int) -> str:
        if version == EMPTY_VERSION:
            return self._current
        if version < 0:
            # Python's % yields a non-negative residue, so a corrupt
            # negative version would silently map onto a REAL cluster
            # (the Go reference's negative modulo fails the lookup);
            # surface the corruption instead of mis-routing it
            raise ValueError(
                f"invalid negative failover version {version}"
            )
        initial = version % self._increment
        name = self._version_to_cluster.get(initial)
        if name is None:
            raise ValueError(
                f"no cluster with initial failover version {initial} "
                f"(failover version {version})"
            )
        return name


# A two-cluster topology used throughout the tests (mirrors the reference's
# cluster.TestActiveClusterMetadata / host/xdc fixtures).
TEST_CLUSTER_METADATA = ClusterMetadata(
    failover_version_increment=10,
    master_cluster_name="active",
    current_cluster_name="active",
    cluster_info={
        "active": ClusterInformation(initial_failover_version=1),
        "standby": ClusterInformation(initial_failover_version=2),
    },
)
