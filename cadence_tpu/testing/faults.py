"""Deterministic fault injection for chaos/recovery testing.

The validation backbone the reconfigurable-SMR literature treats as
mandatory for replicated state machines: recovery invariants are only
credible when the fault schedule that exercises them is reproducible.
A ``FaultSchedule`` is a seeded RNG plus an ordered rule list; every
instrumented call site asks the schedule whether to misbehave, and the
schedule's answers are a pure function of (seed, rule list, call
sequence) — same seed, same workload, same faults, every run.

Three ways faults reach the system:

  * ``FaultInjectionClient`` — a persistence decorator in the same
    ``_Wrapped`` proxy family as the metrics/rate-limit clients
    (runtime/persistence/decorators.py). ``wrap_bundle(faults=...)``
    installs it INNERMOST (closest to the store) so the metrics client
    above it counts injected errors exactly like real backend errors.
    Sites are named ``persistence.<manager>`` and the method name is
    the persistence API name.
  * queue processors — ``QueueProcessorBase`` (and the timer twins)
    accept ``faults=`` and fire ``queue.<name>`` before every task
    attempt, exercising the in-line retry + park-on-exhaustion path.
  * replication — ``NDCHistoryReplicator`` fires
    ``replication.ndc``/``apply_events`` per applied task and
    ``ReplicatorQueueProcessor`` fires ``replication.replicator_queue``
    per fetch, exercising the at-least-once re-fetch contract.

Actions: raise one of the persistence error taxonomy (``error``), delay
the call (``latency``), or — the torn-write simulation — let the write
LAND and then raise as if the connection died on the response
(``torn_write``). Torn writes are the at-least-once storage reality
every retry loop must survive; point them at idempotent APIs.

A schedule can be armed/disarmed at runtime, so a chaos run can drive a
clean warm-up, flip faults on mid-workload, and flip them off to assert
the system drains back to a quiescent state.

Geographic link modeling (``LinkProfile``/``SimulatedLink``): the
degraded-WAN half of the chaos layer. A profile describes one
cross-cluster link — a bytes/sec budget, fixed latency, seeded jitter,
and partition windows over the transfer sequence — and ``chaos_link``
installs it over a replication ``RemoteClusterClient`` so every
``ReplicationTaskFetcher.fetch`` / ``get_workflow_history_raw`` /
snapshot transfer pays the link's cost (a real, bounded sleep) or hits
a partition (``LinkPartitionedError``). Determinism contract mirrors
``FaultSchedule``: delays and partitions are a pure function of
(profile, seed, transfer index), so the same workload sees the same
degraded link every run.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from cadence_tpu.runtime.persistence.decorators import (
    PersistenceBusyError,
    _Wrapped,
)
from cadence_tpu.runtime.persistence.errors import (
    ConditionFailedError,
    EntityNotExistsError,
    PersistenceError,
    ShardOwnershipLostError,
)
from cadence_tpu.utils import tracing
from cadence_tpu.utils.metrics import NOOP, Scope

ACTIONS = ("error", "latency", "torn_write")

# error taxonomy a rule may raise, by name (config/YAML friendly)
_ERRORS = {
    "PersistenceError": lambda msg, sid: PersistenceError(msg),
    "ConditionFailedError": lambda msg, sid: ConditionFailedError(msg),
    "EntityNotExistsError": lambda msg, sid: EntityNotExistsError(msg),
    "ShardOwnershipLostError": lambda msg, sid: ShardOwnershipLostError(
        sid if sid is not None else 0, msg
    ),
    "PersistenceBusyError": lambda msg, sid: PersistenceBusyError(msg),
    "TimeoutError": lambda msg, sid: TimeoutError(msg),
}


@dataclasses.dataclass
class FaultRule:
    """One match-and-misbehave rule.

    ``site``/``method`` are fnmatch patterns against the call site
    (``persistence.execution``, ``queue.transfer-0``,
    ``replication.ndc``) and the operation name. At ``persistence.*``
    sites the operation is the manager API name (``update_*``); at
    ``queue.*`` sites it is the task's ``task_type`` VALUE (e.g.
    ``"0"``) — queue attempts have no API name, so the task type is
    the discriminator; leave ``method="*"`` to hit every task.
    ``shard_id`` pins the rule to one shard (None = any). ``after_calls`` skips the first N
    matching calls (let the workload ramp up), ``max_faults`` stops
    injecting after N hits (bound the blast radius), ``probability`` is
    the per-call injection chance drawn from the schedule's seeded RNG.
    """

    site: str = "*"
    method: str = "*"
    shard_id: Optional[int] = None
    probability: float = 1.0
    after_calls: int = 0
    max_faults: Optional[int] = None
    action: str = "error"            # error | latency | torn_write
    error: str = "PersistenceError"  # key into the error taxonomy
    latency_s: float = 0.0
    message: str = ""

    def validate(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(f"fault rule: unknown action '{self.action}'")
        if self.action != "latency" and self.error not in _ERRORS:
            raise ValueError(f"fault rule: unknown error '{self.error}'")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError("fault rule: probability must be in [0, 1]")
        if self.after_calls < 0 or self.latency_s < 0:
            raise ValueError("fault rule: negative after_calls/latency_s")
        if self.max_faults is not None and self.max_faults < 0:
            # -1 is a plausible typo for "unlimited" (that's None); a
            # negative cap would silently disable the rule in plan()
            raise ValueError("fault rule: max_faults must be >= 0 or None")

    def matches(self, site: str, method: str, shard_id) -> bool:
        if self.shard_id is not None and shard_id != self.shard_id:
            return False
        return fnmatch.fnmatchcase(site, self.site) and fnmatch.fnmatchcase(
            method, self.method
        )


class _Plan:
    """One decided injection: what to do around the intercepted call."""

    __slots__ = ("action", "exc", "latency_s")

    def __init__(self, action, exc=None, latency_s=0.0):
        self.action = action
        self.exc = exc
        self.latency_s = latency_s


class FaultSchedule:
    """Seeded, rule-driven fault decider; thread-safe.

    Determinism contract: decisions are a function of (seed, rules,
    the sequence of matching calls). Concurrent callers serialize on
    the schedule lock, so two runs that present the same call sequence
    get the same fault sequence; a multi-threaded workload is
    deterministic up to its own interleaving.
    """

    def __init__(
        self,
        seed: int = 0,
        rules: Sequence[FaultRule] = (),
        metrics: Scope = NOOP,
        armed: bool = True,
    ) -> None:
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.seed = seed
        self.rules: List[FaultRule] = []
        self._matched: List[int] = []
        self._injected: List[int] = []
        self._armed = armed
        self._metrics = metrics.tagged(layer="fault_injection")
        for r in rules:
            self.add_rule(r)

    # -- lifecycle -----------------------------------------------------

    def add_rule(self, rule: FaultRule) -> "FaultSchedule":
        rule.validate()
        with self._lock:
            self.rules.append(rule)
            self._matched.append(0)
            self._injected.append(0)
        return self

    def arm(self) -> None:
        """Enable injection (chaos phase of a run)."""
        with self._lock:
            self._armed = True

    def disarm(self) -> None:
        """Stop injecting; in-flight latency injections finish."""
        with self._lock:
            self._armed = False

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._armed

    @classmethod
    def from_dicts(
        cls, specs: Sequence[Dict[str, Any]], seed: int = 0,
        metrics: Scope = NOOP,
    ) -> "FaultSchedule":
        """Build from config-shaped rule dicts (keys = FaultRule fields)."""
        names = {f.name for f in dataclasses.fields(FaultRule)}
        rules = []
        for i, spec in enumerate(specs):
            unknown = set(spec) - names
            if unknown:
                raise ValueError(
                    f"fault rule #{i}: unknown keys {sorted(unknown)}"
                )
            rules.append(FaultRule(**spec))
        return cls(seed=seed, rules=rules, metrics=metrics)

    # -- decision ------------------------------------------------------

    def plan(
        self, site: str, method: str = "", shard_id: Optional[int] = None
    ) -> Optional[_Plan]:
        """Decide whether this call misbehaves. First matching rule
        wins; every matching call consumes exactly one RNG draw whether
        or not it fires, so adding ``after_calls``/``max_faults`` to a
        rule does not shift the draws of later calls."""
        with self._lock:
            if not self._armed:
                return None
            for i, rule in enumerate(self.rules):
                if not rule.matches(site, method, shard_id):
                    continue
                self._matched[i] += 1
                draw = self._rng.random()
                if self._matched[i] <= rule.after_calls:
                    return None
                if (
                    rule.max_faults is not None
                    and self._injected[i] >= rule.max_faults
                ):
                    return None
                if draw >= rule.probability:
                    return None
                self._injected[i] += 1
                plan = self._build_plan(rule, site, method, shard_id)
                break
            else:
                return None
        self._metrics.tagged(site=site, action=plan.action).inc(
            "faults_injected"
        )
        # a sampled trace passing through this call site records the
        # injection as a span annotation (utils/tracing.py) — a chaos
        # failure's trace shows WHERE the faults landed next to the
        # retries they caused, instead of hand-correlating logs
        tracing.annotate(
            f"fault_injected site={site} method={method} "
            f"action={plan.action}"
        )
        return plan

    def _build_plan(self, rule, site, method, shard_id) -> _Plan:
        if rule.action == "latency":
            return _Plan("latency", latency_s=rule.latency_s)
        msg = rule.message or (
            f"[fault-injected] {rule.error} at {site}.{method}"
        )
        exc = _ERRORS[rule.error](msg, shard_id)
        return _Plan(rule.action, exc=exc)

    def fire(
        self, site: str, method: str = "", shard_id: Optional[int] = None
    ) -> None:
        """Hook form for call sites with no wrapped write to tear:
        raise or delay per the schedule (torn_write degenerates to a
        plain post-hoc error here)."""
        plan = self.plan(site, method, shard_id)
        if plan is None:
            return
        if plan.action == "latency":
            time.sleep(plan.latency_s)
            return
        raise plan.exc

    # -- observability -------------------------------------------------

    def injected_total(self) -> int:
        with self._lock:
            return sum(self._injected)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Per-rule (matched, injected) counts — the chaos suite's
        assertion surface and the operator's blast-radius view."""
        with self._lock:
            return [
                {
                    "site": r.site,
                    "method": r.method,
                    "action": r.action,
                    "matched": m,
                    "injected": j,
                }
                for r, m, j in zip(self.rules, self._matched, self._injected)
            ]


def hook(schedule: Optional[FaultSchedule], site: str,
         shard_id: Optional[int] = None):
    """``schedule.fire`` bound to one site (and optionally a default
    shard id, for call sites that belong to one shard), or None when no
    schedule is configured — what the queue/replication layers store so
    the disabled path is a single ``is None`` check."""
    if schedule is None:
        return None

    def fire(method: str = "", sid: Optional[int] = None) -> None:
        schedule.fire(site, method, sid if sid is not None else shard_id)

    return fire


# ---------------------------------------------------------------------------
# geographic link modeling
# ---------------------------------------------------------------------------


class LinkPartitionedError(ConnectionError):
    """The simulated WAN link is inside a partition window — the
    transfer never happened (nothing was delivered, nothing acked)."""


@dataclasses.dataclass
class LinkProfile:
    """One cross-cluster link's degradation envelope.

    ``bytes_per_s`` is the link budget (0 = unthrottled): a transfer of
    N bytes sleeps ``N / bytes_per_s`` before returning, which is what
    makes replication-lag-under-constrained-bandwidth measurable in
    real wall time. ``latency_s`` adds a fixed per-transfer RTT;
    ``jitter_s`` adds a uniform seeded draw in ``[0, jitter_s)``.
    ``partitions`` are half-open ``[start, end)`` windows over the
    TRANSFER INDEX (deterministic under frozen clocks, unlike
    wall-time windows): transfer k inside a window raises
    ``LinkPartitionedError`` instead of delivering. ``max_sleep_s``
    caps any single injected sleep so a mis-sized test profile cannot
    wedge a suite (0 = uncapped, what the bench uses)."""

    bytes_per_s: float = 0.0
    latency_s: float = 0.0
    jitter_s: float = 0.0
    partitions: Sequence[Tuple[int, int]] = ()
    max_sleep_s: float = 0.0

    def validate(self) -> None:
        if self.bytes_per_s < 0 or self.latency_s < 0 or self.jitter_s < 0:
            raise ValueError("link profile: negative budget/latency/jitter")
        if self.max_sleep_s < 0:
            raise ValueError("link profile: negative max_sleep_s")
        for w in self.partitions:
            if len(w) != 2 or w[0] < 0 or w[1] < w[0]:
                raise ValueError(f"link profile: bad partition window {w}")


class SimulatedLink:
    """Seeded, thread-safe link shaper; one instance = one direction of
    one geographic link. ``transfer(nbytes)`` consumes exactly one
    transfer index and one RNG draw whether or not the transfer lands,
    so reordering unrelated profile knobs never shifts later draws —
    the same determinism discipline as ``FaultSchedule.plan``."""

    def __init__(self, profile: LinkProfile, seed: int = 0) -> None:
        profile.validate()
        self.profile = profile
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._transfers = 0
        self._forced_partition = False
        self.bytes_total = 0
        self.partitioned_calls = 0
        self.slept_s = 0.0

    def force_partition(self, on: bool) -> None:
        """Manual region-loss switch for drill choreography ("the WAN
        segment is down NOW, heal it THERE") — deterministic as long as
        the caller toggles it at deterministic points, unlike wall-time
        windows and unconstrained by the transfer index the profile
        windows key on. Forced-partitioned transfers still consume
        their transfer index and RNG draw, so toggling never shifts
        later draws."""
        with self._lock:
            self._forced_partition = bool(on)

    def _partitioned(self, index: int) -> bool:
        if self._forced_partition:
            return True
        return any(a <= index < b for a, b in self.profile.partitions)

    def transfer(self, nbytes: int) -> float:
        """Charge one transfer of ``nbytes`` against the link; returns
        the delay applied (seconds). Raises ``LinkPartitionedError``
        inside a partition window."""
        p = self.profile
        with self._lock:
            index = self._transfers
            self._transfers += 1
            jitter = self._rng.random() * p.jitter_s
            if self._partitioned(index):
                self.partitioned_calls += 1
                tracing.annotate(
                    f"link_partitioned transfer={index}"
                )
                raise LinkPartitionedError(
                    f"[link-chaos] transfer {index} dropped "
                    f"(partition window)"
                )
            self.bytes_total += max(0, int(nbytes))
            delay = p.latency_s + jitter
            if p.bytes_per_s > 0:
                delay += max(0, int(nbytes)) / p.bytes_per_s
            if p.max_sleep_s > 0:
                delay = min(delay, p.max_sleep_s)
            self.slept_s += delay
        if delay > 0:
            time.sleep(delay)
        return delay

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "transfers": self._transfers,
                "bytes_total": self.bytes_total,
                "partitioned_calls": self.partitioned_calls,
                "slept_s": self.slept_s,
            }


class ChaosLinkClient:
    """``RemoteClusterClient`` decorator that ships every response over
    a ``SimulatedLink`` — the installation point for link chaos at the
    replication fetch sites (``ReplicationTaskFetcher.fetch`` and the
    rereplicator/backfill ``get_workflow_history_raw`` both dial
    through the wrapped client, as does the adaptive snapshot plane).

    The response is serialized through the replication wire-size
    estimator to charge the link with honest byte counts, then the link
    sleeps (bandwidth + latency + jitter) or raises
    ``LinkPartitionedError`` — exactly what a dead WAN segment does to
    a puller: no data, no cursor movement, retry later."""

    def __init__(self, base: Any, link: SimulatedLink) -> None:
        self._base = base
        self.link = link

    def _shipped(self, payload):
        from cadence_tpu.runtime.replication.transport import wire_size

        self.link.transfer(wire_size(payload))
        return payload

    def get_replication_messages(self, shard_id, last_retrieved_id,
                                 max_tasks=None):
        return self._shipped(
            self._base.get_replication_messages(
                shard_id, last_retrieved_id, max_tasks=max_tasks
            )
        )

    def get_workflow_history_raw(self, domain_id, workflow_id, run_id,
                                 start_event_id, end_event_id):
        return self._shipped(self._base.get_workflow_history_raw(
            domain_id, workflow_id, run_id, start_event_id, end_event_id
        ))

    def get_replication_backlog(self, shard_id, last_retrieved_id):
        return self._shipped(self._base.get_replication_backlog(
            shard_id, last_retrieved_id
        ))

    def get_replication_checkpoint(self, domain_id, workflow_id, run_id):
        return self._shipped(self._base.get_replication_checkpoint(
            domain_id, workflow_id, run_id
        ))

    def __getattr__(self, name: str):
        # anything beyond the replication surface passes through unshaped
        return getattr(self._base, name)


def chaos_link(client: Any, profile: LinkProfile,
               seed: int = 0) -> ChaosLinkClient:
    """Wrap a remote-cluster client in a seeded degraded link."""
    return ChaosLinkClient(client, SimulatedLink(profile, seed=seed))


class FaultInjectionClient(_Wrapped):
    """Persistence decorator that consults a FaultSchedule per call.

    Installed innermost by ``wrap_bundle(faults=...)`` — the metrics
    client above it observes injected errors/latency exactly like real
    backend misbehavior. ``torn_write`` executes the real call and then
    raises, simulating a write that landed while the response was lost;
    callers' retries then face the duplicate-write reality.
    """

    def __init__(
        self, base: Any, schedule: FaultSchedule, manager: str = "",
    ) -> None:
        super().__init__(base)
        self._schedule = schedule
        self._site = f"persistence.{manager or type(base).__name__}"

    @staticmethod
    def _shard_id(args, kwargs) -> Optional[int]:
        """Best-effort shard resolution across the manager APIs: an
        explicit kwarg, the shard_id-first convention of the execution
        manager, or a record argument carrying .shard_id (ShardInfo in
        update_shard/create_shard) — without the last one, a
        shard-pinned rule on persistence.shard would silently never
        match and the chaos run would be vacuous."""
        sid = kwargs.get("shard_id")
        if sid is None and args:
            if isinstance(args[0], int):
                sid = args[0]
            else:
                sid = getattr(args[0], "shard_id", None)
        return sid

    def _invoke(self, name, method, args, kwargs):
        plan = self._schedule.plan(
            self._site, name, self._shard_id(args, kwargs)
        )
        if plan is None:
            return method(*args, **kwargs)
        if plan.action == "latency":
            time.sleep(plan.latency_s)
            return method(*args, **kwargs)
        if plan.action == "torn_write":
            method(*args, **kwargs)  # the write LANDS; the ack is lost
            raise plan.exc
        raise plan.exc
