"""Runtime effect witness: record queue-task persistence effects and
check them against the static footprints.

The dynamic half of Pass 5's bidirectional proof
(``cadence_tpu/analysis/queue_effects.py``). The static side
AST-derives each queue-task type's effect footprint and gates it
against the declared table (``runtime/queues/effects.py``); this module
validates the same claim under execution — including the ≥10%
write-fault storm of the chaos suites, where retries, torn writes and
park/retry loops exercise paths an AST reading can only assume:

* ``EffectRecordingClient`` — a persistence decorator in the
  ``_Wrapped`` family, installed innermost by
  ``wrap_bundle(effects=...)`` exactly like ``FaultInjectionClient``
  (the two compose: the witness sees the real call UNDER the fault
  client, so a torn write that landed is recorded and an injected
  error that never reached the store is not);
* ``EffectRecorder`` — the aggregation store: every persistence call
  made while a queue task is executing (attributed via
  ``runtime/queues/effects.task_effect_scope``) lands as
  (plane, task type) → {(manager, method)};
* ``check_witness`` — recorded ⊆ footprint, per task type. Any
  recorded effect escaping its static footprint is a violation: either
  the handler grew an undeclared effect the AST extractor's
  neutral-by-default heuristic missed, or the footprint table is
  stale. Both mean the conflict matrix can no longer be trusted — the
  exact failure this witness exists to catch before the parallel
  queue does.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from cadence_tpu.runtime.persistence.decorators import _Wrapped
from cadence_tpu.runtime.queues import effects as rt_effects


class EffectRecorder:
    """Thread-safe (plane, task type) → {(manager, method)} aggregator.

    Install with :func:`install`; remove with :func:`uninstall` (or use
    ``recording()``). One recorder is expected per process at a time —
    the underlying hook is a module global, same as the tracer."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._calls: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}

    def record(self, plane: str, task_type: str, manager: str,
               method: str) -> None:
        with self._lock:
            self._calls.setdefault((plane, task_type), set()).add(
                (manager, method)
            )

    def snapshot(self) -> Dict[Tuple[str, str], Set[Tuple[str, str]]]:
        with self._lock:
            return {k: set(v) for k, v in self._calls.items()}

    def recorded_surfaces(
        self,
    ) -> Dict[Tuple[str, str], Set[Tuple[str, str]]]:
        """{(plane, task type) → {(surface, "r"|"w")}} — the recorded
        calls mapped through the shared verb→surface vocabulary."""
        out: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for key, calls in self.snapshot().items():
            surfaces: Set[Tuple[str, str]] = set()
            for manager, method in calls:
                surfaces.update(rt_effects.verb_effects(manager, method))
            out[key] = surfaces
        return out

    def install(self) -> "EffectRecorder":
        rt_effects.set_recorder(self.record)
        return self

    def uninstall(self) -> None:
        rt_effects.set_recorder(None)

    def __enter__(self) -> "EffectRecorder":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class EffectRecordingClient(_Wrapped):
    """Persistence decorator feeding the task-attribution hook.

    Pure pass-through when the calling thread is outside a task scope
    or no recorder is installed (one module-global check)."""

    def __init__(self, base, manager: str = "") -> None:
        super().__init__(base)
        self._manager = manager or type(base).__name__

    def _invoke(self, name, method, args, kwargs):
        rt_effects.record_persistence_call(self._manager, name)
        return method(*args, **kwargs)


def check_witness(
    recorder: EffectRecorder,
    footprints: Optional[Dict[Tuple[str, str], object]] = None,
) -> List[str]:
    """Violation messages for every recorded effect escaping its
    footprint (empty = the witness holds).

    ``footprints`` defaults to the DECLARED table (+ plane-common
    reads); the chaos witness test passes the AST-EXTRACTED footprints
    instead, which is the stronger check — it validates the extractor
    itself, not just the hand-maintained declarations."""
    violations: List[str] = []
    for (plane, ttype), surfaces in sorted(
        recorder.recorded_surfaces().items()
    ):
        if footprints is None:
            fp = rt_effects.effective_footprint(plane, ttype)
        else:
            fp = footprints.get((plane, ttype))
        if fp is None:
            violations.append(
                f"{plane}:{ttype}: task executed with NO footprint "
                f"(recorded {sorted(surfaces)})"
            )
            continue
        reads = set(fp.reads) | rt_effects.PLANE_COMMON_READS
        writes = set(fp.writes)
        for surface, kind in sorted(surfaces):
            if kind == "r":
                if surface not in reads and surface not in writes:
                    violations.append(
                        f"{plane}:{ttype}: recorded READ of {surface} "
                        "outside the static footprint"
                    )
            elif surface not in writes:
                violations.append(
                    f"{plane}:{ttype}: recorded WRITE of {surface} "
                    "outside the static footprint"
                )
    return violations
