"""Runtime concurrency sanitizer: lock-order + guarded-state race
witness, cross-validated against the static Pass 3 lock graph.

Pass 3 (``cadence_tpu/analysis/lock_order.py``) proves lock discipline
by AST reading — and carries a baseline of intentional findings whose
justifications nobody had ever re-verified under execution. This module
is the dynamic half, mirroring the PR 11 effect-witness pattern
(static footprint table + chaos-time recorder):

* ``RaceWitness`` — the tracker installed by
  ``utils/locks.wrap_locks``. Every tracked-lock acquisition feeds a
  **runtime lock-order graph** (with acquiring site + thread), every
  release a **held-duration** record, every guarded-container access a
  **lockset observation**, and every blocking operation performed
  while a tracked lock is held a **blocking observation**. Blocking
  ops reach the witness three ways: the ``SanitizerProbeClient``
  persistence decorator (``wrap_bundle(sanitize=True)``), and the
  patched ``time.sleep`` / ``queue.Queue.get``/``put`` /
  ``threading.Thread.join`` entry points installed by
  ``install()`` (all restored by ``uninstall()``; nothing is patched
  outside sanitizer mode).

Runtime rules (all reported as the same ``Finding`` objects the static
gate uses, so waivers ride the identical fnmatch machinery):

* **RUNTIME-LOCK-INVERSION** — the observed acquisition graph contains
  both A→B and B→A; reported with both threads' acquisition sites.
* **RUNTIME-LOCK-BLOCKING** — store I/O / sleep / join / a blocking
  queue op executed while a tracked lock was held. Anchored
  ``module:Class.method:lockattr:op`` — the same shape as Pass 3's
  LOCK-BLOCKING anchors, so a baselined static entry
  (``config/lint_baseline.json``) waives its runtime twin AND is
  thereby annotated *observed* in the ``--emit-lock-graph`` artifact.
* **GUARDED-FIELD-RACE** — an access to a declared guarded field
  (``utils/locks.make_guarded``) without the guarding lock held, from
  a second thread (or from the first thread after the field went
  shared). Eraser's lockset discipline specialized to a declared
  guard.
* **RUNTIME-EDGE-UNKNOWN** — cross-validation: a runtime-observed lock
  edge with no counterpart in the static Pass 3 graph means the static
  scan has a coverage hole (dynamic dispatch, callback indirection);
  either the static pass grows the edge or the hole is waived with a
  written justification in ``config/sanitizer_waivers.json``.

``check_race_witness`` is the gate: findings minus waivers (sanitizer
waiver file + the static lock baseline for blocking twins) must be
empty — enforced by the tier-1 sanitized Onebox test and the
``CHAOS_SANITIZE=1`` chaos sweep.
"""

from __future__ import annotations

import fnmatch
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from cadence_tpu.analysis.findings import Baseline, Finding, dedupe
from cadence_tpu.runtime.persistence.decorators import _Wrapped
from cadence_tpu.utils import locks

# the declared guarded-field table: short field name → guard short
# name, per owning class. Documentation + machine check: the sanitized
# Onebox test asserts every field here was actually REGISTERED (its
# make_guarded construction site ran) so the table can't silently rot.
GUARDED_FIELDS: Dict[str, str] = {
    "ShardContext._remote_cluster_time": "ShardContext._lock",
    "ShardContext._remote_time_listeners": "ShardContext._lock",
    "QueueAckManager._outstanding": "QueueAckManager._lock",
    "DomainCache._by_id": "DomainCache._lock",
    "DomainCache._by_name": "DomainCache._lock",
    "DomainCache._active_cluster": "DomainCache._lock",
    "MemoryCheckpointStore._rows": "MemoryCheckpointStore._lock",
    "MemoryCheckpointStore._tree": "MemoryCheckpointStore._lock",
    "MemoryShardManager._shards": "MemoryShardManager._lock",
    "MatchingEngine._managers": "MatchingEngine._lock",
    "MatchingEngine._creating": "MatchingEngine._lock",
    "MatchingEngine._pending_queries": "MatchingEngine._query_lock",
    "TaskWriter._queue": "TaskWriter._lock",
    "Registry._counters": "Registry._lock",
    "Registry._gauges": "Registry._lock",
    "Registry._timers": "Registry._lock",
    # continuous-batching serving engine (cadence_tpu/serving/): the
    # lane table, key index, and the fair-admission parked table all
    # ride ONE lock (the engine's — FairAdmissionQueue never acquires,
    # its callers hold the guard); packing/device steps/flushes never
    # run while it is held
    "ResidentEngine._slots": "ResidentEngine._lock",
    "ResidentEngine._by_key": "ResidentEngine._lock",
    "FairAdmissionQueue._parked": "ResidentEngine._lock",
    # capacity autopilot (runtime/autopilot.py): the rate setpoints and
    # the per-actuator cooldown table are written by the epoch thread
    # and read by the admin status/pause verbs
    "CapacityController._rates": "CapacityController._lock",
    "CapacityController._cooldowns": "CapacityController._lock",
    # parallel queue executor (runtime/queues/parallel.py): the slot
    # table is written by register/unregister (service threads) and
    # snapshotted by the pump; the lock is NEVER held across queue
    # collect/run calls, so the executor adds no lock-graph edges
    "ParallelQueueExecutor._slots": "ParallelQueueExecutor._lock",
}


class _EdgeObs:
    __slots__ = ("count", "thread", "holder_site", "acquire_site")

    def __init__(self, thread, holder_site, acquire_site):
        self.count = 1
        self.thread = thread
        self.holder_site = holder_site
        self.acquire_site = acquire_site


class _BlockObs:
    __slots__ = ("count", "kind", "detail")

    def __init__(self, kind, detail):
        self.count = 1
        self.kind = kind
        self.detail = detail


class _GuardObs:
    __slots__ = ("guard", "first_thread", "threads", "unheld")

    def __init__(self, guard):
        self.guard = guard
        self.first_thread = None
        self.threads: Set[int] = set()
        # anchor → (writing, thread, shared_at_access)
        self.unheld: Dict[str, Tuple[bool, int, bool]] = {}


def _short(lock_name: str) -> str:
    """"cadence_tpu/runtime/shard.py:ShardContext._lock" → "_lock"."""
    return lock_name.rsplit(":", 1)[-1].rsplit(".", 1)[-1]


class RaceWitness:
    """The runtime tracker. Install with ``install()`` (or use as a
    context manager); everything constructed through the
    ``utils/locks`` factory afterwards reports here."""

    def __init__(self) -> None:
        # a RAW threading.Lock on purpose: the witness must never
        # trace itself
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[str, str], _EdgeObs] = {}
        self._acquire_sites: Dict[str, Set[str]] = {}
        self._holds: Dict[str, Tuple[int, float]] = {}  # count, max_s
        self._blocking: Dict[str, _BlockObs] = {}       # anchor → obs
        self._guards: Dict[str, _GuardObs] = {}         # field → obs
        self._registered_guards: Dict[str, str] = {}
        self._patched = False
        self._orig: Dict[str, object] = {}

    # -- tracker callbacks (from utils/locks) --------------------------

    def on_acquire(self, lock, entry, prior) -> None:
        anchor = locks.site_anchor(entry.site)
        tid = threading.get_ident()
        with self._mu:
            self._acquire_sites.setdefault(lock.name, set()).add(anchor)
            if prior is not None and prior.lock.name != lock.name:
                key = (prior.lock.name, lock.name)
                obs = self._edges.get(key)
                if obs is None:
                    self._edges[key] = _EdgeObs(
                        tid,
                        locks.site_anchor(prior.site),
                        anchor,
                    )
                else:
                    obs.count += 1

    def on_release(self, lock, entry, held_s: float) -> None:
        with self._mu:
            count, mx = self._holds.get(lock.name, (0, 0.0))
            self._holds[lock.name] = (count + 1, max(mx, held_s))

    def on_blocking(self, entry, kind: str, detail: str) -> None:
        op = detail.rsplit(".", 1)[-1]
        anchor = (
            f"{locks.site_anchor(entry.site)}:"
            f"{_short(entry.lock.name)}:{op}"
        )
        with self._mu:
            obs = self._blocking.get(anchor)
            if obs is None:
                self._blocking[anchor] = _BlockObs(kind, detail)
            else:
                obs.count += 1

    def on_guard_registered(self, field: str, guard_name: str) -> None:
        with self._mu:
            self._registered_guards[field] = guard_name
            if field not in self._guards:
                self._guards[field] = _GuardObs(guard_name)

    def on_guarded_access(self, field: str, held: bool, writing: bool,
                          site) -> None:
        tid = threading.get_ident()
        with self._mu:
            obs = self._guards.get(field)
            if obs is None:
                obs = self._guards[field] = _GuardObs("")
            if obs.first_thread is None:
                obs.first_thread = tid
            obs.threads.add(tid)
            if not held and site is not None:
                anchor = locks.site_anchor(site)
                new = (writing, tid, len(obs.threads) > 1)
                cur = obs.unheld.get(anchor)

                def _exempt(t):
                    # matches the findings() exemption: owner thread,
                    # before the field ever went shared
                    return t[1] == obs.first_thread and not t[2]

                # keep the WORST observation per site: an exempt
                # init-time record must not mask a later genuine race
                # at the same anchor (second thread, or post-sharing)
                if cur is None or (_exempt(cur) and not _exempt(new)):
                    obs.unheld[anchor] = new

    # -- install / uninstall -------------------------------------------

    def install(self) -> "RaceWitness":
        locks.wrap_locks(self)
        if not self._patched:
            self._orig = {
                "sleep": time.sleep,
                "qget": queue.Queue.get,
                "qput": queue.Queue.put,
                "join": threading.Thread.join,
            }
            orig_sleep = self._orig["sleep"]
            orig_qget = self._orig["qget"]
            orig_qput = self._orig["qput"]
            orig_join = self._orig["join"]

            def _sleep(seconds):
                locks.note_blocking("sleep", "time.sleep")
                return orig_sleep(seconds)

            def _qget(q, block=True, timeout=None):
                if block and timeout != 0:
                    locks.note_blocking("queue", "Queue.get")
                return orig_qget(q, block, timeout)

            def _qput(q, item, block=True, timeout=None):
                if block and timeout != 0:
                    locks.note_blocking("queue", "Queue.put")
                return orig_qput(q, item, block, timeout)

            def _join(thread, timeout=None):
                locks.note_blocking("join", "Thread.join")
                return orig_join(thread, timeout)

            time.sleep = _sleep
            queue.Queue.get = _qget
            queue.Queue.put = _qput
            threading.Thread.join = _join
            self._patched = True
        return self

    def uninstall(self) -> None:
        locks.unwrap_locks()
        if self._patched:
            time.sleep = self._orig["sleep"]
            queue.Queue.get = self._orig["qget"]
            queue.Queue.put = self._orig["qput"]
            threading.Thread.join = self._orig["join"]
            self._patched = False

    def __enter__(self) -> "RaceWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- report / findings ---------------------------------------------

    def observed_edges(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted(self._edges)

    def registered_guard_fields(self) -> Dict[str, str]:
        """{full field name → guard name} for every make_guarded site
        that actually constructed a proxy under this witness."""
        with self._mu:
            return dict(self._registered_guards)

    def findings(self) -> List[Finding]:
        """The three runtime rules over everything observed so far
        (cross-validation against the static graph is separate — see
        ``cross_validate``)."""
        out: List[Finding] = []
        with self._mu:
            edges = dict(self._edges)
            blocking = dict(self._blocking)
            guards = dict(self._guards)
        reported: Set[Tuple[str, str]] = set()
        for (a, b), obs in sorted(edges.items()):
            rev = edges.get((b, a))
            if rev is None or (b, a) in reported:
                continue
            reported.add((a, b))
            out.append(Finding(
                "RUNTIME-LOCK-INVERSION",
                f"runtime-inversion:{min(a, b)}<->{max(a, b)}",
                f"observed {a} -> {b} (thread {obs.thread}: held at "
                f"{obs.holder_site}, acquired at {obs.acquire_site}) "
                f"AND {b} -> {a} (thread {rev.thread}: held at "
                f"{rev.holder_site}, acquired at {rev.acquire_site}) "
                "— deadlock-capable at runtime",
            ))
        for anchor, obs in sorted(blocking.items()):
            out.append(Finding(
                "RUNTIME-LOCK-BLOCKING",
                anchor,
                f"{obs.kind} op {obs.detail} executed {obs.count}x "
                "while the anchored lock was held",
            ))
        for field, obs in sorted(guards.items()):
            if len(obs.threads) < 2 or not obs.unheld:
                continue
            for anchor, (writing, tid, shared) in sorted(
                obs.unheld.items()
            ):
                if tid == obs.first_thread and not shared:
                    # single-owner initialization before the field
                    # ever went shared: exempt (Eraser's exclusive
                    # state)
                    continue
                out.append(Finding(
                    "GUARDED-FIELD-RACE",
                    f"guarded:{field}:{anchor}",
                    f"{'write' if writing else 'read'} of {field} at "
                    f"{anchor} without holding {obs.guard or 'its guard'}"
                    f" (field accessed by {len(obs.threads)} threads)",
                ))
        return dedupe(out)

    def report(self) -> Dict:
        """JSON-ready witness document (wrapped with the artifact
        envelope by ``save``)."""
        with self._mu:
            edges = [
                {
                    "a": a, "b": b, "count": o.count,
                    "holder_site": o.holder_site,
                    "acquire_site": o.acquire_site,
                }
                for (a, b), o in sorted(self._edges.items())
            ]
            acquires = {
                name: sorted(sites)
                for name, sites in sorted(self._acquire_sites.items())
            }
            holds = {
                name: {"count": c, "max_held_s": round(mx, 6)}
                for name, (c, mx) in sorted(self._holds.items())
            }
            blocking = [
                {
                    "anchor": anchor, "kind": o.kind,
                    "detail": o.detail, "count": o.count,
                }
                for anchor, o in sorted(self._blocking.items())
            ]
            guarded = {
                field: {
                    "guard": o.guard,
                    "threads": len(o.threads),
                    "unheld": [
                        {
                            "site": anchor, "writing": w,
                            "shared": shared,
                        }
                        for anchor, (w, _t, shared) in sorted(
                            o.unheld.items()
                        )
                    ],
                }
                for field, o in sorted(self._guards.items())
            }
        return {
            "edges": edges,
            "acquire_sites": acquires,
            "holds": holds,
            "blocking": blocking,
            "guarded": guarded,
            "findings": [
                {"rule": f.rule, "anchor": f.anchor, "message": f.message}
                for f in self.findings()
            ],
        }

    def save(self, path: str) -> None:
        """Persist the witness as the versioned ``lock_witness``
        artifact ``--emit-lock-graph`` consumes for its
        observed/never-observed annotations."""
        from cadence_tpu.analysis import artifact

        artifact.write_artifact(path, "lock_witness", self.report())


# --------------------------------------------------------------------------
# persistence probe (wrap_bundle(sanitize=True))
# --------------------------------------------------------------------------


class SanitizerProbeClient(_Wrapped):
    """Persistence decorator reporting store I/O performed while a
    tracked lock is held. Installed OUTERMOST by
    ``wrap_bundle(sanitize=True)`` so every attempted store call is
    seen — an injected fault that blocks the caller under a lock is
    as real a stall as a slow backend."""

    def __init__(self, base, manager: str = "") -> None:
        super().__init__(base)
        self._manager = manager or type(base).__name__

    def _invoke(self, name, method, args, kwargs):
        locks.note_blocking("store", f"{self._manager}.{name}")
        return method(*args, **kwargs)


# --------------------------------------------------------------------------
# cross-validation against the static Pass 3 graph
# --------------------------------------------------------------------------


def cross_validate(
    witness: "RaceWitness", repo_root: str, graph=None
) -> List[Finding]:
    """RUNTIME-EDGE-UNKNOWN for every observed acquisition-order edge
    absent from the static lock graph: the runtime saw an ordering the
    AST scan cannot — a static coverage hole to fix or waive.

    ``graph`` takes a prebuilt ``lock_order.LockGraph`` so a gate that
    also emits the artifact parses the tree once, not three times."""
    from cadence_tpu.analysis import lock_order

    if graph is None:
        graph = lock_order.build_graph(repo_root)
    static_edges = list(graph.edges)
    out: List[Finding] = []
    with witness._mu:
        observed = {
            k: (o.holder_site, o.acquire_site)
            for k, o in witness._edges.items()
        }
    for (a, b), (hsite, asite) in sorted(observed.items()):
        if lock_order.edge_in_static((a, b), static_edges):
            continue
        out.append(Finding(
            "RUNTIME-EDGE-UNKNOWN",
            f"runtime-edge:{a}->{b}",
            f"runtime-observed lock edge {a} -> {b} (held at {hsite}, "
            f"acquired at {asite}) has no counterpart in the static "
            "Pass 3 graph — static coverage hole",
        ))
    return out


# --------------------------------------------------------------------------
# the gate
# --------------------------------------------------------------------------

DEFAULT_WAIVERS = "config/sanitizer_waivers.json"
DEFAULT_BASELINE = "config/lint_baseline.json"

# static rules whose baselined entries waive a runtime blocking twin
_STATIC_BLOCKING_RULES = ("LOCK-BLOCKING", "LOCK-CROSS-BLOCKING")


def check_race_witness(
    witness: "RaceWitness",
    repo_root: str,
    waivers_path: Optional[str] = None,
    baseline_path: Optional[str] = None,
    graph=None,
) -> List[Finding]:
    """Unwaived runtime findings (empty = the sanitizer holds).

    A finding is waived by (a) a matching entry in the sanitizer
    waiver file (same rule, fnmatch anchor), or (b) for
    RUNTIME-LOCK-BLOCKING only, a baselined static LOCK-BLOCKING /
    LOCK-CROSS-BLOCKING entry matching the anchor — the runtime
    observation then serves as evidence FOR the baseline's prose
    justification instead of a new alarm (and flips that entry to
    *observed* in the lock-graph artifact)."""
    findings = witness.findings() + cross_validate(
        witness, repo_root, graph=graph
    )

    waivers = Baseline()
    wpath = waivers_path or os.path.join(repo_root, DEFAULT_WAIVERS)
    if os.path.isfile(wpath):
        waivers = Baseline.load(wpath)
    static_entries = []
    bpath = baseline_path or os.path.join(repo_root, DEFAULT_BASELINE)
    if os.path.isfile(bpath):
        static_entries = [
            e for e in Baseline.load(bpath).entries
            if e.rule in _STATIC_BLOCKING_RULES
        ]

    out: List[Finding] = []
    for f in findings:
        if any(e.matches(f) for e in waivers.entries):
            continue
        if f.rule == "RUNTIME-LOCK-BLOCKING" and any(
            fnmatch.fnmatchcase(f.anchor, e.anchor)
            for e in static_entries
        ):
            continue
        out.append(f)
    return out
