"""Trace demo: boot Onebox, run one workflow, dump its trace.

The zero-to-trace walkthrough (scripts/run_trace_demo.sh wraps it, a
tier-1 smoke test invokes it so the endpoint can't rot):

1. configure the process tracer (utils/tracing.py) and start a
   PProfServer on an ephemeral port;
2. boot an in-process Onebox and register a two-activity worker;
3. drive ONE workflow decision to completion inside an explicitly
   sampled root span — the production shape where the edge (an RPC
   endpoint at ``telemetry.sampleRate``) roots the trace;
4. fetch ``GET /debug/pprof/traces`` over real HTTP and pretty-print
   the Chrome-trace JSON (or a per-span summary with ``--summary``).

Exit status 0 requires the dumped trace to span frontend → history →
matching → queue → persistence with ≥ 6 spans and intact parent/child
links — the same invariant tests/test_telemetry.py asserts in-process.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def _doubler(ctx, input):
    a = yield ctx.schedule_activity("double", input)
    b = yield ctx.schedule_activity("double", a)
    return b


def run_demo(summary: bool = False, quiet: bool = False,
             timeout_s: float = 30.0) -> int:
    from cadence_tpu.runtime.api import StartWorkflowRequest
    from cadence_tpu.testing.onebox import Onebox
    from cadence_tpu.utils.pprof import PProfServer
    from cadence_tpu.utils.tracing import TRACER
    from cadence_tpu.worker import Worker

    def say(msg):
        if not quiet:
            print(msg, file=sys.stderr)

    TRACER.configure(sample_rate=1.0)
    TRACER.clear()
    pprof = PProfServer(port=0).start()
    box = Onebox(num_shards=2).start()
    TRACER.configure(metrics=box.metrics)
    w = Worker(box.frontend, "trace-demo", "trace-demo-tl",
               identity="trace-demo-worker")
    w.register_workflow("demo-wf", _doubler)
    w.register_activity("double", lambda inp: inp * 2)
    try:
        box.domain_handler.register_domain("trace-demo")
        w.start()
        say(f"onebox up; pprof on http://{pprof.address}")

        with TRACER.trace("workflow_decision", sampled=True,
                          service="demo") as root:
            trace_id = root.trace_id
            run_id = box.frontend.start_workflow_execution(
                StartWorkflowRequest(
                    domain="trace-demo", workflow_id="trace-demo-wf",
                    workflow_type="demo-wf", task_list="trace-demo-tl",
                    input=b"\x02", request_id="trace-demo-req",
                    execution_start_to_close_timeout_seconds=60,
                )
            )
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                d = box.frontend.describe_workflow_execution(
                    "trace-demo", "trace-demo-wf", run_id
                )
                if not d.is_running:
                    break
                time.sleep(0.02)
            else:
                say("workflow did not complete in time")
                return 1
        # let the asynchronous tail (queue/matching spans on pump
        # threads) finish into the flight recorder
        time.sleep(0.3)

        url = (f"http://{pprof.address}/debug/pprof/traces"
               f"?trace_id={trace_id}")
        say(f"GET {url}")
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            doc = json.loads(resp.read().decode())
    finally:
        w.stop()
        box.stop()
        pprof.stop()

    spans = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    services = {
        next(
            m["args"]["name"]
            for m in doc["traceEvents"]
            if m.get("ph") == "M" and m["pid"] == e["pid"]
        )
        for e in spans
    }
    ids = {e["args"]["span_id"] for e in spans}
    orphans = [
        e["name"] for e in spans
        if e["args"]["parent_id"] and e["args"]["parent_id"] not in ids
    ]

    if summary:
        for e in sorted(spans, key=lambda e: e["ts"]):
            print(f"{e['dur'] / 1000.0:9.3f}ms  "
                  f"{e['args']['parent_id'] and '└ ' or ''}{e['name']}")
    else:
        print(json.dumps(doc, indent=1))

    say(f"trace {trace_id}: {len(spans)} spans across "
        f"{sorted(services)}")
    required = {"frontend", "history", "matching", "history_queue",
                "persistence"}
    missing = required - services
    if missing:
        say(f"FAIL: trace is missing service planes: {sorted(missing)}")
        return 1
    if len(spans) < 6:
        say(f"FAIL: expected >= 6 spans, got {len(spans)}")
        return 1
    if orphans:
        say(f"FAIL: spans with dangling parent links: {orphans}")
        return 1
    say("OK: single cross-service trace, parent/child links intact")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cadence_tpu.testing.trace_demo"
    )
    ap.add_argument("--summary", action="store_true",
                    help="print a per-span summary instead of raw JSON")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress progress chatter on stderr")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)
    return run_demo(summary=args.summary, quiet=args.quiet,
                    timeout_s=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
