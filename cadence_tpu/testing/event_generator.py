"""Model-based random history generator.

The framework's equivalent of the reference's event-graph generator
(/root/reference/common/testing/event_generator.go:38-551): it simulates a
workflow's legal state machine and emits random *valid* walks — histories
any replayer must accept — grouped into transaction batches the way the
active side persists them. Used as fuzz input for kernel-vs-oracle
differential testing and NDC replication tests.

Every generated history is deterministic in the seed, fits the supplied
``Capacities``, uses whole-second timestamps (the device time quantum),
and keeps failover versions monotonic.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Set

from cadence_tpu.core import history_factory as F
from cadence_tpu.core.enums import ParentClosePolicy, TimeoutType
from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.ids import EMPTY_EVENT_ID
from cadence_tpu.core.mutable_state import SECOND
from cadence_tpu.ops.schema import Capacities


class HistoryFuzzer:
    def __init__(
        self,
        seed: int = 0,
        caps: Optional[Capacities] = None,
        version_bump_prob: float = 0.05,
    ) -> None:
        self.rng = random.Random(seed)
        self.caps = caps or Capacities()
        self.version_bump_prob = version_bump_prob

    def generate(
        self,
        target_events: int = 40,
        start_time: int = 1_700_000_000 * SECOND,
        version: int = 10,
        close: bool = True,
        close_prob: float = 0.1,
    ) -> List[List[HistoryEvent]]:
        """One random valid history as a list of transaction batches."""
        rng = self.rng
        caps = self.caps
        batches: List[List[HistoryEvent]] = []

        eid = 1
        t = start_time
        v = version
        version_items = 1
        # simulation state
        dec_scheduled: Optional[int] = None
        dec_started: Optional[int] = None
        dec_attempt = 0
        acts_scheduled: Dict[int, str] = {}   # schedule_id → activity_id
        acts_started: Dict[int, int] = {}     # schedule_id → started_id
        act_names_live: Set[str] = set()
        act_counter = 0
        timers: Dict[str, int] = {}           # timer_id → started_id
        timer_counter = 0
        children_init: Dict[int, Optional[int]] = {}  # initiated → started_id|None
        child_counter = 0
        cancels: Set[int] = set()
        signals: Set[int] = set()
        closed = False

        def bump_time() -> None:
            nonlocal t
            t += rng.randint(0, 5) * SECOND

        def bump_version() -> None:
            nonlocal v, version_items
            if (
                version_items < caps.max_version_items
                and rng.random() < self.version_bump_prob
            ):
                v += rng.randint(1, 3) * 10
                version_items += 1

        def next_id() -> int:
            nonlocal eid
            out = eid
            eid += 1
            return out

        def emit(batch: List[HistoryEvent]) -> None:
            batches.append(batch)

        # ---- start
        emit([F.workflow_execution_started(
            next_id(), v, t,
            task_list="tl", workflow_type="fuzz",
            execution_start_to_close_timeout_seconds=3600,
            task_start_to_close_timeout_seconds=10,
        )])

        def schedule_decision() -> None:
            nonlocal dec_scheduled
            sid = next_id()
            emit([F.decision_task_scheduled(sid, v, t, attempt=dec_attempt)])
            dec_scheduled = sid

        def total_pending() -> int:
            return (
                len(acts_scheduled) + len(timers) + len(children_init)
                + len(cancels) + len(signals)
            )

        while not closed and eid < target_events:
            bump_time()
            bump_version()

            # decision lifecycle drives most progress
            if dec_scheduled is None and dec_started is None:
                choice = rng.random()
                if choice < 0.55:
                    schedule_decision()
                    continue
                # async environment events between decisions
                self._async_event(
                    _Bundle(
                        rng=rng, v=v, t=t, next_id=next_id, emit=emit,
                        acts_scheduled=acts_scheduled, acts_started=acts_started,
                        act_names_live=act_names_live, timers=timers,
                        children_init=children_init, cancels=cancels,
                        signals=signals,
                    )
                )
                continue

            if dec_scheduled is not None and dec_started is None:
                r = rng.random()
                if r < 0.8:
                    sid = next_id()
                    emit([F.decision_task_started(sid, v, t,
                                                  scheduled_event_id=dec_scheduled)])
                    dec_started = sid
                    dec_attempt = 0
                else:
                    # sticky schedule-to-start timeout: decision dropped and
                    # the FSM resets the attempt (fail_decision(False))
                    emit([F.decision_task_timed_out(
                        next_id(), v, t, scheduled_event_id=dec_scheduled,
                        timeout_type=TimeoutType.ScheduleToStart)])
                    dec_scheduled = None
                    dec_attempt = 0
                continue

            # in-flight decision: complete (usually), fail, or time out
            r = rng.random()
            if r < 0.08:
                emit([F.decision_task_failed(
                    next_id(), v, t, scheduled_event_id=dec_scheduled,
                    started_event_id=dec_started)])
                dec_attempt += 1
                # transient decision is in memory; the next scheduled event
                # carries the attempt
                dec_scheduled = dec_started = None
                schedule_decision()
                continue
            if r < 0.14:
                emit([F.decision_task_timed_out(
                    next_id(), v, t, scheduled_event_id=dec_scheduled,
                    started_event_id=dec_started)])
                dec_attempt += 1
                dec_scheduled = dec_started = None
                schedule_decision()
                continue

            # complete + commands in one transaction batch
            batch = [F.decision_task_completed(
                next_id(), v, t, scheduled_event_id=dec_scheduled,
                started_event_id=dec_started)]
            completed_id = batch[0].event_id
            dec_scheduled = dec_started = None

            n_cmds = rng.randint(0, 3)
            for _ in range(n_cmds):
                if eid >= target_events:
                    break
                cmd = rng.random()
                if cmd < 0.35 and len(acts_scheduled) < caps.max_activities - 1:
                    act_counter += 1
                    name = f"act-{act_counter}"
                    sid = next_id()
                    batch.append(F.activity_task_scheduled(
                        sid, v, t, activity_id=name,
                        decision_task_completed_event_id=completed_id,
                        schedule_to_start_timeout_seconds=rng.choice([0, 10]),
                        schedule_to_close_timeout_seconds=rng.choice([0, 60]),
                        start_to_close_timeout_seconds=rng.choice([0, 30]),
                        heartbeat_timeout_seconds=rng.choice([0, 0, 5]),
                    ))
                    acts_scheduled[sid] = name
                    act_names_live.add(name)
                elif cmd < 0.5 and len(timers) < caps.max_timers - 1:
                    timer_counter += 1
                    name = f"timer-{timer_counter}"
                    sid = next_id()
                    batch.append(F.timer_started(
                        sid, v, t, timer_id=name,
                        start_to_fire_timeout_seconds=rng.randint(1, 120),
                        decision_task_completed_event_id=completed_id))
                    timers[name] = sid
                elif cmd < 0.6 and len(children_init) < caps.max_children - 1:
                    child_counter += 1
                    sid = next_id()
                    batch.append(F.start_child_initiated(
                        sid, v, t, domain="dom",
                        workflow_id=f"child-{child_counter}",
                        parent_close_policy=rng.choice(list(ParentClosePolicy)),
                        decision_task_completed_event_id=completed_id))
                    children_init[sid] = None
                elif cmd < 0.68 and len(cancels) < caps.max_request_cancels - 1:
                    sid = next_id()
                    batch.append(F.request_cancel_external_initiated(
                        sid, v, t, domain="dom", workflow_id=f"ext-{sid}",
                        decision_task_completed_event_id=completed_id))
                    cancels.add(sid)
                elif cmd < 0.76 and len(signals) < caps.max_signals_ext - 1:
                    sid = next_id()
                    batch.append(F.signal_external_initiated(
                        sid, v, t, domain="dom", workflow_id=f"ext-{sid}",
                        decision_task_completed_event_id=completed_id))
                    signals.add(sid)
                elif cmd < 0.84:
                    batch.append(F.marker_recorded(
                        next_id(), v, t,
                        decision_task_completed_event_id=completed_id))
                elif cmd < 0.9 and act_names_live:
                    name = rng.choice(sorted(act_names_live))
                    batch.append(F.activity_task_cancel_requested(
                        next_id(), v, t, activity_id=name,
                        decision_task_completed_event_id=completed_id))
                elif cmd < 0.96 and timers:
                    name = rng.choice(sorted(timers))
                    started = timers.pop(name)
                    batch.append(F.timer_canceled(
                        next_id(), v, t, timer_id=name, started_event_id=started,
                        decision_task_completed_event_id=completed_id))
                else:
                    batch.append(F.upsert_workflow_search_attributes(
                        next_id(), v, t,
                        search_attributes={f"k{rng.randint(0,3)}": b"v"},
                        decision_task_completed_event_id=completed_id))

            # maybe close in this same batch
            if close and (eid >= target_events or rng.random() < close_prob):
                closer = rng.random()
                if closer < 0.5:
                    batch.append(F.workflow_execution_completed(
                        next_id(), v, t,
                        decision_task_completed_event_id=completed_id))
                elif closer < 0.75:
                    batch.append(F.workflow_execution_failed(
                        next_id(), v, t,
                        decision_task_completed_event_id=completed_id,
                        reason="fuzz"))
                else:
                    batch.append(F.workflow_execution_canceled(
                        next_id(), v, t,
                        decision_task_completed_event_id=completed_id))
                closed = True
            emit(batch)

        if not closed and close:
            # hard close from the environment: terminate or time out
            # (both legal at any point; timeout is how the timer queue
            # closes an expired run, so replayers must accept it too)
            bump_time()
            if rng.random() < 0.25:
                emit([F.workflow_execution_timed_out(next_id(), v, t)])
            else:
                emit([F.workflow_execution_terminated(
                    next_id(), v, t, reason="fuzz-end")])
        return batches

    # ------------------------------------------------------------------

    def _async_event(self, b: "_Bundle") -> None:
        """One environment-driven transaction batch (activity progress,
        timer fire, child/external resolution, signal, cancel request)."""
        rng = b.rng
        options = []
        unstarted = [sid for sid in b.acts_scheduled if sid not in b.acts_started]
        started = list(b.acts_started)
        if unstarted:
            options.append("act_start")
            options.append("act_s2s_timeout")
        if started:
            options.extend(["act_complete", "act_fail", "act_timeout"])
        if b.timers:
            options.append("timer_fire")
        pending_children = [i for i, s in b.children_init.items() if s is None]
        started_children = [i for i, s in b.children_init.items() if s is not None]
        if pending_children:
            options.extend(["child_start", "child_start_failed"])
        if started_children:
            options.append("child_close")
        if b.cancels:
            options.append("cancel_resolve")
        if b.signals:
            options.append("signal_resolve")
        options.append("wf_signal")
        options.append("wf_cancel_request")
        choice = rng.choice(options)

        if choice == "act_start":
            sid = rng.choice(unstarted)
            ev_id = b.next_id()
            b.emit([F.activity_task_started(ev_id, b.v, b.t, scheduled_event_id=sid)])
            b.acts_started[sid] = ev_id
        elif choice == "act_s2s_timeout":
            sid = rng.choice(unstarted)
            b.emit([F.activity_task_timed_out(
                b.next_id(), b.v, b.t, scheduled_event_id=sid,
                started_event_id=EMPTY_EVENT_ID,
                timeout_type=TimeoutType.ScheduleToStart)])
            b.act_names_live.discard(b.acts_scheduled.pop(sid))
        elif choice in ("act_complete", "act_fail", "act_timeout"):
            sid = rng.choice(started)
            st = b.acts_started.pop(sid)
            name = b.acts_scheduled.pop(sid)
            b.act_names_live.discard(name)
            if choice == "act_complete":
                ev = F.activity_task_completed(
                    b.next_id(), b.v, b.t, scheduled_event_id=sid, started_event_id=st)
            elif choice == "act_fail":
                ev = F.activity_task_failed(
                    b.next_id(), b.v, b.t, scheduled_event_id=sid, started_event_id=st,
                    reason="fuzz")
            else:
                ev = F.activity_task_timed_out(
                    b.next_id(), b.v, b.t, scheduled_event_id=sid, started_event_id=st,
                    timeout_type=rng.choice(
                        [TimeoutType.StartToClose, TimeoutType.Heartbeat]))
            b.emit([ev])
        elif choice == "timer_fire":
            name = rng.choice(sorted(b.timers))
            started = b.timers.pop(name)
            b.emit([F.timer_fired(b.next_id(), b.v, b.t, timer_id=name,
                                  started_event_id=started)])
        elif choice == "child_start":
            init = rng.choice(pending_children)
            ev_id = b.next_id()
            b.emit([F.child_execution_started(
                ev_id, b.v, b.t, initiated_event_id=init,
                workflow_id=f"child-{init}", run_id=f"crun-{init}")])
            b.children_init[init] = ev_id
        elif choice == "child_start_failed":
            init = rng.choice(pending_children)
            del b.children_init[init]
            b.emit([F.start_child_failed(
                b.next_id(), b.v, b.t, initiated_event_id=init, cause=0)])
        elif choice == "child_close":
            init = rng.choice(started_children)
            st = b.children_init.pop(init)
            kind = rng.random()
            if kind < 0.4:
                ev = F.child_execution_completed(
                    b.next_id(), b.v, b.t, initiated_event_id=init, started_event_id=st)
            elif kind < 0.6:
                ev = F.child_execution_failed(
                    b.next_id(), b.v, b.t, initiated_event_id=init, started_event_id=st)
            elif kind < 0.75:
                ev = F.child_execution_canceled(
                    b.next_id(), b.v, b.t, initiated_event_id=init, started_event_id=st)
            elif kind < 0.9:
                ev = F.child_execution_timed_out(
                    b.next_id(), b.v, b.t, initiated_event_id=init, started_event_id=st)
            else:
                ev = F.child_execution_terminated(
                    b.next_id(), b.v, b.t, initiated_event_id=init, started_event_id=st)
            b.emit([ev])
        elif choice == "cancel_resolve":
            init = rng.choice(sorted(b.cancels))
            b.cancels.discard(init)
            if rng.random() < 0.7:
                ev = F.external_workflow_execution_cancel_requested(
                    b.next_id(), b.v, b.t, initiated_event_id=init)
            else:
                ev = F.request_cancel_external_failed(
                    b.next_id(), b.v, b.t, initiated_event_id=init)
            b.emit([ev])
        elif choice == "signal_resolve":
            init = rng.choice(sorted(b.signals))
            b.signals.discard(init)
            if rng.random() < 0.7:
                ev = F.external_workflow_execution_signaled(
                    b.next_id(), b.v, b.t, initiated_event_id=init)
            else:
                ev = F.signal_external_failed(
                    b.next_id(), b.v, b.t, initiated_event_id=init)
            b.emit([ev])
        elif choice == "wf_cancel_request":
            # workflow-level cancel request: legal at any point while
            # running, idempotent on repeat (both replayers set a flag)
            b.emit([F.workflow_execution_cancel_requested(
                b.next_id(), b.v, b.t)])
        else:
            b.emit([F.workflow_execution_signaled(
                b.next_id(), b.v, b.t, signal_name=f"sig-{rng.randint(0, 9)}")])


class _Bundle:
    """Mutable references shared with _async_event."""

    def __init__(self, **kw):
        self.__dict__.update(kw)
