"""Onebox: a full cluster in one process.

Reference: host/onebox.go:69 — frontend + matching + history + worker
wired over real persistence with static membership (host/simpleMonitor
.go), the backbone of the reference's integration suite. Used here by
integration tests, the CLI's embedded server, and the canary.
"""

from __future__ import annotations

from typing import Optional

from cadence_tpu.client import HistoryClient, MatchingClient
from cadence_tpu.cluster import ClusterMetadata
from cadence_tpu.frontend import (
    AdminHandler,
    DCRedirectionHandler,
    DomainHandler,
    WorkflowHandler,
)
from cadence_tpu.matching import MatchingEngine
from cadence_tpu.messaging import MessageBus
from cadence_tpu.runtime.domains import DomainCache
from cadence_tpu.runtime.membership import single_host_monitor
from cadence_tpu.runtime.persistence.memory import create_memory_bundle
from cadence_tpu.runtime.service import HistoryService
from cadence_tpu.visibility import AdvancedVisibilityStore
from cadence_tpu.worker.archiver import ArchivalClient
from cadence_tpu.worker.service import WorkerService


class Onebox:
    def __init__(
        self,
        num_shards: int = 4,
        persistence=None,
        cluster_metadata: Optional[ClusterMetadata] = None,
        host_identity: str = "onebox-0",
        start_worker: bool = True,
        queue_worker_count: int = 4,
        faults=None,
        time_source=None,
        poll_request_id_fn=None,
        checkpoints=None,
        serving=None,
        sanitize: bool = False,
        autopilot=None,
        queue_parallel: int = 0,
    ) -> None:
        self.faults = faults
        self.persistence = persistence or create_memory_bundle()
        # every Onebox carries a real metrics scope and a metrics-
        # wrapped bundle: per-store histogram latencies and the
        # persistence hop of request traces are observable in every
        # integration test, not just chaos runs (the MetricsClient's
        # untraced cost is a perf_counter pair per call). Fault
        # injection (chaos mode) additionally installs the fault client
        # innermost; the default path installs no fault machinery.
        from cadence_tpu.runtime.persistence.decorators import wrap_bundle
        from cadence_tpu.utils.metrics import Scope

        # sanitize: the concurrency sanitizer's store probe
        # (RUNTIME-LOCK-BLOCKING) — pair with a RaceWitness installed
        # via utils/locks.wrap_locks BEFORE constructing the box
        self.metrics = Scope()
        self.persistence = wrap_bundle(
            self.persistence, metrics=self.metrics, faults=faults,
            sanitize=sanitize,
        )
        self.bus = MessageBus()
        self.cluster_metadata = cluster_metadata or ClusterMetadata()
        self.domain_handler = DomainHandler(
            self.persistence.metadata, self.cluster_metadata,
            replication_producer=self.bus.new_producer("domain-replication"),
        )
        self.domains = DomainCache(self.persistence.metadata)
        self.monitor = single_host_monitor(host_identity)
        # checkpoints: True builds a CheckpointManager over the bundle's
        # checkpoint store (fault-wrapped above when chaos is on); or
        # pass a ready CheckpointManager; None/False = cold rebuilds
        if checkpoints is True:
            from cadence_tpu.checkpoint import CheckpointManager

            checkpoints = (
                CheckpointManager(self.persistence.checkpoint)
                if self.persistence.checkpoint is not None else None
            )
        self.checkpoints = checkpoints or None
        # serving: True builds a ResidentEngine (continuous-batching
        # resident serving megabatch) over the fault-wrapped history
        # manager + this box's checkpoint plane; or pass a ready
        # ResidentEngine; None/False = serving reads rebuild cold
        if serving is True:
            from cadence_tpu.serving import ResidentEngine

            serving = ResidentEngine(
                checkpoints=self.checkpoints,
                history=self.persistence.history,
                metrics=self.metrics,
            )
        self.serving = serving or None
        # queue_parallel > 0: the shared conflict-keyed wave executor
        # (queues.parallelism gate) over this box's transfer/timer
        # pumps. Built from the live footprint table (matrix=None →
        # ConflictMatrix.live()), so it is fresh by construction and
        # never degrades in-process.
        self.queue_executor = None
        if queue_parallel:
            from cadence_tpu.runtime.queues.parallel import (
                ParallelQueueExecutor,
            )

            self.queue_executor = ParallelQueueExecutor(
                parallelism=queue_parallel, metrics=self.metrics
            )
        self.history = HistoryService(
            num_shards, self.persistence, self.domains, self.monitor,
            cluster_metadata=self.cluster_metadata,
            queue_worker_count=queue_worker_count,
            metrics=self.metrics,
            faults=faults,
            time_source=time_source,
            checkpoints=self.checkpoints,
            serving=self.serving,
            queue_executor=self.queue_executor,
        )
        self.history_client = HistoryClient(
            self.history.controller, metrics=self.metrics
        )
        # the clock and the poll nonce are the two entropy sources a
        # deterministic chaos run must pin: matching shares history's
        # time source, and poll_request_id_fn replaces the per-poll
        # uuid with a caller-derived id (see tests/test_chaos_recovery)
        self.matching = MatchingEngine(
            self.persistence.task, self.history_client,
            time_source=time_source,
            metrics=self.metrics,
            poll_request_id_fn=poll_request_id_fn,
        )
        self.matching_client = MatchingClient(self.matching)
        self.history.wire(self.matching_client, self.history_client)
        self.visibility = AdvancedVisibilityStore(self.persistence.visibility)
        self.frontend = WorkflowHandler(
            self.domain_handler, self.domains,
            self.history_client, self.matching_client,
            visibility=self.visibility,
            metrics=self.metrics,
        )
        self.admin = AdminHandler(self.history, self.domains, bus=self.bus)
        # autopilot: True builds an in-process CapacityController over
        # this box's registry + shared reshard coordinator (epoch loop
        # starts/stops with the history service); or pass an
        # AutopilotConfig for custom knobs; None/False = manual capacity
        self.autopilot = None
        if autopilot:
            from cadence_tpu.config.static import AutopilotConfig
            from cadence_tpu.runtime.autopilot import CapacityController

            ap_cfg = (
                autopilot if isinstance(autopilot, AutopilotConfig)
                else AutopilotConfig(enabled=True)
            )
            rate_hooks = {}
            initial_rates = {}
            if (self.serving is not None
                    and self.serving.admission_quota_rps() > 0):
                from cadence_tpu.runtime.autopilot import (
                    KEY_SERVING_QUOTA_RPS,
                )

                rate_hooks[KEY_SERVING_QUOTA_RPS] = (
                    self.serving.retune_admission
                )
                initial_rates[KEY_SERVING_QUOTA_RPS] = (
                    self.serving.admission_quota_rps()
                )
            self.autopilot = self.history.autopilot = CapacityController(
                ap_cfg,
                registry=self.metrics.registry,
                overrides=None,
                rate_hooks=rate_hooks,
                initial_rates=initial_rates,
                resharder=self.history.reshard_coordinator,
                history=self.history,
                monitor=self.monitor,
                metrics=self.metrics,
            )
        self.worker: Optional[WorkerService] = None
        self._start_worker = start_worker
        self._started = False

    def start(self) -> "Onebox":
        if self._started:
            return self
        self.history.start()
        if self._start_worker:
            self.worker = WorkerService(
                self.frontend, self.persistence,
                num_shards=self.history.controller.num_shards,
                bus=self.bus, domain_handler=self.domain_handler,
                history_service=self.history,
            )
            # archival trigger on every shard's close processor
            client = ArchivalClient(self.frontend, self.domains)
            for shard_id in self.history.controller.owned_shards():
                handle = self.history.controller._handles[shard_id]
                for p in handle.processors:
                    if hasattr(p, "_process_close"):
                        p.archival_client = client
            self.worker.start()
        self._started = True
        return self

    def stop(self) -> None:
        if self.worker is not None:
            self.worker.stop()
        self.history.stop()
        self.matching.shutdown()
        self.bus.close()
        self._started = False

    def __enter__(self) -> "Onebox":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
