"""Test/deploy environment resolution.

Reference: environment/env.go — every integration harness resolves its
backend endpoints (Cassandra/MySQL/Kafka/ES seeds + ports) from env
vars with local defaults, so the same suite runs against a laptop, a
docker-compose network, or CI. This build's equivalents:

  CADENCE_TPU_STORE          "memory" | "sqlite"        (default memory)
  CADENCE_TPU_SQLITE_PATH    sqlite file                (default tmp)
  CADENCE_TPU_NUM_SHARDS     history shard count        (default 4)
  CADENCE_TPU_JAX_PLATFORM   "cpu" | "tpu"              (default cpu —
                             tests always pin the virtual CPU mesh)
  CADENCE_TPU_MESH_DEVICES   virtual device count       (default 8)
  CADENCE_TPU_BIND_IP        service bind address       (default 127.0.0.1)

``setup_env()`` applies the JAX knobs exactly the way tests/conftest.py
does (it is the shared implementation), so standalone harnesses and
the docker entrypoint agree with the test suite.
"""

from __future__ import annotations

import os
import tempfile

LOCALHOST = "127.0.0.1"

STORE = "CADENCE_TPU_STORE"
SQLITE_PATH = "CADENCE_TPU_SQLITE_PATH"
NUM_SHARDS = "CADENCE_TPU_NUM_SHARDS"
JAX_PLATFORM = "CADENCE_TPU_JAX_PLATFORM"
MESH_DEVICES = "CADENCE_TPU_MESH_DEVICES"
BIND_IP = "CADENCE_TPU_BIND_IP"


def store() -> str:
    return os.environ.get(STORE, "memory")


def sqlite_path() -> str:
    path = os.environ.get(SQLITE_PATH, "")
    if path:
        return path
    return os.path.join(tempfile.gettempdir(), "cadence_tpu.db")


def num_shards() -> int:
    return int(os.environ.get(NUM_SHARDS, "4"))


def jax_platform() -> str:
    return os.environ.get(JAX_PLATFORM, "cpu")


def mesh_devices() -> int:
    return int(os.environ.get(MESH_DEVICES, "8"))


def bind_ip() -> str:
    return os.environ.get(BIND_IP, LOCALHOST)


def create_bundle():
    """A persistence bundle per the env (env.go's backend selection)."""
    if store() == "sqlite":
        from cadence_tpu.runtime.persistence.sqlite import (
            create_sqlite_bundle,
        )

        return create_sqlite_bundle(sqlite_path())
    from cadence_tpu.runtime.persistence.memory import create_memory_bundle

    return create_memory_bundle()


def setup_env(environ=os.environ) -> None:
    """Pin JAX to the configured platform/mesh BEFORE jax first loads.

    cpu (the default, and what tests/conftest.py applies): force the
    virtual ``mesh_devices()``-device CPU mesh and neutralize the axon
    TPU tunnel plugin, whose bootstrap can block every process start
    when the tunnel is unhealthy. tpu: leave the platform alone so the
    real chip resolves.
    """
    if jax_platform() != "cpu":
        return
    environ["JAX_PLATFORMS"] = "cpu"
    environ.pop("PALLAS_AXON_POOL_IPS", None)
    flag = f"--xla_force_host_platform_device_count={mesh_devices()}"
    xla_flags = environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        environ["XLA_FLAGS"] = (xla_flags + " " + flag).strip()
