"""Serving demo: boot Onebox with serving enabled, drive an open-loop
burst, prove resident hits and a clean drain.

The zero-to-resident walkthrough (scripts/run_serve_demo.sh wraps it, a
tier-1 smoke test invokes it so the serving plane can't rot):

1. boot an in-process Onebox with the continuous-batching resident
   engine attached (the ``serving:`` config section's wiring) and a
   checkpoint plane for eviction flushes;
2. start a few signal-sink workflows through the real frontend;
3. drive a short open-loop burst: signal arrivals paced by the same
   ``ArrivalProcess`` schedule the SLO harness uses, each followed by
   a ``serving_read`` — the first read per workflow cold-misses and
   seats a lane, every later read answers resident with the Δ suffix
   composed (the persist feed marks the lane behind on every durable
   signal write);
4. shut down — ``HistoryService.stop`` drains the engine, flushing
   every resident lane back through the checkpoint plane.

Exit status 0 requires resident hits ≥ requests − workflows (at most
one cold miss per workflow), zero flush failures on the drain, and an
empty engine after shutdown. One JSON summary line lands on stdout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _signal_sink(ctx, input):
    while True:
        yield ctx.wait_signal("ping")


def run_demo(workflows: int = 3, requests: int = 18, qps: float = 60.0,
             kind: str = "bursty", quiet: bool = False,
             timeout_s: float = 30.0) -> int:
    from cadence_tpu.runtime.api import SignalRequest, StartWorkflowRequest
    from cadence_tpu.serving import ArrivalProcess
    from cadence_tpu.testing.onebox import Onebox
    from cadence_tpu.worker import Worker

    def say(msg):
        if not quiet:
            print(msg, file=sys.stderr)

    box = Onebox(num_shards=2, checkpoints=True, serving=True).start()
    w = Worker(box.frontend, "serve-demo", "serve-demo-tl",
               identity="serve-demo-worker")
    w.register_workflow("signal-sink", _signal_sink)
    try:
        box.domain_handler.register_domain("serve-demo")
        w.start()
        say(f"onebox up; serving engine: {box.serving.lanes} lanes")
        wf_ids = [f"serve-demo-wf-{i}" for i in range(workflows)]
        for wid in wf_ids:
            box.frontend.start_workflow_execution(
                StartWorkflowRequest(
                    domain="serve-demo", workflow_id=wid,
                    workflow_type="signal-sink",
                    task_list="serve-demo-tl",
                    input=b"", request_id=f"req-{wid}",
                    execution_start_to_close_timeout_seconds=300,
                )
            )
        dom_id = box.domains.get_by_name("serve-demo").info.id

        # the open-loop burst: arrivals on an absolute schedule (the
        # same process the SLO harness uses) — falling behind shows up
        # as latency, never as a thinner burst
        schedule = ArrivalProcess(
            qps=qps, kind=kind, seed=11
        ).schedule(requests)
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        lat_ms = []
        for i in range(requests):
            if time.monotonic() > deadline:
                say(f"FAIL: burst exceeded --timeout {timeout_s}s "
                    f"at request {i}/{requests}")
                return 1
            now = time.monotonic() - t0
            if schedule[i] > now:
                time.sleep(schedule[i] - now)
            wid = wf_ids[i % workflows]
            box.frontend.signal_workflow_execution(
                SignalRequest(
                    domain="serve-demo", workflow_id=wid,
                    signal_name="ping", input=b"%d" % i,
                )
            )
            # per-read duration (the resident-read claim); the bench's
            # serve_continuous config owns the open-loop scheduled-
            # arrival SLOs, where compile stalls count as queueing
            t_read = time.monotonic()
            got = box.history.serving_read(dom_id, wid)
            assert got is not None, f"serving read lost {wid}"
            lat_ms.append((time.monotonic() - t_read) * 1e3)
        wall = time.monotonic() - t0
        reg = box.metrics.registry
        hits = reg.counter_value("serving_resident_hits")
        misses = reg.counter_value("serving_cold_misses")
        occupancy = box.serving.occupancy()
    finally:
        w.stop()
        box.stop()  # HistoryService.stop drains the resident engine

    evictions = reg.counter_value("serving_evictions")
    flush_failed = reg.counter_value("serving_flush_failures")
    lat_ms.sort()
    summary = {
        "workflows": workflows,
        "requests": requests,
        "qps_target": qps,
        "qps_sustained": round(requests / wall, 1) if wall > 0 else 0.0,
        "arrival": kind,
        "resident_hits": hits,
        "cold_misses": misses,
        "occupancy_before_drain": occupancy,
        "drain_evictions": evictions,
        "drain_flush_failures": flush_failed,
        "read_p50_ms": round(lat_ms[len(lat_ms) // 2], 3),
        "read_max_ms": round(lat_ms[-1], 3),
    }
    print(json.dumps(summary))

    if hits < requests - workflows:
        say(f"FAIL: expected >= {requests - workflows} resident hits, "
            f"got {hits} ({misses} cold misses)")
        return 1
    if occupancy <= 0:
        say("FAIL: no lanes were resident at burst end")
        return 1
    if flush_failed:
        say(f"FAIL: drain left {flush_failed} unflushed lanes")
        return 1
    if evictions < 1:
        say("FAIL: the shutdown drain never flushed a lane")
        return 1
    if box.serving.occupancy() != 0.0:
        say("FAIL: engine not empty after drain")
        return 1
    say(f"OK: {hits} resident hits / {misses} cold misses at "
        f"{summary['qps_sustained']} qps; clean drain "
        f"({evictions} lanes flushed, 0 failures)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cadence_tpu.testing.serve_demo"
    )
    ap.add_argument("--workflows", type=int, default=3)
    ap.add_argument("--requests", type=int, default=18)
    ap.add_argument("--qps", type=float, default=60.0)
    ap.add_argument("--kind", choices=("poisson", "bursty"),
                    default="bursty")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress progress chatter on stderr")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)
    return run_demo(workflows=args.workflows, requests=args.requests,
                    qps=args.qps, kind=args.kind, quiet=args.quiet,
                    timeout_s=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
