"""Benchmark workload histories — the five BASELINE.md configurations.

Shapes mirror the reference's canary workload definitions
(/root/reference/canary/const.go:64-84): echo, signal-heavy, timer
storm (cron/timeout-class), activity-retry/concurrent deep histories,
and the NDC replication-storm mix. Each generator returns the
transaction-batch list the packer and the oracle both consume, so one
workload feeds the TPU kernel, the C++ sequential baseline, and the
host oracle identically.
"""

from __future__ import annotations

import random
from typing import List

from cadence_tpu.core import history_factory as F
from cadence_tpu.core.events import HistoryEvent

SECOND = 1_000_000_000
T0 = 1_700_000_000 * SECOND

Batches = List[List[HistoryEvent]]


class _Ids:
    def __init__(self) -> None:
        self.eid = 0
        self.t = T0

    def next(self) -> int:
        self.eid += 1
        return self.eid

    def tick(self, seconds: int = 1) -> int:
        self.t += seconds * SECOND
        return self.t


def _start(ids: _Ids, v: int, workflow_type: str) -> List[HistoryEvent]:
    return [F.workflow_execution_started(
        ids.next(), v, ids.t, task_list="tl", workflow_type=workflow_type,
        execution_start_to_close_timeout_seconds=3600,
        task_start_to_close_timeout_seconds=10,
    )]


def _decision_cycle(ids: _Ids, v: int) -> Batches:
    """scheduled → started → (completed is appended by the caller so it
    can ride in the same batch as the commands it emits)."""
    sch = ids.next()
    out = [[F.decision_task_scheduled(sch, v, ids.t)]]
    sta = ids.next()
    out.append([F.decision_task_started(sta, v, ids.tick(),
                                        scheduled_event_id=sch)])
    return out


def _decision_completed(ids: _Ids, v: int) -> HistoryEvent:
    sta = ids.eid
    return F.decision_task_completed(
        ids.next(), v, ids.tick(), scheduled_event_id=sta - 1,
        started_event_id=sta,
    )


def echo_history(v: int = 10) -> Batches:
    """canary/echo: one activity round-trip, ~11 events, closed."""
    ids = _Ids()
    out = [_start(ids, v, "echo")]
    out += _decision_cycle(ids, v)
    dcomp = _decision_completed(ids, v)
    act = ids.next()
    out.append([dcomp, F.activity_task_scheduled(
        act, v, ids.t, activity_id="a1",
        decision_task_completed_event_id=dcomp.event_id,
    )])
    sta = ids.next()
    out.append([F.activity_task_started(sta, v, ids.tick(),
                                        scheduled_event_id=act)])
    out.append([F.activity_task_completed(
        ids.next(), v, ids.tick(), scheduled_event_id=act,
        started_event_id=sta,
    ), F.decision_task_scheduled(ids.next(), v, ids.t)])
    sch = ids.eid
    sta2 = ids.next()
    out.append([F.decision_task_started(sta2, v, ids.tick(),
                                        scheduled_event_id=sch)])
    dcomp2 = F.decision_task_completed(
        ids.next(), v, ids.tick(), scheduled_event_id=sch,
        started_event_id=sta2,
    )
    out.append([dcomp2, F.workflow_execution_completed(
        ids.next(), v, ids.t,
        decision_task_completed_event_id=dcomp2.event_id,
    )])
    return out


def signal_history(rng: random.Random, v: int = 10,
                   min_events: int = 20, max_events: int = 400) -> Batches:
    """canary/signal: signal-dominated, ragged lengths, left open."""
    ids = _Ids()
    target = rng.randint(min_events, max_events)
    out = [_start(ids, v, "signal")]
    out += _decision_cycle(ids, v)
    out.append([_decision_completed(ids, v)])
    n = 0
    while ids.eid < target:
        # burst of signals, then a decision cycle consuming them
        for _ in range(rng.randint(1, 4)):
            n += 1
            out.append([F.workflow_execution_signaled(
                ids.next(), v, ids.tick(), signal_name=f"sig-{n}",
            )])
        out += _decision_cycle(ids, v)
        out.append([_decision_completed(ids, v)])
    return out


def timer_storm_history(rng: random.Random, v: int = 10,
                        depth: int = 400, fanout: int = 8) -> Batches:
    """canary/cron + canary/timeout: timer-fire-dominated stream — each
    decision starts a fan of timers which then fire back-to-back."""
    ids = _Ids()
    out = [_start(ids, v, "timer-storm")]
    timer_n = 0
    while ids.eid < depth:
        out += _decision_cycle(ids, v)
        dcomp = _decision_completed(ids, v)
        batch = [dcomp]
        started: List[tuple] = []
        for _ in range(fanout):
            timer_n += 1
            tid = f"t{timer_n}"
            sid = ids.next()
            batch.append(F.timer_started(
                sid, v, ids.t, timer_id=tid,
                start_to_fire_timeout_seconds=rng.randint(1, 30),
                decision_task_completed_event_id=dcomp.event_id,
            ))
            started.append((tid, sid))
        out.append(batch)
        for tid, sid in started:
            out.append([F.timer_fired(ids.next(), v, ids.tick(),
                                      timer_id=tid, started_event_id=sid)])
    return out


def retry_deep_history(rng: random.Random, v: int = 10,
                       depth: int = 1000) -> Batches:
    """canary/retry + canary/concurrentExec: deep history of activity
    schedule/start/fail retry loops with interleaved decisions."""
    ids = _Ids()
    out = [_start(ids, v, "retry-deep")]
    act_n = 0
    while ids.eid < depth:
        out += _decision_cycle(ids, v)
        dcomp = _decision_completed(ids, v)
        act_n += 1
        act = ids.next()
        out.append([dcomp, F.activity_task_scheduled(
            act, v, ids.t, activity_id=f"a{act_n}",
            decision_task_completed_event_id=dcomp.event_id,
            schedule_to_close_timeout_seconds=300,
        )])
        attempts = rng.randint(1, 3)
        for attempt in range(attempts):
            sta = ids.next()
            out.append([F.activity_task_started(
                sta, v, ids.tick(), scheduled_event_id=act,
                attempt=attempt,
            )])
            last = attempt == attempts - 1
            if last and rng.random() < 0.7:
                out.append([F.activity_task_completed(
                    ids.next(), v, ids.tick(), scheduled_event_id=act,
                    started_event_id=sta,
                )])
            else:
                out.append([F.activity_task_failed(
                    ids.next(), v, ids.tick(), scheduled_event_id=act,
                    started_event_id=sta, reason="retry",
                )])
                if not last:
                    # server reschedules the retry attempt in place:
                    # same activity slot, fresh schedule event
                    act = ids.next()
                    out.append([F.activity_task_scheduled(
                        act, v, ids.t, activity_id=f"a{act_n}",
                        schedule_to_close_timeout_seconds=300,
                    )])
    return out


def ndc_storm_history(fuzzer, depth: int = 1000) -> Batches:
    """NDC replication storm: the fuzzer's mixed-event histories with
    failover-version bumps, left open (rebuild-shaped)."""
    return fuzzer.generate(target_events=depth, close_prob=0.0)
