"""Test fixtures: event-graph fuzzer, fake membership, scripted pollers."""
