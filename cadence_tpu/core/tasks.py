"""Transfer / timer / replication task records.

Model of the reference's persistence.Task hierarchy
(/root/reference/common/persistence/dataInterfaces.go:409+ — DecisionTask,
ActivityTask, CloseExecutionTask, CancelExecutionTask, SignalExecutionTask,
StartChildExecutionTask, RecordWorkflowStartedTask, Upsert...Task and the
timer family DecisionTimeoutTask/ActivityTimeoutTask/UserTimerTask/
WorkflowTimeoutTask/DeleteHistoryEventTask/ActivityRetryTimerTask/
WorkflowBackoffTimerTask, HistoryReplicationTask).

These are host-side queue work items; the TPU replay kernel emits them as
compact integer codes that the host hydrates into these records
(cadence_tpu/ops/unpack.py).
"""

from __future__ import annotations

import dataclasses

from .enums import TimerTaskType, TransferTaskType


@dataclasses.dataclass
class TransferTask:
    task_type: TransferTaskType
    domain_id: str = ""
    workflow_id: str = ""
    run_id: str = ""
    task_id: int = 0
    version: int = 0
    # decision / activity dispatch
    task_list: str = ""
    schedule_id: int = 0
    # cross-workflow targets (cancel/signal/child-start)
    target_domain_id: str = ""
    target_workflow_id: str = ""
    target_run_id: str = ""
    target_child_workflow_only: bool = False
    initiated_id: int = 0
    record_visibility: bool = False
    visibility_timestamp: int = 0  # ns

    def sort_key(self):
        return (self.task_id,)


@dataclasses.dataclass
class TimerTask:
    task_type: TimerTaskType
    visibility_timestamp: int  # ns — when the timer fires
    domain_id: str = ""
    workflow_id: str = ""
    run_id: str = ""
    task_id: int = 0
    version: int = 0
    timeout_type: int = 0  # TimeoutType or WorkflowBackoffType
    event_id: int = 0
    schedule_attempt: int = 0

    def sort_key(self):
        return (self.visibility_timestamp, self.task_id)


@dataclasses.dataclass
class ReplicationTask:
    """History replication task (reference: ReplicationTaskInfo)."""

    domain_id: str = ""
    workflow_id: str = ""
    run_id: str = ""
    task_id: int = 0
    first_event_id: int = 0
    next_event_id: int = 0
    version: int = 0
    scheduled_id: int = 0
    branch_token: bytes = b""
    new_run_branch_token: bytes = b""
    reset_workflow: bool = False


def decision_transfer_task(domain_id: str, task_list: str, schedule_id: int) -> TransferTask:
    # reference: stateBuilder.go scheduleDecisionTransferTask
    return TransferTask(
        task_type=TransferTaskType.DecisionTask,
        domain_id=domain_id,
        task_list=task_list,
        schedule_id=schedule_id,
    )


def activity_transfer_task(domain_id: str, task_list: str, schedule_id: int) -> TransferTask:
    return TransferTask(
        task_type=TransferTaskType.ActivityTask,
        domain_id=domain_id,
        task_list=task_list,
        schedule_id=schedule_id,
    )


def close_execution_transfer_task() -> TransferTask:
    return TransferTask(task_type=TransferTaskType.CloseExecution)


def record_workflow_started_task() -> TransferTask:
    return TransferTask(task_type=TransferTaskType.RecordWorkflowStarted)


def upsert_search_attributes_task() -> TransferTask:
    return TransferTask(task_type=TransferTaskType.UpsertWorkflowSearchAttributes)


def start_child_transfer_task(
    target_domain_id: str, target_workflow_id: str, initiated_id: int
) -> TransferTask:
    return TransferTask(
        task_type=TransferTaskType.StartChildExecution,
        target_domain_id=target_domain_id,
        target_workflow_id=target_workflow_id,
        initiated_id=initiated_id,
    )


def cancel_external_transfer_task(
    target_domain_id: str,
    target_workflow_id: str,
    target_run_id: str,
    child_workflow_only: bool,
    initiated_id: int,
) -> TransferTask:
    return TransferTask(
        task_type=TransferTaskType.CancelExecution,
        target_domain_id=target_domain_id,
        target_workflow_id=target_workflow_id,
        target_run_id=target_run_id,
        target_child_workflow_only=child_workflow_only,
        initiated_id=initiated_id,
    )


def signal_external_transfer_task(
    target_domain_id: str,
    target_workflow_id: str,
    target_run_id: str,
    child_workflow_only: bool,
    initiated_id: int,
) -> TransferTask:
    return TransferTask(
        task_type=TransferTaskType.SignalExecution,
        target_domain_id=target_domain_id,
        target_workflow_id=target_workflow_id,
        target_run_id=target_run_id,
        target_child_workflow_only=child_workflow_only,
        initiated_id=initiated_id,
    )
