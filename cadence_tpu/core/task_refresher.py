"""Task refresher: regenerate all queue tasks from a state snapshot.

Host twin of the reference's ``mutableStateTaskRefresher.refreshTasks``
(/root/reference/service/history/mutableStateTaskRefresher.go): after a
rebuild/reset, per-replay task bookkeeping is discarded and the complete
set of outstanding transfer/timer tasks is a pure function of final state.
The device version (cadence_tpu/ops/refresh.py) computes the same sets as
compact arrays; tests assert parity.
"""

from __future__ import annotations

from typing import List, Tuple

from .enums import TimeoutType, TimerTaskType, TransferTaskType
from .ids import EMPTY_EVENT_ID
from .mutable_state import MutableState, SECOND
from . import tasks as T
from .timer_sequence import TimerSequence


def refresh_tasks(ms: MutableState) -> Tuple[List[T.TransferTask], List[T.TimerTask]]:
    """All outstanding tasks implied by ``ms``.

    Ordering is deterministic: transfer tasks by (kind, id); timer tasks by
    (visibility, id) — the device refresher emits the same order.
    """
    transfer: List[T.TransferTask] = []
    timer: List[T.TimerTask] = []
    ei = ms.execution_info

    if not ms.is_workflow_execution_running():
        transfer.append(T.close_execution_transfer_task())
        return transfer, timer

    # workflow timeout (refreshTasksForWorkflowStart); a pending
    # first-decision backoff extends the window exactly as the
    # StateBuilder does at start
    backoff_extra = 0
    if ei.first_decision_backoff_deadline:
        backoff_extra = max(
            0, ei.first_decision_backoff_deadline - ei.start_timestamp
        )
    timer.append(
        T.TimerTask(
            task_type=TimerTaskType.WorkflowTimeout,
            visibility_timestamp=ei.start_timestamp
            + ei.workflow_timeout * SECOND + backoff_extra,
        )
    )
    # cron/retry runs waiting on their first decision re-arm the
    # backoff timer (refreshTasksForWorkflowStart delayed-decision
    # branch); without it a rebuilt/staged run never schedules its
    # first decision after failover
    if (
        ei.first_decision_backoff_deadline
        and not ms.has_pending_decision()
        and ei.last_processed_event < 1
    ):
        timer.append(
            T.TimerTask(
                task_type=TimerTaskType.WorkflowBackoffTimer,
                visibility_timestamp=ei.first_decision_backoff_deadline,
            )
        )

    # decision (refreshTasksForDecision)
    if ms.has_pending_decision():
        transfer.append(
            T.decision_transfer_task(ei.domain_id, ei.task_list, ei.decision_schedule_id)
        )
        if ms.has_inflight_decision():
            timer.append(
                T.TimerTask(
                    task_type=TimerTaskType.DecisionTimeout,
                    visibility_timestamp=ei.decision_started_timestamp
                    + ei.decision_timeout * SECOND,
                    timeout_type=int(TimeoutType.StartToClose),
                    event_id=ei.decision_schedule_id,
                    schedule_attempt=ei.decision_attempt,
                )
            )

    # activities (refreshTasksForActivity): transfer for unstarted; timer
    # statuses reset then earliest timeout re-armed
    for sid in sorted(ms.pending_activities):
        ai = ms.pending_activities[sid]
        ai.timer_task_status = 0
        if ai.started_id == EMPTY_EVENT_ID:
            transfer.append(
                T.activity_transfer_task(ei.domain_id, ai.task_list, sid)
            )
    # user timers (refreshTasksForTimer): statuses reset, earliest re-armed
    for ti in ms.pending_timers.values():
        ti.task_status = 0
    seq = TimerSequence(ms)
    at = seq.activity_timer_task_if_needed()
    if at is not None:
        timer.append(at)
    ut = seq.user_timer_task_if_needed()
    if ut is not None:
        timer.append(ut)

    # children / external cancels / signals not yet acknowledged
    for cid in sorted(ms.pending_children):
        ci = ms.pending_children[cid]
        if ci.started_id == EMPTY_EVENT_ID:
            transfer.append(
                T.start_child_transfer_task(ci.domain_name, ci.started_workflow_id, cid)
            )
    for rid in sorted(ms.pending_request_cancels):
        rc = ms.pending_request_cancels[rid]
        transfer.append(
            T.cancel_external_transfer_task(
                rc.target_domain_id or ei.domain_id,
                rc.target_workflow_id,
                rc.target_run_id,
                rc.target_child_workflow_only,
                rid,
            )
        )
    for sid in sorted(ms.pending_signals):
        sg = ms.pending_signals[sid]
        transfer.append(
            T.signal_external_transfer_task(
                sg.target_domain_id or ei.domain_id,
                sg.target_workflow_id,
                sg.target_run_id,
                sg.target_child_workflow_only,
                sid,
            )
        )
    return transfer, timer
