"""Active-side event creation: one RPC = one ActiveTransaction.

The reference splits the active path across historyBuilder (44 Add*Event
constructors), mutableStateBuilder (92 Add*/Replicate* methods) and
mutableStateTaskGenerator (/root/reference/service/history/
historyBuilder.go, mutableStateBuilder.go, mutableStateTaskGenerator.go).
Here the active path is "create events, then replay them through the
SAME StateBuilder the passive/rebuild path uses" — state mutation and
task generation are never implemented twice, so active and replay
semantics cannot diverge (the property the reference maintains by
hand-mirroring stateBuilder and taskGenerator).

Buffered events (reference mutableStateBuilder.go:95-97): while a
decision task is in flight, externally-caused events (signals, activity
results, timer fires, child/external resolutions) are held in
``ms.buffered_events`` with no event IDs and flushed — IDs assigned —
into the batch right after the decision-close event, so history reads
DecisionTaskStarted … DecisionTaskCompleted, Signal, … exactly as the
reference orders it.

Transient decisions (reference mutableStateDecisionTaskManager.go):
after a decision fails/times out, subsequent attempts are tracked
in-memory only; their Scheduled/Started events materialize at the front
of the completion batch. Activity Started events are likewise lazy
(reference RecordActivityTaskStarted writes no event): started info
lives in ActivityInfo until the activity closes, when the Started event
materializes immediately before the close event.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import history_factory as F
from . import tasks as T
from .enums import (
    CloseStatus,
    ContinueAsNewInitiator,
    EventType,
    ParentClosePolicy,
    TimeoutType,
)
from .events import HistoryEvent, RetryPolicy
from .ids import (
    BUFFERED_EVENT_ID,
    EMPTY_EVENT_ID,
    EMPTY_UUID,
    TRANSIENT_EVENT_ID,
)
from .mutable_state import ActivityInfo, DecisionInfo, MutableState, SECOND
from .state_builder import StateBuilder


class WorkflowStateError(Exception):
    """The operation is illegal in the workflow's current state
    (reference: BadRequestError / mutable-state-mutability failures)."""


@dataclasses.dataclass
class TransactionResult:
    """Everything a closed transaction hands to persistence."""

    events: List[HistoryEvent]
    transfer_tasks: List[T.TransferTask]
    timer_tasks: List[T.TimerTask]
    new_run_events: List[HistoryEvent] = dataclasses.field(default_factory=list)
    new_run_ms: Optional[MutableState] = None
    new_run_transfer_tasks: List[T.TransferTask] = dataclasses.field(default_factory=list)
    new_run_timer_tasks: List[T.TimerTask] = dataclasses.field(default_factory=list)


# event types held back while a decision is in flight
# (reference: mutableStateBuilder.shouldBufferEvent)
_BUFFERABLE = frozenset(
    {
        EventType.ActivityTaskStarted,
        EventType.ActivityTaskCompleted,
        EventType.ActivityTaskFailed,
        EventType.ActivityTaskTimedOut,
        EventType.ActivityTaskCanceled,
        EventType.TimerFired,
        EventType.WorkflowExecutionSignaled,
        EventType.StartChildWorkflowExecutionFailed,
        EventType.ChildWorkflowExecutionStarted,
        EventType.ChildWorkflowExecutionCompleted,
        EventType.ChildWorkflowExecutionFailed,
        EventType.ChildWorkflowExecutionCanceled,
        EventType.ChildWorkflowExecutionTimedOut,
        EventType.ChildWorkflowExecutionTerminated,
        EventType.ExternalWorkflowExecutionCancelRequested,
        EventType.ExternalWorkflowExecutionSignaled,
        EventType.RequestCancelExternalWorkflowExecutionFailed,
        EventType.SignalExternalWorkflowExecutionFailed,
    }
)


class ActiveTransaction:
    def __init__(
        self,
        ms: MutableState,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        version: int,
        request_id: str = "",
        domain_resolver: Callable[[str], str] = lambda name: name,
        id_generator: Callable[[], str] = None,
        retention_days: int = 1,
    ) -> None:
        import uuid as _uuid

        self.ms = ms
        self.domain_id = domain_id
        self.workflow_id = workflow_id
        self.run_id = run_id
        self.version = version
        self.request_id = request_id
        self.id_generator = id_generator or (lambda: str(_uuid.uuid4()))
        self.domain_resolver = domain_resolver
        self.retention_days = retention_days
        self.batch: List[HistoryEvent] = []
        # batch-local dedup sets (state only updates at close-replay)
        self._batch_activity_ids: set = set()
        self._batch_timer_ids: set = set()
        self._batch_canceled_timers: set = set()
        self._closed_in_batch = False
        self._decision_closed_in_batch = False
        self._extra_transfer: List[T.TransferTask] = []
        self._extra_timer: List[T.TimerTask] = []
        self._new_run_events: List[HistoryEvent] = []

    # -- plumbing -----------------------------------------------------

    def schedule_transfer_task(self, task: T.TransferTask) -> None:
        """Stage an out-of-band transfer task (queue processors)."""
        self._extra_transfer.append(task)

    def schedule_timer_task(self, task: T.TimerTask) -> None:
        """Stage an out-of-band timer task (timer re-arm, retry timers)."""
        self._extra_timer.append(task)

    def _next_id(self) -> int:
        return self.ms.next_event_id + len(self.batch)

    def _require_running(self) -> None:
        if self._closed_in_batch or not self.ms.is_workflow_execution_running():
            raise WorkflowStateError(
                f"workflow {self.workflow_id} is not running"
            )

    def _add(self, make: Callable[[int], HistoryEvent]) -> HistoryEvent:
        """Create an event; route to batch or buffer."""
        probe = make(BUFFERED_EVENT_ID)
        if (
            probe.event_type in _BUFFERABLE
            and self.ms.has_inflight_decision()
            # a decision closed earlier in this batch clears the
            # in-flight state at close-replay; nothing to buffer behind
            and not self._decision_closed_in_batch
        ):
            self.ms.buffered_events.append(probe)
            return probe
        event = make(self._next_id())
        self.batch.append(event)
        return event

    def _flush_buffered(self) -> None:
        """Assign IDs to buffered events and append them to the batch
        (called right after a decision-close event enters the batch).

        Cross-references are patched the way the reference's
        assignEventIDToBufferedEvents does: a close event buffered
        before its lazily-materialized started event carries a sentinel
        ``started_event_id`` — once the started event gets its real id,
        every sibling referencing the same scheduled/initiated event is
        rewritten to it."""
        started_by_sched: dict = {}   # scheduled_event_id → started id
        started_by_init: dict = {}    # initiated_event_id → started id
        for event in self.ms.buffered_events:
            event.event_id = self._next_id()
            self.batch.append(event)
            a = event.attributes
            if event.event_type == EventType.ActivityTaskStarted:
                started_by_sched[a.get("scheduled_event_id")] = (
                    event.event_id
                )
            elif event.event_type == EventType.ChildWorkflowExecutionStarted:
                started_by_init[a.get("initiated_event_id")] = (
                    event.event_id
                )
        for event in self.batch:
            a = event.attributes
            sid = a.get("started_event_id")
            if sid is None or sid >= 0:
                continue
            real = started_by_sched.get(a.get("scheduled_event_id"))
            if real is None:
                real = started_by_init.get(a.get("initiated_event_id"))
            if real is not None:
                a["started_event_id"] = real
        self.ms.buffered_events = []

    def _buffered(self, event_type: EventType, **attr_match: Any) -> bool:
        for e in self.ms.buffered_events:
            if e.event_type == event_type and all(
                e.attributes.get(k) == v for k, v in attr_match.items()
            ):
                return True
        return False

    def has_buffered_events(self) -> bool:
        return bool(self.ms.buffered_events)

    # -- workflow start ----------------------------------------------

    def add_workflow_execution_started(
        self, now: int, **attrs: Any
    ) -> HistoryEvent:
        if self.ms.execution_info.start_timestamp or self.batch:
            raise WorkflowStateError("workflow already started")
        event = F.workflow_execution_started(
            self._next_id(), self.version, now, **attrs
        )
        self.batch.append(event)
        return event

    # -- decision lifecycle ------------------------------------------

    def add_decision_task_scheduled(
        self, now: int, task_list: str = "", timeout_seconds: int = 0
    ) -> DecisionInfo:
        """Schedule a decision; transient (in-memory) when attempt > 0."""
        self._require_running()
        # a decision closed earlier in this batch only clears from ms at
        # close-replay; treat it as already cleared (attempt resets too)
        if not self._decision_closed_in_batch and self.ms.has_pending_decision():
            raise WorkflowStateError("decision already scheduled")
        ei = self.ms.execution_info
        # during the start transaction ms is still empty (replay is
        # deferred to close) — read defaults off the in-batch started
        # event (reference: scheduling reads mutableState populated
        # eagerly; our deferred replay needs the batch fallback)
        started_attrs: Dict[str, Any] = {}
        for ev in self.batch:
            if ev.event_type == EventType.WorkflowExecutionStarted:
                started_attrs = ev.attributes
                break
        task_list = (
            ei.sticky_task_list or task_list or ei.task_list
            or started_attrs.get("task_list", "")
        )
        timeout = (
            timeout_seconds
            or ei.decision_timeout_value
            or started_attrs.get("task_start_to_close_timeout_seconds", 0)
        )
        if ei.decision_attempt > 0 and not self._decision_closed_in_batch:
            # transient: no event until completion materializes it
            decision = self.ms.replicate_transient_decision_task_scheduled(now)
            self._extra_transfer.append(
                T.decision_transfer_task(
                    self.domain_id, task_list, decision.schedule_id
                )
            )
            return decision
        event = self._add(
            lambda eid: F.decision_task_scheduled(
                eid, self.version, now,
                task_list=task_list,
                start_to_close_timeout_seconds=timeout,
                attempt=0,
            )
        )
        return DecisionInfo(
            version=self.version,
            schedule_id=event.event_id,
            started_id=EMPTY_EVENT_ID,
            task_list=task_list,
            decision_timeout=timeout,
            scheduled_timestamp=now,
        )

    def add_decision_task_started(
        self, schedule_id: int, request_id: str, identity: str, now: int
    ) -> DecisionInfo:
        self._require_running()
        ms = self.ms
        ei = ms.execution_info
        if (
            ei.decision_schedule_id != schedule_id
            or ei.decision_started_id != EMPTY_EVENT_ID
        ):
            raise WorkflowStateError(
                f"decision {schedule_id} not scheduled or already started"
            )
        if ei.decision_attempt > 0:
            # transient: in-memory started; events materialize at close.
            # Pass the decision explicitly — the decision=None path is the
            # replication-correction path that resets the attempt.
            return ms.replicate_decision_task_started_event(
                ms.get_decision_info(), self.version, schedule_id,
                schedule_id + 1, request_id, now,
            )
        event = self._add(
            lambda eid: F.decision_task_started(
                eid, self.version, now,
                scheduled_event_id=schedule_id,
                identity=identity, request_id=request_id,
            )
        )
        return DecisionInfo(
            version=self.version,
            schedule_id=schedule_id,
            started_id=event.event_id,
            request_id=request_id,
            started_timestamp=now,
        )

    def _materialize_transient_decision(self, now: int) -> None:
        """Write the scheduled+started pair for an attempt>0 decision at
        the front of the close batch (IDs match the in-memory shadow IDs
        because nothing else was persisted while it was pending)."""
        ei = self.ms.execution_info
        scheduled = F.decision_task_scheduled(
            self._next_id(), self.version, ei.decision_scheduled_timestamp or now,
            task_list=self.ms.execution_info.task_list,
            start_to_close_timeout_seconds=ei.decision_timeout,
            attempt=ei.decision_attempt,
        )
        if scheduled.event_id != ei.decision_schedule_id:
            raise WorkflowStateError(
                f"transient decision id drift: {scheduled.event_id} != "
                f"{ei.decision_schedule_id}"
            )
        self.batch.append(scheduled)
        started = F.decision_task_started(
            self._next_id(), self.version, ei.decision_started_timestamp or now,
            scheduled_event_id=ei.decision_schedule_id,
            request_id=ei.decision_request_id,
        )
        self.batch.append(started)

    def _check_inflight_decision(self, schedule_id: int, started_id: int) -> None:
        ei = self.ms.execution_info
        if (
            ei.decision_schedule_id != schedule_id
            or ei.decision_started_id != started_id
        ):
            raise WorkflowStateError(
                f"decision ({schedule_id},{started_id}) not in flight "
                f"(have {ei.decision_schedule_id},{ei.decision_started_id})"
            )

    def add_decision_task_completed(
        self, schedule_id: int, started_id: int, now: int,
        identity: str = "", binary_checksum: str = "",
    ) -> HistoryEvent:
        self._require_running()
        self._check_inflight_decision(schedule_id, started_id)
        if self.ms.execution_info.decision_attempt > 0:
            self._materialize_transient_decision(now)
        event = F.decision_task_completed(
            self._next_id(), self.version, now,
            scheduled_event_id=schedule_id, started_event_id=started_id,
            identity=identity, binary_checksum=binary_checksum,
        )
        self.batch.append(event)
        self._decision_closed_in_batch = True
        self._flush_buffered()
        return event

    def add_decision_task_failed(
        self, schedule_id: int, started_id: int, now: int,
        cause: int = 0, identity: str = "", details: bytes = b"",
    ) -> HistoryEvent:
        self._require_running()
        self._check_inflight_decision(schedule_id, started_id)
        if self.ms.execution_info.decision_attempt > 0:
            self._materialize_transient_decision(now)
        event = F.decision_task_failed(
            self._next_id(), self.version, now,
            scheduled_event_id=schedule_id, started_event_id=started_id,
            cause=cause, identity=identity, details=details,
        )
        self.batch.append(event)
        self._decision_closed_in_batch = True
        self._flush_buffered()
        return event

    def add_decision_task_timed_out(
        self, schedule_id: int, started_id: int, now: int,
        timeout_type: TimeoutType = TimeoutType.StartToClose,
    ) -> HistoryEvent:
        self._require_running()
        if timeout_type == TimeoutType.StartToClose:
            self._check_inflight_decision(schedule_id, started_id)
            if self.ms.execution_info.decision_attempt > 0:
                self._materialize_transient_decision(now)
        event = F.decision_task_timed_out(
            self._next_id(), self.version, now,
            scheduled_event_id=schedule_id, started_event_id=started_id,
            timeout_type=timeout_type,
        )
        self.batch.append(event)
        self._decision_closed_in_batch = True
        self._flush_buffered()
        return event

    # -- activities ---------------------------------------------------

    def add_activity_task_scheduled(
        self, decision_completed_id: int, now: int, *, activity_id: str,
        **attrs: Any,
    ) -> HistoryEvent:
        self._require_running()
        if (
            activity_id in self.ms.activity_by_id
            or activity_id in self._batch_activity_ids
        ):
            raise WorkflowStateError(f"duplicate activity id {activity_id}")
        self._batch_activity_ids.add(activity_id)
        event = F.activity_task_scheduled(
            self._next_id(), self.version, now,
            activity_id=activity_id,
            decision_task_completed_event_id=decision_completed_id,
            **attrs,
        )
        self.batch.append(event)
        return event

    def record_activity_task_started(
        self, ai: ActivityInfo, request_id: str, identity: str, now: int
    ) -> None:
        """State-only (no event until the activity closes — reference
        RecordActivityTaskStarted, historyEngine.go)."""
        self._require_running()
        if ai.started_id != EMPTY_EVENT_ID:
            raise WorkflowStateError(
                f"activity {ai.schedule_id} already started"
            )
        ai.started_id = TRANSIENT_EVENT_ID
        ai.request_id = request_id
        ai.started_identity = identity
        ai.started_time = now
        ai.version = self.version

    def _materialize_activity_started(self, ai: ActivityInfo) -> int:
        """Create the lazy Started event; returns its (possibly buffered)
        id for the close event's started_event_id linkage."""
        event = self._add(
            lambda eid: F.activity_task_started(
                eid, ai.version, ai.started_time,
                scheduled_event_id=ai.schedule_id,
                identity=ai.started_identity,
                request_id=ai.request_id,
                attempt=ai.attempt,
            )
        )
        return event.event_id

    def _activity_for_close(self, schedule_id: int) -> ActivityInfo:
        ai = self.ms.get_activity_info(schedule_id)
        if ai is None or self._buffered_activity_close(schedule_id):
            raise WorkflowStateError(f"activity {schedule_id} not pending")
        return ai

    def _buffered_activity_close(self, schedule_id: int) -> bool:
        return any(
            self._buffered(et, scheduled_event_id=schedule_id)
            for et in (
                EventType.ActivityTaskCompleted,
                EventType.ActivityTaskFailed,
                EventType.ActivityTaskTimedOut,
                EventType.ActivityTaskCanceled,
            )
        )

    def add_activity_task_completed(
        self, schedule_id: int, now: int, result: bytes = b"", identity: str = ""
    ) -> HistoryEvent:
        self._require_running()
        ai = self._activity_for_close(schedule_id)
        if ai.started_id == EMPTY_EVENT_ID:
            raise WorkflowStateError(f"activity {schedule_id} not started")
        started_id = (
            self._materialize_activity_started(ai)
            if ai.started_id == TRANSIENT_EVENT_ID
            else ai.started_id
        )
        return self._add(
            lambda eid: F.activity_task_completed(
                eid, self.version, now,
                scheduled_event_id=schedule_id, started_event_id=started_id,
                result=result, identity=identity,
            )
        )

    def add_activity_task_failed(
        self, schedule_id: int, now: int, reason: str = "",
        details: bytes = b"", identity: str = "",
    ) -> HistoryEvent:
        self._require_running()
        ai = self._activity_for_close(schedule_id)
        if ai.started_id == EMPTY_EVENT_ID:
            raise WorkflowStateError(f"activity {schedule_id} not started")
        started_id = (
            self._materialize_activity_started(ai)
            if ai.started_id == TRANSIENT_EVENT_ID
            else ai.started_id
        )
        return self._add(
            lambda eid: F.activity_task_failed(
                eid, self.version, now,
                scheduled_event_id=schedule_id, started_event_id=started_id,
                reason=reason, details=details, identity=identity,
            )
        )

    def add_activity_task_timed_out(
        self, schedule_id: int, now: int, timeout_type: TimeoutType,
        details: bytes = b"",
    ) -> HistoryEvent:
        self._require_running()
        ai = self._activity_for_close(schedule_id)
        started_id = ai.started_id
        if started_id == TRANSIENT_EVENT_ID:
            started_id = self._materialize_activity_started(ai)
        return self._add(
            lambda eid: F.activity_task_timed_out(
                eid, self.version, now,
                scheduled_event_id=schedule_id,
                started_event_id=(
                    started_id if started_id != EMPTY_EVENT_ID else EMPTY_EVENT_ID
                ),
                timeout_type=timeout_type, details=details,
            )
        )

    def add_activity_task_cancel_requested(
        self, decision_completed_id: int, activity_id: str, now: int
    ) -> Tuple[Optional[HistoryEvent], Optional[ActivityInfo]]:
        """Returns (event, activity) or (failed_event, None) when the
        activity id is unknown (reference: AddActivityTaskCancelRequestedEvent
        + RequestCancelActivityTaskFailed)."""
        self._require_running()
        schedule_id = self.ms.activity_by_id.get(activity_id)
        ai = (
            self.ms.get_activity_info(schedule_id)
            if schedule_id is not None
            else None
        )
        if ai is None or self._buffered_activity_close(schedule_id):
            event = F.request_cancel_activity_task_failed(
                self._next_id(), self.version, now,
                activity_id=activity_id,
                decision_task_completed_event_id=decision_completed_id,
            )
            self.batch.append(event)
            return event, None
        event = F.activity_task_cancel_requested(
            self._next_id(), self.version, now,
            activity_id=activity_id,
            decision_task_completed_event_id=decision_completed_id,
        )
        self.batch.append(event)
        return event, ai

    def add_activity_task_canceled(
        self, schedule_id: int, cancel_request_id: int, now: int,
        details: bytes = b"", identity: str = "",
    ) -> HistoryEvent:
        self._require_running()
        ai = self._activity_for_close(schedule_id)
        started_id = ai.started_id
        if started_id == TRANSIENT_EVENT_ID:
            started_id = self._materialize_activity_started(ai)
        return self._add(
            lambda eid: F.activity_task_canceled(
                eid, self.version, now,
                scheduled_event_id=schedule_id, started_event_id=started_id,
                latest_cancel_requested_event_id=cancel_request_id,
                details=details, identity=identity,
            )
        )

    # -- timers -------------------------------------------------------

    def add_timer_started(
        self, decision_completed_id: int, timer_id: str,
        fire_timeout_seconds: int, now: int,
    ) -> HistoryEvent:
        self._require_running()
        if (
            timer_id in self.ms.pending_timers
            or timer_id in self._batch_timer_ids
        ):
            raise WorkflowStateError(f"duplicate timer id {timer_id}")
        self._batch_timer_ids.add(timer_id)
        event = F.timer_started(
            self._next_id(), self.version, now,
            timer_id=timer_id,
            start_to_fire_timeout_seconds=fire_timeout_seconds,
            decision_task_completed_event_id=decision_completed_id,
        )
        self.batch.append(event)
        return event

    def add_timer_fired(self, timer_id: str, now: int) -> HistoryEvent:
        self._require_running()
        ti = self.ms.get_user_timer(timer_id)
        if ti is None or self._buffered(EventType.TimerFired, timer_id=timer_id):
            raise WorkflowStateError(f"timer {timer_id} not pending")
        return self._add(
            lambda eid: F.timer_fired(
                eid, self.version, now,
                timer_id=timer_id, started_event_id=ti.started_id,
            )
        )

    def add_timer_canceled(
        self, decision_completed_id: int, timer_id: str, now: int,
        identity: str = "",
    ) -> HistoryEvent:
        """Cancel a pending timer; emits CancelTimerFailed if unknown."""
        self._require_running()
        ti = self.ms.get_user_timer(timer_id)
        known = (
            ti is not None
            and timer_id not in self._batch_canceled_timers
            and not self._buffered(EventType.TimerFired, timer_id=timer_id)
        )
        if not known:
            event = F.cancel_timer_failed(
                self._next_id(), self.version, now,
                timer_id=timer_id, cause="TIMER_ID_UNKNOWN",
                decision_task_completed_event_id=decision_completed_id,
            )
            self.batch.append(event)
            return event
        self._batch_canceled_timers.add(timer_id)
        event = F.timer_canceled(
            self._next_id(), self.version, now,
            timer_id=timer_id, started_event_id=ti.started_id,
            decision_task_completed_event_id=decision_completed_id,
            identity=identity,
        )
        self.batch.append(event)
        return event

    # -- signals / cancel --------------------------------------------

    def add_workflow_execution_signaled(
        self, name: str, input: bytes, identity: str, now: int
    ) -> HistoryEvent:
        self._require_running()
        return self._add(
            lambda eid: F.workflow_execution_signaled(
                eid, self.version, now,
                signal_name=name, input=input, identity=identity,
            )
        )

    def add_workflow_execution_cancel_requested(
        self, cause: str, identity: str, now: int,
        external_workflow_id: str = "", external_run_id: str = "",
        request_id: str = "",
    ) -> HistoryEvent:
        self._require_running()
        if self.ms.execution_info.cancel_requested:
            raise WorkflowStateError("cancellation already requested")
        event = F.workflow_execution_cancel_requested(
            self._next_id(), self.version, now,
            cause=cause, identity=identity,
            cancel_request_id=request_id,
            external_workflow_id=external_workflow_id,
            external_run_id=external_run_id,
        )
        self.batch.append(event)
        return event

    # -- markers / search attributes ---------------------------------

    def add_marker_recorded(
        self, decision_completed_id: int, marker_name: str, now: int,
        details: bytes = b"",
    ) -> HistoryEvent:
        self._require_running()
        event = F.marker_recorded(
            self._next_id(), self.version, now,
            marker_name=marker_name, details=details,
            decision_task_completed_event_id=decision_completed_id,
        )
        self.batch.append(event)
        return event

    def add_upsert_search_attributes(
        self, decision_completed_id: int, search_attributes: Dict[str, bytes],
        now: int,
    ) -> HistoryEvent:
        self._require_running()
        event = F.upsert_workflow_search_attributes(
            self._next_id(), self.version, now,
            search_attributes=search_attributes,
            decision_task_completed_event_id=decision_completed_id,
        )
        self.batch.append(event)
        return event

    # -- external workflows ------------------------------------------

    def add_request_cancel_external_initiated(
        self, decision_completed_id: int, domain: str, workflow_id: str,
        run_id: str, child_workflow_only: bool, now: int,
    ) -> HistoryEvent:
        self._require_running()
        event = F.request_cancel_external_initiated(
            self._next_id(), self.version, now,
            domain=domain, workflow_id=workflow_id, run_id=run_id,
            child_workflow_only=child_workflow_only,
            decision_task_completed_event_id=decision_completed_id,
        )
        self.batch.append(event)
        return event

    def add_external_cancel_requested(
        self, initiated_id: int, domain: str, workflow_id: str, run_id: str,
        now: int,
    ) -> HistoryEvent:
        self._require_running()
        if self.ms.get_request_cancel_info(initiated_id) is None:
            raise WorkflowStateError(
                f"request-cancel {initiated_id} not pending"
            )
        return self._add(
            lambda eid: F.external_workflow_execution_cancel_requested(
                eid, self.version, now,
                initiated_event_id=initiated_id, domain=domain,
                workflow_id=workflow_id, run_id=run_id,
            )
        )

    def add_request_cancel_external_failed(
        self, initiated_id: int, domain: str, workflow_id: str, run_id: str,
        cause: int, now: int,
    ) -> HistoryEvent:
        self._require_running()
        if self.ms.get_request_cancel_info(initiated_id) is None:
            raise WorkflowStateError(
                f"request-cancel {initiated_id} not pending"
            )
        return self._add(
            lambda eid: F.request_cancel_external_failed(
                eid, self.version, now,
                initiated_event_id=initiated_id, domain=domain,
                workflow_id=workflow_id, run_id=run_id, cause=cause,
                decision_task_completed_event_id=EMPTY_EVENT_ID,
            )
        )

    def add_signal_external_initiated(
        self, decision_completed_id: int, domain: str, workflow_id: str,
        run_id: str, signal_name: str, input: bytes, control: bytes,
        child_workflow_only: bool, now: int,
    ) -> HistoryEvent:
        self._require_running()
        event = F.signal_external_initiated(
            self._next_id(), self.version, now,
            domain=domain, workflow_id=workflow_id, run_id=run_id,
            signal_name=signal_name, input=input, control=control,
            child_workflow_only=child_workflow_only,
            decision_task_completed_event_id=decision_completed_id,
        )
        self.batch.append(event)
        return event

    def add_external_signaled(
        self, initiated_id: int, domain: str, workflow_id: str, run_id: str,
        control: bytes, now: int,
    ) -> HistoryEvent:
        self._require_running()
        if self.ms.get_signal_info(initiated_id) is None:
            raise WorkflowStateError(f"external signal {initiated_id} not pending")
        return self._add(
            lambda eid: F.external_workflow_execution_signaled(
                eid, self.version, now,
                initiated_event_id=initiated_id, domain=domain,
                workflow_id=workflow_id, run_id=run_id, control=control,
            )
        )

    def add_signal_external_failed(
        self, initiated_id: int, domain: str, workflow_id: str, run_id: str,
        cause: int, now: int,
    ) -> HistoryEvent:
        self._require_running()
        if self.ms.get_signal_info(initiated_id) is None:
            raise WorkflowStateError(f"external signal {initiated_id} not pending")
        return self._add(
            lambda eid: F.signal_external_failed(
                eid, self.version, now,
                initiated_event_id=initiated_id, domain=domain,
                workflow_id=workflow_id, run_id=run_id, cause=cause,
                decision_task_completed_event_id=EMPTY_EVENT_ID,
            )
        )

    # -- child workflows ---------------------------------------------

    def add_start_child_initiated(
        self, decision_completed_id: int, now: int, *, domain: str,
        workflow_id: str, **attrs: Any,
    ) -> HistoryEvent:
        self._require_running()
        event = F.start_child_initiated(
            self._next_id(), self.version, now,
            domain=domain, workflow_id=workflow_id,
            decision_task_completed_event_id=decision_completed_id,
            **attrs,
        )
        self.batch.append(event)
        return event

    def _check_pending_child(self, initiated_id: int) -> None:
        if self.ms.get_child_execution_info(initiated_id) is None:
            raise WorkflowStateError(f"child {initiated_id} not pending")

    def add_child_started(
        self, initiated_id: int, domain: str, workflow_id: str, run_id: str,
        workflow_type: str, now: int,
    ) -> HistoryEvent:
        self._require_running()
        self._check_pending_child(initiated_id)
        return self._add(
            lambda eid: F.child_execution_started(
                eid, self.version, now,
                initiated_event_id=initiated_id, domain=domain,
                workflow_id=workflow_id, run_id=run_id,
                workflow_type=workflow_type,
            )
        )

    def add_start_child_failed(
        self, initiated_id: int, domain: str, workflow_id: str,
        workflow_type: str, cause: int, now: int,
    ) -> HistoryEvent:
        self._require_running()
        self._check_pending_child(initiated_id)
        return self._add(
            lambda eid: F.start_child_failed(
                eid, self.version, now,
                initiated_event_id=initiated_id, domain=domain,
                workflow_id=workflow_id, workflow_type=workflow_type,
                cause=cause, decision_task_completed_event_id=EMPTY_EVENT_ID,
            )
        )

    def add_child_closed(
        self, initiated_id: int, close_type: EventType, now: int, **attrs: Any
    ) -> HistoryEvent:
        self._require_running()
        ci = self.ms.get_child_execution_info(initiated_id)
        if ci is None:
            raise WorkflowStateError(f"child {initiated_id} not pending")
        factory = {
            EventType.ChildWorkflowExecutionCompleted: F.child_execution_completed,
            EventType.ChildWorkflowExecutionFailed: F.child_execution_failed,
            EventType.ChildWorkflowExecutionCanceled: F.child_execution_canceled,
            EventType.ChildWorkflowExecutionTimedOut: F.child_execution_timed_out,
            EventType.ChildWorkflowExecutionTerminated: F.child_execution_terminated,
        }[close_type]
        return self._add(
            lambda eid: factory(
                eid, self.version, now,
                initiated_event_id=initiated_id,
                started_event_id=ci.started_id,
                **attrs,
            )
        )

    # -- workflow close ----------------------------------------------

    def _close_event(self, make: Callable[[int], HistoryEvent]) -> HistoryEvent:
        self._require_running()
        event = make(self._next_id())
        self.batch.append(event)
        self._closed_in_batch = True
        return event

    def add_workflow_execution_completed(
        self, decision_completed_id: int, now: int, result: bytes = b""
    ) -> HistoryEvent:
        return self._close_event(
            lambda eid: F.workflow_execution_completed(
                eid, self.version, now, result=result,
                decision_task_completed_event_id=decision_completed_id,
            )
        )

    def add_workflow_execution_failed(
        self, decision_completed_id: int, now: int, reason: str = "",
        details: bytes = b"",
    ) -> HistoryEvent:
        return self._close_event(
            lambda eid: F.workflow_execution_failed(
                eid, self.version, now, reason=reason, details=details,
                decision_task_completed_event_id=decision_completed_id,
            )
        )

    def add_workflow_execution_canceled(
        self, decision_completed_id: int, now: int, details: bytes = b""
    ) -> HistoryEvent:
        return self._close_event(
            lambda eid: F.workflow_execution_canceled(
                eid, self.version, now, details=details,
                decision_task_completed_event_id=decision_completed_id,
            )
        )

    def add_workflow_execution_terminated(
        self, now: int, reason: str = "", details: bytes = b"",
        identity: str = "",
    ) -> HistoryEvent:
        # terminate flushes the buffer into its own batch so no external
        # results are lost (terminate is legal with a decision in flight)
        self._require_running()
        self._flush_buffered()
        return self._close_event(
            lambda eid: F.workflow_execution_terminated(
                eid, self.version, now, reason=reason, details=details,
                identity=identity,
            )
        )

    def add_workflow_execution_timed_out(self, now: int) -> HistoryEvent:
        self._require_running()
        self._flush_buffered()
        return self._close_event(
            lambda eid: F.workflow_execution_timed_out(
                eid, self.version, now,
                timeout_type=TimeoutType.StartToClose,
            )
        )

    def add_continued_as_new(
        self, decision_completed_id: int, now: int, new_run_id: str, *,
        workflow_type: str, task_list: str,
        execution_start_to_close_timeout_seconds: int,
        task_start_to_close_timeout_seconds: int,
        input: bytes = b"",
        backoff_start_interval_seconds: int = 0,
        initiator: int = int(ContinueAsNewInitiator.Decider),
        schedule_new_decision: bool = True,
        **new_run_attrs: Any,
    ) -> HistoryEvent:
        """Close this run continued-as-new and stage the new run's first
        events (reference: retry/cron/decider continue-as-new,
        workflowExecutionContext.go continueAsNewWorkflowExecution)."""
        event = self._close_event(
            lambda eid: F.workflow_execution_continued_as_new(
                eid, self.version, now,
                new_execution_run_id=new_run_id,
                workflow_type=workflow_type, task_list=task_list,
                execution_start_to_close_timeout_seconds=(
                    execution_start_to_close_timeout_seconds
                ),
                task_start_to_close_timeout_seconds=(
                    task_start_to_close_timeout_seconds
                ),
                input=input,
                backoff_start_interval_in_seconds=backoff_start_interval_seconds,
                initiator=initiator,
                decision_task_completed_event_id=decision_completed_id,
            )
        )
        started = F.workflow_execution_started(
            1, self.version, now,
            workflow_type=workflow_type, task_list=task_list,
            execution_start_to_close_timeout_seconds=(
                execution_start_to_close_timeout_seconds
            ),
            task_start_to_close_timeout_seconds=(
                task_start_to_close_timeout_seconds
            ),
            input=input,
            continued_execution_run_id=self.run_id,
            first_decision_task_backoff_seconds=backoff_start_interval_seconds,
            initiator=initiator,
            **new_run_attrs,
        )
        self._new_run_events = [started]
        if schedule_new_decision and not backoff_start_interval_seconds:
            self._new_run_events.append(
                F.decision_task_scheduled(
                    2, self.version, now,
                    task_list=task_list,
                    start_to_close_timeout_seconds=(
                        task_start_to_close_timeout_seconds
                    ),
                )
            )
        return event

    # -- close --------------------------------------------------------

    def close(self) -> TransactionResult:
        """Replay the batch through the shared StateBuilder: mutates ms,
        generates transfer/timer tasks, handles the new run."""
        if not self.batch:
            return TransactionResult(
                events=[],
                transfer_tasks=self._extra_transfer,
                timer_tasks=self._extra_timer,
            )
        sb = StateBuilder(
            self.ms,
            domain_resolver=self.domain_resolver,
            id_generator=self.id_generator,
            retention_days=self.retention_days,
            # active path: the engine manages stickiness explicitly
            # (set on completion, cleared on decision failure/timeout)
            preserve_stickiness=True,
        )
        _, _, new_run_ms = sb.apply_events(
            self.domain_id,
            self.request_id,
            self.workflow_id,
            self.run_id,
            self.batch,
            new_run_history=self._new_run_events or None,
        )
        # replay auto-schedules transient retry decisions with a stale
        # schedule ID (the reference documents this is wrong on the
        # replica and corrected on the active side —
        # mutableStateDecisionTaskManager.go:174-183); we ARE the active
        # side, so correct it before anything observes it
        ei = self.ms.execution_info
        if (
            ei.decision_attempt > 0
            and ei.decision_schedule_id != EMPTY_EVENT_ID
            and ei.decision_started_id == EMPTY_EVENT_ID
            and ei.decision_schedule_id != self.ms.next_event_id
        ):
            stale = ei.decision_schedule_id
            ei.decision_schedule_id = self.ms.next_event_id
            for task in sb.transfer_tasks:
                if (
                    task.task_type == T.TransferTaskType.DecisionTask
                    and task.schedule_id == stale
                ):
                    task.schedule_id = ei.decision_schedule_id
        return TransactionResult(
            events=self.batch,
            transfer_tasks=self._extra_transfer + sb.transfer_tasks,
            timer_tasks=self._extra_timer + sb.timer_tasks,
            new_run_events=self._new_run_events,
            new_run_ms=new_run_ms,
            new_run_transfer_tasks=sb.new_run_transfer_tasks,
            new_run_timer_tasks=sb.new_run_timer_tasks,
        )
