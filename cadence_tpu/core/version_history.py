"""NDC version histories: (event_id, version) item chains + LCA.

Model of the reference's version-history types
(/root/reference/common/persistence/versionHistory.go:32-317 — items,
AddOrUpdateItem, FindLCAItem, IsLCAAppendable) used for multi-master
conflict resolution: each branch of a workflow's history tree carries the
list of ``(last event_id, failover version)`` runs that produced it; the
lowest common ancestor of two version histories decides where branches
diverged.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


class VersionHistoryError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class VersionHistoryItem:
    event_id: int
    version: int


class VersionHistory:
    """One branch's version history: items with increasing event_id AND
    increasing version (reference: versionHistory.go)."""

    def __init__(
        self,
        branch_token: bytes = b"",
        items: Optional[List[VersionHistoryItem]] = None,
    ) -> None:
        self.branch_token = branch_token
        self.items: List[VersionHistoryItem] = list(items or [])

    def duplicate(self) -> "VersionHistory":
        return VersionHistory(self.branch_token, list(self.items))

    def add_or_update_item(self, event_id: int, version: int) -> None:
        # reference: versionHistory.go AddOrUpdateItem
        if not self.items:
            self.items.append(VersionHistoryItem(event_id, version))
            return
        last = self.items[-1]
        if version < last.version:
            raise VersionHistoryError(
                f"version {version} < last version {last.version}"
            )
        if event_id <= last.event_id:
            raise VersionHistoryError(
                f"event id {event_id} <= last event id {last.event_id}"
            )
        if version == last.version:
            self.items[-1] = VersionHistoryItem(event_id, version)
        else:
            self.items.append(VersionHistoryItem(event_id, version))

    def last_item(self) -> VersionHistoryItem:
        if not self.items:
            raise VersionHistoryError("empty version history")
        return self.items[-1]

    def get_event_version(self, event_id: int) -> int:
        """Version that produced ``event_id`` (reference: GetEventVersion)."""
        prev_event_id = 0
        for item in self.items:
            if prev_event_id < event_id <= item.event_id:
                return item.version
            prev_event_id = item.event_id
        raise VersionHistoryError(f"event id {event_id} not in version history")

    def find_lca_item(self, other: "VersionHistory") -> VersionHistoryItem:
        """Lowest common ancestor item (reference: versionHistory.go FindLCAItem)."""
        i = len(self.items) - 1
        j = len(other.items) - 1
        while i >= 0 and j >= 0:
            a, b = self.items[i], other.items[j]
            if a.version == b.version:
                return VersionHistoryItem(min(a.event_id, b.event_id), a.version)
            if a.version > b.version:
                i -= 1
            else:
                j -= 1
        raise VersionHistoryError("version histories have no common ancestor")

    def is_lca_appendable(self, item: VersionHistoryItem) -> bool:
        # reference: IsLCAVersionHistoryItemAppendable
        return bool(self.items) and self.items[-1] == item

    def contains_item(self, item: VersionHistoryItem) -> bool:
        prev_event_id = 0
        for it in self.items:
            if prev_event_id < item.event_id <= it.event_id and item.version == it.version:
                return True
            prev_event_id = it.event_id
        return False

    def to_dict(self) -> dict:
        return {
            "branch_token": self.branch_token.decode("latin-1"),
            "items": [[it.event_id, it.version] for it in self.items],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "VersionHistory":
        return cls(
            d.get("branch_token", "").encode("latin-1"),
            [VersionHistoryItem(e, v) for e, v in d.get("items", [])],
        )


class VersionHistories:
    """All branches + the current one (reference: versionHistory.go
    VersionHistories, GetCurrentVersionHistory / FindLCAVersionHistoryIndexAndItem)."""

    def __init__(self, histories: Optional[List[VersionHistory]] = None,
                 current_index: int = 0) -> None:
        self.histories: List[VersionHistory] = histories or [VersionHistory()]
        self.current_index = current_index

    @classmethod
    def new_empty(cls) -> "VersionHistories":
        return cls()

    def get_current_version_history(self) -> VersionHistory:
        return self.histories[self.current_index]

    def get_version_history(self, index: int) -> VersionHistory:
        return self.histories[index]

    def add_version_history(self, vh: VersionHistory) -> Tuple[bool, int]:
        """Add a branch; returns (current_changed, new_index).

        The current branch switches iff the new branch's last write version
        is the highest (reference: AddVersionHistory)."""
        self.histories.append(vh)
        new_index = len(self.histories) - 1
        current = self.get_current_version_history()
        changed = False
        if vh.last_item().version > current.last_item().version:
            self.current_index = new_index
            changed = True
        return changed, new_index

    def find_lca_index_and_item(
        self, incoming: VersionHistory
    ) -> Tuple[int, VersionHistoryItem]:
        """Branch with the deepest LCA against ``incoming``."""
        best_index = -1
        best_item: Optional[VersionHistoryItem] = None
        for idx, vh in enumerate(self.histories):
            try:
                item = vh.find_lca_item(incoming)
            except VersionHistoryError:
                continue
            if best_item is None or item.event_id > best_item.event_id:
                best_index, best_item = idx, item
        if best_item is None:
            raise VersionHistoryError("no LCA across any branch")
        return best_index, best_item

    def find_first_matching_index(self, item: VersionHistoryItem) -> int:
        for idx, vh in enumerate(self.histories):
            if vh.contains_item(item):
                return idx
        raise VersionHistoryError(f"no branch contains item {item}")

    def to_dict(self) -> dict:
        return {
            "current_index": self.current_index,
            "histories": [h.to_dict() for h in self.histories],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "VersionHistories":
        return cls(
            [VersionHistory.from_dict(h) for h in d.get("histories", [])],
            d.get("current_index", 0),
        )
