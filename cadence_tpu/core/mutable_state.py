"""MutableState: the workflow finite-state machine.

This is the host-side (and semantic source-of-truth) twin of the reference's
``mutableStateBuilder`` (/root/reference/service/history/mutableStateBuilder.go:68-133
struct; Replicate* transitions :1639-3650) plus its decision-task sub-FSM
(/root/reference/service/history/mutableStateDecisionTaskManager.go).

Design: all *state* lives in plain dataclasses (ExecutionInfo + pending-info
maps) so that
  * the host runtime mutates it directly (active path),
  * ``cadence_tpu.ops.pack``/``unpack`` convert it to/from the dense tensor
    layout replayed on TPU (passive/rebuild path), and
  * differential tests compare host-oracle replay vs device-kernel replay
    field by field.

The ``replicate_*`` methods are pure state transitions driven by a
``HistoryEvent`` — no I/O, no persistence — exactly the contract the TPU
kernel vectorizes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Set, Tuple

from .enums import (
    CloseStatus,
    EventType,
    ParentClosePolicy,
    TimeoutType,
    WorkflowState,
    TIMER_TASK_STATUS_NONE,
)
from .events import HistoryEvent, RetryPolicy
from .ids import EMPTY_EVENT_ID, EMPTY_UUID, EMPTY_VERSION, FIRST_EVENT_ID

SECOND = 1_000_000_000  # ns


class InvalidHistoryError(Exception):
    """Raised when an event cannot legally apply to the current state."""


class StateTransitionError(Exception):
    """Raised on an illegal workflow state/close-status transition."""


@dataclasses.dataclass
class ExecutionInfo:
    """The workflow execution "state vector".

    Field-for-field model of the reference's WorkflowExecutionInfo
    (/root/reference/common/persistence/dataInterfaces.go:259-316).
    """

    domain_id: str = ""
    workflow_id: str = ""
    run_id: str = ""
    parent_domain_id: str = ""
    parent_workflow_id: str = ""
    parent_run_id: str = ""
    initiated_id: int = EMPTY_EVENT_ID
    completion_event_batch_id: int = EMPTY_EVENT_ID
    task_list: str = ""
    workflow_type_name: str = ""
    workflow_timeout: int = 0  # seconds
    decision_timeout_value: int = 0  # seconds
    execution_context: bytes = b""
    state: WorkflowState = WorkflowState.Created
    close_status: CloseStatus = CloseStatus.NONE
    last_first_event_id: int = EMPTY_EVENT_ID
    last_event_task_id: int = EMPTY_EVENT_ID
    next_event_id: int = FIRST_EVENT_ID
    last_processed_event: int = EMPTY_EVENT_ID
    start_timestamp: int = 0  # ns
    last_updated_timestamp: int = 0  # ns
    create_request_id: str = ""
    signal_count: int = 0
    # decision sub-FSM
    decision_version: int = EMPTY_VERSION
    decision_schedule_id: int = EMPTY_EVENT_ID
    decision_started_id: int = EMPTY_EVENT_ID
    decision_request_id: str = EMPTY_UUID
    decision_timeout: int = 0  # seconds
    decision_attempt: int = 0
    decision_started_timestamp: int = 0  # ns
    decision_scheduled_timestamp: int = 0  # ns
    decision_original_scheduled_timestamp: int = 0  # ns
    cancel_requested: bool = False
    cancel_request_id: str = ""
    sticky_task_list: str = ""
    sticky_schedule_to_start_timeout: int = 0
    client_library_version: str = ""
    client_feature_version: str = ""
    client_impl: str = ""
    auto_reset_points: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    memo: Dict[str, bytes] = dataclasses.field(default_factory=dict)
    search_attributes: Dict[str, bytes] = dataclasses.field(default_factory=dict)
    # workflow retry
    attempt: int = 0
    has_retry_policy: bool = False
    initial_interval: int = 0
    backoff_coefficient: float = 0.0
    maximum_interval: int = 0
    expiration_time: int = 0  # ns
    maximum_attempts: int = 0
    non_retriable_errors: List[str] = dataclasses.field(default_factory=list)
    branch_token: bytes = b""
    # cron
    cron_schedule: str = ""
    expiration_seconds: int = 0
    # first-decision backoff (cron/retry continued runs): absolute ns
    # deadline; task refresh re-arms the WorkflowBackoffTimer from it
    first_decision_backoff_deadline: int = 0
    # stats
    history_size: int = 0


@dataclasses.dataclass
class ActivityInfo:
    """Pending-activity entry (reference: dataInterfaces.go:625-662)."""

    version: int = EMPTY_VERSION
    schedule_id: int = EMPTY_EVENT_ID
    scheduled_event_batch_id: int = EMPTY_EVENT_ID
    scheduled_time: int = 0  # ns
    started_id: int = EMPTY_EVENT_ID
    started_time: int = 0  # ns
    activity_id: str = ""
    request_id: str = ""
    details: bytes = b""
    schedule_to_start_timeout: int = 0
    schedule_to_close_timeout: int = 0
    start_to_close_timeout: int = 0
    heartbeat_timeout: int = 0
    cancel_requested: bool = False
    cancel_request_id: int = EMPTY_EVENT_ID
    last_heartbeat_updated_time: int = 0  # ns
    timer_task_status: int = TIMER_TASK_STATUS_NONE
    attempt: int = 0
    domain_id: str = ""
    started_identity: str = ""
    task_list: str = ""
    has_retry_policy: bool = False
    initial_interval: int = 0
    backoff_coefficient: float = 0.0
    maximum_interval: int = 0
    expiration_time: int = 0  # ns
    maximum_attempts: int = 0
    non_retriable_errors: List[str] = dataclasses.field(default_factory=list)
    last_failure_reason: str = ""
    last_worker_identity: str = ""
    last_failure_details: bytes = b""


@dataclasses.dataclass
class TimerInfo:
    """Pending user-timer entry (reference: dataInterfaces.go:665-671)."""

    version: int = EMPTY_VERSION
    timer_id: str = ""
    started_id: int = EMPTY_EVENT_ID
    expiry_time: int = 0  # ns
    task_status: int = TIMER_TASK_STATUS_NONE


@dataclasses.dataclass
class ChildExecutionInfo:
    """Pending child-workflow entry (reference: dataInterfaces.go:674-691)."""

    version: int = EMPTY_VERSION
    initiated_id: int = EMPTY_EVENT_ID
    initiated_event_batch_id: int = EMPTY_EVENT_ID
    started_id: int = EMPTY_EVENT_ID
    started_workflow_id: str = ""
    started_run_id: str = ""
    create_request_id: str = ""
    domain_name: str = ""
    workflow_type_name: str = ""
    parent_close_policy: ParentClosePolicy = ParentClosePolicy.Abandon


@dataclasses.dataclass
class RequestCancelInfo:
    """Pending external-cancel entry (reference: dataInterfaces.go RequestCancelInfo)."""

    version: int = EMPTY_VERSION
    initiated_id: int = EMPTY_EVENT_ID
    initiated_event_batch_id: int = EMPTY_EVENT_ID
    cancel_request_id: str = ""
    # target coordinates (from the initiated event) — task refresh must
    # be able to regenerate a full CancelExecution transfer task
    target_domain_id: str = ""
    target_workflow_id: str = ""
    target_run_id: str = ""
    target_child_workflow_only: bool = False


@dataclasses.dataclass
class SignalInfo:
    """Pending external-signal entry (reference: dataInterfaces.go SignalInfo)."""

    version: int = EMPTY_VERSION
    initiated_id: int = EMPTY_EVENT_ID
    initiated_event_batch_id: int = EMPTY_EVENT_ID
    signal_request_id: str = ""
    signal_name: str = ""
    input: bytes = b""
    control: bytes = b""
    # target coordinates (from the initiated event) — see RequestCancelInfo
    target_domain_id: str = ""
    target_workflow_id: str = ""
    target_run_id: str = ""
    target_child_workflow_only: bool = False


@dataclasses.dataclass
class DecisionInfo:
    """In-flight decision descriptor (reference: service/history/mutableState.go decisionInfo)."""

    version: int = EMPTY_VERSION
    schedule_id: int = EMPTY_EVENT_ID
    started_id: int = EMPTY_EVENT_ID
    request_id: str = EMPTY_UUID
    decision_timeout: int = 0  # seconds
    task_list: str = ""
    attempt: int = 0
    scheduled_timestamp: int = 0  # ns
    started_timestamp: int = 0  # ns
    original_scheduled_timestamp: int = 0  # ns


# Legal (state, close_status) pairs — mirrors the reference validator
# (common/persistence/workflowStateCloseStatusValidator.go): only the
# Completed state may carry a non-NONE close status, and it must carry one.
def _validate_state_close(state: WorkflowState, close: CloseStatus) -> None:
    if state == WorkflowState.Completed:
        if close == CloseStatus.NONE:
            raise StateTransitionError("completed state requires a close status")
    elif close != CloseStatus.NONE:
        raise StateTransitionError(
            f"state {state.name} cannot carry close status {close.name}"
        )


class MutableState:
    """The full workflow mutable state + its replicate transitions."""

    def __init__(
        self,
        domain_id: str = "",
        current_version: int = EMPTY_VERSION,
    ) -> None:
        self.execution_info = ExecutionInfo(domain_id=domain_id)
        self.current_version = current_version

        # Pending maps, keyed exactly like the reference keeps them
        # (mutableStateBuilder.go:68-133).
        self.pending_activities: Dict[int, ActivityInfo] = {}  # schedule_id →
        self.activity_by_id: Dict[str, int] = {}  # activity_id → schedule_id
        self.pending_timers: Dict[str, TimerInfo] = {}  # timer_id →
        self.timer_by_started_id: Dict[int, str] = {}  # started_event_id → timer_id
        self.pending_children: Dict[int, ChildExecutionInfo] = {}  # initiated_id →
        self.pending_request_cancels: Dict[int, RequestCancelInfo] = {}
        self.pending_signals: Dict[int, SignalInfo] = {}
        self.signal_requested_ids: Set[str] = set()

        self.buffered_events: List[HistoryEvent] = []

        # NDC version histories (cadence_tpu.runtime.ndc.VersionHistories);
        # kept as Any to avoid a core→runtime dependency.
        self.version_histories: Optional[Any] = None

        # events written to the events cache by transitions (activity
        # scheduled / child initiated / completion events): the host runtime
        # drains this into its events cache.
        self.cached_events: List[HistoryEvent] = []

    # -- queries ----------------------------------------------------------

    @property
    def next_event_id(self) -> int:
        return self.execution_info.next_event_id

    def is_workflow_execution_running(self) -> bool:
        return self.execution_info.state not in (
            WorkflowState.Completed,
            WorkflowState.Zombie,
            WorkflowState.Void,
            WorkflowState.Corrupted,
        )

    def has_pending_decision(self) -> bool:
        # reference: mutableStateDecisionTaskManager.go:704-706
        return self.execution_info.decision_schedule_id != EMPTY_EVENT_ID

    def has_inflight_decision(self) -> bool:
        return self.execution_info.decision_started_id > 0

    def get_decision_info(self) -> Optional[DecisionInfo]:
        if not self.has_pending_decision():
            return None
        ei = self.execution_info
        return DecisionInfo(
            version=ei.decision_version,
            schedule_id=ei.decision_schedule_id,
            started_id=ei.decision_started_id,
            request_id=ei.decision_request_id,
            decision_timeout=ei.decision_timeout,
            task_list=ei.task_list,
            attempt=ei.decision_attempt,
            scheduled_timestamp=ei.decision_scheduled_timestamp,
            started_timestamp=ei.decision_started_timestamp,
            original_scheduled_timestamp=ei.decision_original_scheduled_timestamp,
        )

    def get_activity_info(self, schedule_id: int) -> Optional[ActivityInfo]:
        return self.pending_activities.get(schedule_id)

    def get_activity_by_activity_id(self, activity_id: str) -> Optional[ActivityInfo]:
        sid = self.activity_by_id.get(activity_id)
        return None if sid is None else self.pending_activities.get(sid)

    def get_user_timer(self, timer_id: str) -> Optional[TimerInfo]:
        return self.pending_timers.get(timer_id)

    def get_child_execution_info(self, initiated_id: int) -> Optional[ChildExecutionInfo]:
        return self.pending_children.get(initiated_id)

    def get_request_cancel_info(self, initiated_id: int) -> Optional[RequestCancelInfo]:
        return self.pending_request_cancels.get(initiated_id)

    def get_signal_info(self, initiated_id: int) -> Optional[SignalInfo]:
        return self.pending_signals.get(initiated_id)

    def has_parent_execution(self) -> bool:
        return (
            self.execution_info.parent_workflow_id != ""
            and self.execution_info.initiated_id != EMPTY_EVENT_ID
        )

    # -- generic state plumbing ------------------------------------------

    def update_current_version(self, version: int, force: bool = False) -> None:
        """Track the failover version of the event stream being applied."""
        if force or version > self.current_version or self.current_version == EMPTY_VERSION:
            self.current_version = version

    def update_workflow_state_close_status(
        self, state: WorkflowState, close_status: CloseStatus
    ) -> None:
        _validate_state_close(state, close_status)
        self.execution_info.state = state
        self.execution_info.close_status = close_status

    def clear_stickiness(self) -> None:
        self.execution_info.sticky_task_list = ""
        self.execution_info.sticky_schedule_to_start_timeout = 0

    def is_sticky_task_list_enabled(self) -> bool:
        return self.execution_info.sticky_task_list != ""

    def _write_event_to_cache(self, event: HistoryEvent) -> None:
        self.cached_events.append(event)

    # -- decision sub-FSM (reference: mutableStateDecisionTaskManager.go) --

    def _update_decision(self, d: DecisionInfo) -> None:
        # reference: mutableStateDecisionTaskManager.go:677-702
        ei = self.execution_info
        ei.decision_version = d.version
        ei.decision_schedule_id = d.schedule_id
        ei.decision_started_id = d.started_id
        ei.decision_request_id = d.request_id
        ei.decision_timeout = d.decision_timeout
        ei.decision_attempt = d.attempt
        ei.decision_started_timestamp = d.started_timestamp
        ei.decision_scheduled_timestamp = d.scheduled_timestamp
        ei.decision_original_scheduled_timestamp = d.original_scheduled_timestamp

    def delete_decision(self) -> None:
        # reference: mutableStateDecisionTaskManager.go:659-674
        self._update_decision(
            DecisionInfo(
                version=EMPTY_VERSION,
                schedule_id=EMPTY_EVENT_ID,
                started_id=EMPTY_EVENT_ID,
                request_id=EMPTY_UUID,
                decision_timeout=0,
                attempt=0,
                started_timestamp=0,
                scheduled_timestamp=0,
                original_scheduled_timestamp=self.execution_info.decision_original_scheduled_timestamp,
            )
        )

    def fail_decision(self, increment_attempt: bool, now: int = 0) -> None:
        # reference: mutableStateDecisionTaskManager.go:635-656
        self.clear_stickiness()
        d = DecisionInfo(
            version=EMPTY_VERSION,
            schedule_id=EMPTY_EVENT_ID,
            started_id=EMPTY_EVENT_ID,
            request_id=EMPTY_UUID,
            decision_timeout=0,
            started_timestamp=0,
            original_scheduled_timestamp=0,
        )
        if increment_attempt:
            d.attempt = self.execution_info.decision_attempt + 1
            d.scheduled_timestamp = now
        self._update_decision(d)

    # -- replicate transitions (the vectorized surface) -------------------

    def replicate_workflow_execution_started_event(
        self,
        parent_domain_id: Optional[str],
        workflow_id: str,
        run_id: str,
        request_id: str,
        event: HistoryEvent,
    ) -> None:
        # reference: mutableStateBuilder.go:1639-1718
        a = event.attributes
        ei = self.execution_info
        ei.create_request_id = request_id
        ei.workflow_id = workflow_id
        ei.run_id = run_id
        ei.task_list = a.get("task_list", "")
        ei.workflow_type_name = a.get("workflow_type", "")
        ei.workflow_timeout = a.get("execution_start_to_close_timeout_seconds", 0)
        ei.decision_timeout_value = a.get("task_start_to_close_timeout_seconds", 0)
        self.update_workflow_state_close_status(WorkflowState.Created, CloseStatus.NONE)
        ei.last_processed_event = EMPTY_EVENT_ID
        ei.last_first_event_id = event.event_id
        ei.decision_version = EMPTY_VERSION
        ei.decision_schedule_id = EMPTY_EVENT_ID
        ei.decision_started_id = EMPTY_EVENT_ID
        ei.decision_request_id = EMPTY_UUID
        ei.decision_timeout = 0
        ei.cron_schedule = a.get("cron_schedule", "")
        if parent_domain_id is not None:
            ei.parent_domain_id = parent_domain_id
        if a.get("parent_workflow_id"):
            ei.parent_workflow_id = a["parent_workflow_id"]
            ei.parent_run_id = a.get("parent_run_id", "")
        ei.initiated_id = a.get("parent_initiated_event_id", EMPTY_EVENT_ID)
        ei.attempt = a.get("attempt", 0)
        backoff_s = a.get("first_decision_task_backoff_seconds", 0) or 0
        ei.first_decision_backoff_deadline = (
            event.timestamp + backoff_s * 1_000_000_000 if backoff_s else 0
        )
        if a.get("expiration_timestamp", 0):
            ei.expiration_time = a["expiration_timestamp"]
        rp = RetryPolicy.from_dict(a.get("retry_policy"))
        if rp is not None:
            ei.has_retry_policy = True
            ei.backoff_coefficient = rp.backoff_coefficient
            ei.expiration_seconds = rp.expiration_interval_seconds
            ei.initial_interval = rp.initial_interval_seconds
            ei.maximum_attempts = rp.maximum_attempts
            ei.maximum_interval = rp.maximum_interval_seconds
            ei.non_retriable_errors = list(rp.non_retriable_error_reasons)
        ei.start_timestamp = event.timestamp
        if a.get("memo"):
            ei.memo = dict(a["memo"])
        if a.get("search_attributes"):
            ei.search_attributes = dict(a["search_attributes"])
        self._write_event_to_cache(event)

    def replicate_decision_task_scheduled_event(
        self,
        version: int,
        schedule_id: int,
        task_list: str,
        start_to_close_timeout_seconds: int,
        attempt: int,
        schedule_timestamp: int,
        original_scheduled_timestamp: int,
    ) -> DecisionInfo:
        # reference: mutableStateDecisionTaskManager.go:143-167
        d = DecisionInfo(
            version=version,
            schedule_id=schedule_id,
            started_id=EMPTY_EVENT_ID,
            request_id=EMPTY_UUID,
            decision_timeout=start_to_close_timeout_seconds,
            task_list=task_list,
            attempt=attempt,
            scheduled_timestamp=schedule_timestamp,
            started_timestamp=0,
            original_scheduled_timestamp=original_scheduled_timestamp,
        )
        self._update_decision(d)
        return d

    def replicate_transient_decision_task_scheduled(
        self, now: int
    ) -> Optional[DecisionInfo]:
        # reference: mutableStateDecisionTaskManager.go:169-198
        if self.has_pending_decision() or self.execution_info.decision_attempt == 0:
            return None
        d = DecisionInfo(
            version=self.current_version,
            schedule_id=self.execution_info.next_event_id,
            started_id=EMPTY_EVENT_ID,
            request_id=EMPTY_UUID,
            decision_timeout=self.execution_info.decision_timeout_value,
            task_list=self.execution_info.task_list,
            attempt=self.execution_info.decision_attempt,
            scheduled_timestamp=now,
            started_timestamp=0,
        )
        self._update_decision(d)
        return d

    def replicate_decision_task_started_event(
        self,
        decision: Optional[DecisionInfo],
        version: int,
        schedule_id: int,
        started_id: int,
        request_id: str,
        timestamp: int,
    ) -> DecisionInfo:
        # reference: mutableStateDecisionTaskManager.go:200-253
        if decision is None:
            decision = self.get_decision_info()
            if decision is None or decision.schedule_id != schedule_id:
                raise InvalidHistoryError(f"unable to find decision {schedule_id}")
            # replication path: reset attempt so a half-replicated transient
            # decision can still time out correctly
            decision.attempt = 0

        if self.execution_info.state == WorkflowState.Created:
            self.update_workflow_state_close_status(
                WorkflowState.Running, CloseStatus.NONE
            )

        d = DecisionInfo(
            version=version,
            schedule_id=schedule_id,
            started_id=started_id,
            request_id=request_id,
            decision_timeout=decision.decision_timeout,
            attempt=decision.attempt,
            started_timestamp=timestamp,
            scheduled_timestamp=decision.scheduled_timestamp,
            task_list=decision.task_list,
            original_scheduled_timestamp=decision.original_scheduled_timestamp,
        )
        self._update_decision(d)
        return d

    # reference: dynamicconfig MaxAutoResetPoints (default 20)
    MAX_RESET_POINTS = 20

    @staticmethod
    def record_reset_point(
        points: List[Dict[str, Any]], checksum: str, run_id: str,
        completed_event_id: int, created_time: int,
    ) -> None:
        """Append the first-completed-decision-per-binary reset anchor
        (reference addBinaryCheckSumIfNotExists) with dedup + cap. The
        ONE implementation shared by the live replicate path and the
        device packer (ops/pack.py) so rebuilt state always agrees."""
        if not checksum or any(
            p.get("binary_checksum") == checksum for p in points
        ):
            return
        points.append({
            "binary_checksum": checksum,
            "run_id": run_id,
            "first_decision_completed_id": completed_event_id,
            "created_time": created_time,
            "resettable": True,
        })
        del points[:-MutableState.MAX_RESET_POINTS]

    def replicate_decision_task_completed_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateDecisionTaskManager.go:255-262,789-800
        self.delete_decision()
        self.execution_info.last_processed_event = event.attributes.get(
            "started_event_id", EMPTY_EVENT_ID
        )
        # auto reset points live on the replicate path so active,
        # replicated, and rebuilt state all agree
        ei = self.execution_info
        self.record_reset_point(
            ei.auto_reset_points,
            event.attributes.get("binary_checksum", "") or "",
            ei.run_id, event.event_id, event.timestamp,
        )

    def replicate_decision_task_failed_event(self, now: int = 0) -> None:
        # reference: mutableStateDecisionTaskManager.go:264-267
        self.fail_decision(True, now)

    def replicate_decision_task_timed_out_event(
        self, timeout_type: TimeoutType, now: int = 0
    ) -> None:
        # reference: mutableStateDecisionTaskManager.go:269-279 — sticky
        # (schedule-to-start) timeouts do not increment the attempt.
        self.fail_decision(timeout_type != TimeoutType.ScheduleToStart, now)

    # activities

    def replicate_activity_task_scheduled_event(
        self, first_event_id: int, event: HistoryEvent
    ) -> ActivityInfo:
        # reference: mutableStateBuilder.go:1982-2029
        a = event.attributes
        schedule_to_close = a.get("schedule_to_close_timeout_seconds", 0)
        ai = ActivityInfo(
            version=event.version,
            schedule_id=event.event_id,
            scheduled_event_batch_id=first_event_id,
            scheduled_time=event.timestamp,
            started_id=EMPTY_EVENT_ID,
            started_time=0,
            activity_id=a.get("activity_id", ""),
            schedule_to_start_timeout=a.get("schedule_to_start_timeout_seconds", 0),
            schedule_to_close_timeout=schedule_to_close,
            start_to_close_timeout=a.get("start_to_close_timeout_seconds", 0),
            heartbeat_timeout=a.get("heartbeat_timeout_seconds", 0),
            cancel_requested=False,
            cancel_request_id=EMPTY_EVENT_ID,
            timer_task_status=TIMER_TASK_STATUS_NONE,
            task_list=a.get("task_list", ""),
            has_retry_policy=a.get("retry_policy") is not None,
        )
        ai.expiration_time = ai.scheduled_time + schedule_to_close * SECOND
        rp = RetryPolicy.from_dict(a.get("retry_policy"))
        if rp is not None:
            ai.initial_interval = rp.initial_interval_seconds
            ai.backoff_coefficient = rp.backoff_coefficient
            ai.maximum_interval = rp.maximum_interval_seconds
            ai.maximum_attempts = rp.maximum_attempts
            ai.non_retriable_errors = list(rp.non_retriable_error_reasons)
            if rp.expiration_interval_seconds > schedule_to_close:
                ai.expiration_time = (
                    ai.scheduled_time + rp.expiration_interval_seconds * SECOND
                )
        self.pending_activities[ai.schedule_id] = ai
        self.activity_by_id[ai.activity_id] = ai.schedule_id
        self._write_event_to_cache(event)
        return ai

    def replicate_activity_task_started_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2083-2098
        schedule_id = event.attributes.get("scheduled_event_id", EMPTY_EVENT_ID)
        ai = self.pending_activities.get(schedule_id)
        if ai is None:
            raise InvalidHistoryError(f"activity started for unknown schedule {schedule_id}")
        ai.version = event.version
        ai.started_id = event.event_id
        ai.request_id = event.attributes.get("request_id", "")
        ai.started_time = event.timestamp
        ai.last_heartbeat_updated_time = ai.started_time
        ai.attempt = event.attributes.get("attempt", ai.attempt)
        ai.started_identity = event.attributes.get("identity", "")

    def _delete_activity(self, schedule_id: int) -> None:
        ai = self.pending_activities.pop(schedule_id, None)
        if ai is None:
            raise InvalidHistoryError(f"delete of unknown activity {schedule_id}")
        # only drop the secondary index if it still points at us
        if self.activity_by_id.get(ai.activity_id) == schedule_id:
            del self.activity_by_id[ai.activity_id]

    def retry_activity(self, ai: ActivityInfo, now: int, failure_reason: str = ""):
        """Schedule the next attempt in place; returns the
        ActivityRetryTimer task or None when retries are exhausted
        (reference: mutableStateBuilder.go RetryActivity). No history
        event is written — only the final failure is recorded."""
        from cadence_tpu.utils.backoff import (
            NO_INTERVAL,
            RetryPolicy as BackoffPolicy,
            next_backoff_interval_seconds,
        )

        from .tasks import TimerTask
        from .enums import TimerTaskType

        if not ai.has_retry_policy or ai.cancel_requested:
            return None
        policy = BackoffPolicy(
            initial_interval_seconds=ai.initial_interval,
            backoff_coefficient=ai.backoff_coefficient,
            maximum_interval_seconds=ai.maximum_interval,
            maximum_attempts=ai.maximum_attempts,
            expiration_seconds=1 if ai.expiration_time else 0,
            non_retriable_errors=tuple(ai.non_retriable_errors),
        )
        interval = next_backoff_interval_seconds(
            policy, ai.attempt, ai.expiration_time, now,
            error_reason=failure_reason,
        )
        if interval == NO_INTERVAL:
            return None
        ai.version = self.current_version
        ai.attempt += 1
        ai.scheduled_time = now + interval * SECOND
        ai.started_id = EMPTY_EVENT_ID
        ai.started_time = 0
        ai.request_id = ""
        ai.timer_task_status = TIMER_TASK_STATUS_NONE
        if failure_reason:
            ai.last_failure_reason = failure_reason
        return TimerTask(
            task_type=TimerTaskType.ActivityRetryTimer,
            visibility_timestamp=ai.scheduled_time,
            event_id=ai.schedule_id,
            schedule_attempt=ai.attempt,
            version=ai.version,
        )

    def replicate_activity_task_completed_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2132-2140
        self._delete_activity(event.attributes.get("scheduled_event_id", EMPTY_EVENT_ID))

    def replicate_activity_task_failed_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2174-2182
        self._delete_activity(event.attributes.get("scheduled_event_id", EMPTY_EVENT_ID))

    def replicate_activity_task_timed_out_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2220-2228
        self._delete_activity(event.attributes.get("scheduled_event_id", EMPTY_EVENT_ID))

    def replicate_activity_task_cancel_requested_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2292+ — looked up by activity ID;
        # a missing activity is a corrupt history.
        activity_id = event.attributes.get("activity_id", "")
        ai = self.get_activity_by_activity_id(activity_id)
        if ai is None:
            raise InvalidHistoryError(
                f"cancel requested for unknown activity {activity_id!r}"
            )
        ai.version = event.version
        ai.cancel_requested = True
        ai.cancel_request_id = event.event_id

    def replicate_activity_task_canceled_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2346-2354
        self._delete_activity(event.attributes.get("scheduled_event_id", EMPTY_EVENT_ID))

    # timers

    def replicate_timer_started_event(self, event: HistoryEvent) -> TimerInfo:
        # reference: mutableStateBuilder.go:2877-2901; a duplicate pending
        # timer ID is treated as corrupt history (the active path can never
        # produce one — AddStartTimer validates), keeping host-replay and
        # pack-time strictness identical.
        a = event.attributes
        timer_id = a.get("timer_id", "")
        if timer_id in self.pending_timers:
            raise InvalidHistoryError(f"duplicate pending timer {timer_id!r}")
        ti = TimerInfo(
            version=event.version,
            timer_id=timer_id,
            expiry_time=event.timestamp
            + a.get("start_to_fire_timeout_seconds", 0) * SECOND,
            started_id=event.event_id,
            task_status=TIMER_TASK_STATUS_NONE,
        )
        self.pending_timers[timer_id] = ti
        self.timer_by_started_id[ti.started_id] = timer_id
        return ti

    def _delete_user_timer(self, timer_id: str) -> None:
        ti = self.pending_timers.pop(timer_id, None)
        if ti is None:
            raise InvalidHistoryError(f"delete of unknown timer {timer_id!r}")
        self.timer_by_started_id.pop(ti.started_id, None)

    def replicate_timer_fired_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2930-2939
        self._delete_user_timer(event.attributes.get("timer_id", ""))

    def replicate_timer_canceled_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2982-2991
        self._delete_user_timer(event.attributes.get("timer_id", ""))

    # workflow-level

    def replicate_workflow_execution_signaled(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:3082-3089
        self.execution_info.signal_count += 1

    def replicate_workflow_execution_cancel_requested_event(
        self, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:2504-2510
        self.execution_info.cancel_requested = True
        self.execution_info.cancel_request_id = event.attributes.get("cancel_request_id", "")

    def _close_execution(
        self, first_event_id: int, event: HistoryEvent, close_status: CloseStatus
    ) -> None:
        self.update_workflow_state_close_status(WorkflowState.Completed, close_status)
        self.execution_info.completion_event_batch_id = first_event_id
        self.clear_stickiness()
        self._write_event_to_cache(event)

    def replicate_workflow_execution_completed_event(
        self, first_event_id: int, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:2379-2395
        self._close_execution(first_event_id, event, CloseStatus.Completed)

    def replicate_workflow_execution_failed_event(
        self, first_event_id: int, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:2419-2436
        self._close_execution(first_event_id, event, CloseStatus.Failed)

    def replicate_workflow_execution_timedout_event(
        self, first_event_id: int, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:2456-2472
        self._close_execution(first_event_id, event, CloseStatus.TimedOut)

    def replicate_workflow_execution_canceled_event(
        self, first_event_id: int, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:2535-2551
        self._close_execution(first_event_id, event, CloseStatus.Canceled)

    def replicate_workflow_execution_terminated_event(
        self, first_event_id: int, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:3047-3063
        self._close_execution(first_event_id, event, CloseStatus.Terminated)

    def replicate_workflow_execution_continued_as_new_event(
        self, first_event_id: int, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:3207-3225
        self._close_execution(first_event_id, event, CloseStatus.ContinuedAsNew)

    def replicate_upsert_workflow_search_attributes_event(
        self, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:2746-2757 — merge semantics
        upserts = event.attributes.get("search_attributes", {})
        self.execution_info.search_attributes.update(upserts)

    # external cancel / signal

    def replicate_request_cancel_external_initiated_event(
        self, first_event_id: int, event: HistoryEvent, cancel_request_id: str
    ) -> RequestCancelInfo:
        # reference: mutableStateBuilder.go:2577-2607
        rci = RequestCancelInfo(
            version=event.version,
            initiated_id=event.event_id,
            initiated_event_batch_id=first_event_id,
            cancel_request_id=cancel_request_id,
        )
        self.pending_request_cancels[rci.initiated_id] = rci
        return rci

    def _delete_pending_request_cancel(self, initiated_id: int) -> None:
        if self.pending_request_cancels.pop(initiated_id, None) is None:
            raise InvalidHistoryError(f"delete of unknown request-cancel {initiated_id}")

    def replicate_external_workflow_execution_cancel_requested(
        self, event: HistoryEvent
    ) -> None:
        # reference: mutableStateBuilder.go:2626-2633
        self._delete_pending_request_cancel(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    def replicate_request_cancel_external_failed_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2666-2673
        self._delete_pending_request_cancel(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    def replicate_signal_external_initiated_event(
        self, first_event_id: int, event: HistoryEvent, signal_request_id: str
    ) -> SignalInfo:
        # reference: mutableStateBuilder.go:2701-2736
        a = event.attributes
        si = SignalInfo(
            version=event.version,
            initiated_id=event.event_id,
            initiated_event_batch_id=first_event_id,
            signal_request_id=signal_request_id,
            signal_name=a.get("signal_name", ""),
            input=a.get("input", b""),
            control=a.get("control", b""),
        )
        self.pending_signals[si.initiated_id] = si
        return si

    def _delete_pending_signal(self, initiated_id: int) -> None:
        if self.pending_signals.pop(initiated_id, None) is None:
            raise InvalidHistoryError(f"delete of unknown external signal {initiated_id}")

    def replicate_external_workflow_execution_signaled(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2799-2806
        self._delete_pending_signal(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    def replicate_signal_external_failed_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:2840-2847
        self._delete_pending_signal(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    # children

    def replicate_start_child_initiated_event(
        self, first_event_id: int, event: HistoryEvent, create_request_id: str
    ) -> ChildExecutionInfo:
        # reference: mutableStateBuilder.go:3256-3281
        a = event.attributes
        ci = ChildExecutionInfo(
            version=event.version,
            initiated_id=event.event_id,
            initiated_event_batch_id=first_event_id,
            started_id=EMPTY_EVENT_ID,
            started_workflow_id=a.get("workflow_id", ""),
            create_request_id=create_request_id,
            domain_name=a.get("domain", ""),
            workflow_type_name=a.get("workflow_type", ""),
            parent_close_policy=ParentClosePolicy(
                a.get("parent_close_policy", int(ParentClosePolicy.Abandon))
            ),
        )
        self.pending_children[ci.initiated_id] = ci
        self._write_event_to_cache(event)
        return ci

    def replicate_child_execution_started_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:3312-3325
        initiated_id = event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        ci = self.pending_children.get(initiated_id)
        if ci is None:
            raise InvalidHistoryError(f"child started for unknown initiated {initiated_id}")
        ci.started_id = event.event_id
        ci.started_run_id = event.attributes.get("run_id", "")

    def _delete_pending_child(self, initiated_id: int) -> None:
        if self.pending_children.pop(initiated_id, None) is None:
            raise InvalidHistoryError(f"delete of unknown child {initiated_id}")

    def replicate_start_child_failed_event(self, event: HistoryEvent) -> None:
        # reference: mutableStateBuilder.go:3355-3368
        self._delete_pending_child(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    def replicate_child_execution_completed_event(self, event: HistoryEvent) -> None:
        self._delete_pending_child(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    def replicate_child_execution_failed_event(self, event: HistoryEvent) -> None:
        self._delete_pending_child(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    def replicate_child_execution_canceled_event(self, event: HistoryEvent) -> None:
        self._delete_pending_child(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    def replicate_child_execution_terminated_event(self, event: HistoryEvent) -> None:
        self._delete_pending_child(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    def replicate_child_execution_timed_out_event(self, event: HistoryEvent) -> None:
        self._delete_pending_child(
            event.attributes.get("initiated_event_id", EMPTY_EVENT_ID)
        )

    # -- snapshotting -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """A plain-dict snapshot for persistence / comparison."""
        return {
            "execution_info": dataclasses.asdict(self.execution_info),
            "pending_activities": {
                k: dataclasses.asdict(v) for k, v in self.pending_activities.items()
            },
            "pending_timers": {
                k: dataclasses.asdict(v) for k, v in self.pending_timers.items()
            },
            "pending_children": {
                k: dataclasses.asdict(v) for k, v in self.pending_children.items()
            },
            "pending_request_cancels": {
                k: dataclasses.asdict(v)
                for k, v in self.pending_request_cancels.items()
            },
            "pending_signals": {
                k: dataclasses.asdict(v) for k, v in self.pending_signals.items()
            },
            "signal_requested_ids": sorted(self.signal_requested_ids),
            "current_version": self.current_version,
            "buffered_events": [e.to_dict() for e in self.buffered_events],
            "version_histories": (
                self.version_histories.to_dict()
                if self.version_histories is not None
                else None
            ),
        }

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "MutableState":
        ms = cls()
        ei = dict(snap["execution_info"])
        ei["state"] = WorkflowState(ei["state"])
        ei["close_status"] = CloseStatus(ei["close_status"])
        ms.execution_info = ExecutionInfo(**ei)
        for k, v in snap.get("pending_activities", {}).items():
            ai = ActivityInfo(**v)
            ms.pending_activities[int(k)] = ai
            ms.activity_by_id[ai.activity_id] = int(k)
        for k, v in snap.get("pending_timers", {}).items():
            ti = TimerInfo(**v)
            ms.pending_timers[k] = ti
            ms.timer_by_started_id[ti.started_id] = k
        for k, v in snap.get("pending_children", {}).items():
            v = dict(v)
            v["parent_close_policy"] = ParentClosePolicy(v["parent_close_policy"])
            ms.pending_children[int(k)] = ChildExecutionInfo(**v)
        for k, v in snap.get("pending_request_cancels", {}).items():
            ms.pending_request_cancels[int(k)] = RequestCancelInfo(**v)
        for k, v in snap.get("pending_signals", {}).items():
            ms.pending_signals[int(k)] = SignalInfo(**v)
        ms.signal_requested_ids = set(snap.get("signal_requested_ids", []))
        ms.current_version = snap.get("current_version", EMPTY_VERSION)
        ms.buffered_events = [
            HistoryEvent.from_dict(d) for d in snap.get("buffered_events", [])
        ]
        vh = snap.get("version_histories")
        if vh is not None:
            from .version_history import VersionHistories

            ms.version_histories = VersionHistories.from_dict(vh)
        return ms
