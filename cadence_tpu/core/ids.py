"""Sentinel IDs/versions shared across the FSM, host runtime, and kernels.

Mirrors the reference's common/constants.go:28-41 sentinels so that replay
semantics (e.g. "no pending decision" == DecisionScheduleID ==
EMPTY_EVENT_ID) are identical.
"""

from __future__ import annotations

# First event in any history.
FIRST_EVENT_ID = 1
# "no event" sentinel.
EMPTY_EVENT_ID = -23
# Event held in the buffered-events list, not yet assigned a real ID.
BUFFERED_EVENT_ID = -123
# Transient (not-yet-persisted) decision/activity started event.
TRANSIENT_EVENT_ID = -124
# Uninitialized per-event task ID.
EMPTY_EVENT_TASK_ID = -1234
# "no version" sentinel (local domains / uninitialized).
EMPTY_VERSION = -24

EMPTY_UUID = "emptyUuid"

# Versions for cross-cluster failover arithmetic
# (reference: common/cluster/metadata.go — version % increment selects cluster).
DEFAULT_FAILOVER_VERSION_INCREMENT = 10
