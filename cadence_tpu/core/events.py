"""History events: the durable record of every workflow state transition.

A ``HistoryEvent`` is the unit of the event-sourced log (reference model:
idl/github.com/uber/cadence/shared.thrift HistoryEvent + the per-type
*EventAttributes structs). Attributes are stored as a plain dict with
snake_case keys so that events serialize to JSON losslessly and pack into
dense tensors cheaply (cadence_tpu/ops/pack.py extracts the integer columns,
leaving payload bytes in a host-side side table — payloads never influence
transitions).

Timestamps are int nanoseconds (host precision); the device path quantizes
to seconds relative to a batch epoch during packing.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterable, List, Optional

from .enums import EventType
from .ids import EMPTY_EVENT_TASK_ID


@dataclasses.dataclass
class HistoryEvent:
    event_id: int
    event_type: EventType
    version: int
    timestamp: int  # ns
    task_id: int = EMPTY_EVENT_TASK_ID
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def get(self, key: str, default: Any = None) -> Any:
        return self.attributes.get(key, default)

    # -- serialization ----------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "event_id": self.event_id,
            "event_type": int(self.event_type),
            "version": self.version,
            "timestamp": self.timestamp,
            "task_id": self.task_id,
            "attributes": _jsonable(self.attributes),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "HistoryEvent":
        return cls(
            event_id=d["event_id"],
            event_type=EventType(d["event_type"]),
            version=d["version"],
            timestamp=d["timestamp"],
            task_id=d.get("task_id", EMPTY_EVENT_TASK_ID),
            attributes=_unjsonable(d.get("attributes", {})),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "HistoryEvent":
        return cls.from_dict(json.loads(s))


def _jsonable(obj: Any) -> Any:
    """Make attribute values JSON-safe (bytes → latin-1 tagged strings)."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, bytes):
        return {"__bytes__": obj.decode("latin-1")}
    return obj


def _unjsonable(obj: Any) -> Any:
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__bytes__"}:
            return obj["__bytes__"].encode("latin-1")
        return {k: _unjsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unjsonable(v) for v in obj]
    return obj


def encode_batch(events: Iterable[HistoryEvent]) -> bytes:
    """Serialize an event batch (one history node) to bytes."""
    return json.dumps([e.to_dict() for e in events], separators=(",", ":")).encode()


def decode_batch(blob: bytes) -> List[HistoryEvent]:
    return [HistoryEvent.from_dict(d) for d in json.loads(blob.decode())]


@dataclasses.dataclass
class RetryPolicy:
    """Activity/workflow retry policy (reference: shared.thrift RetryPolicy)."""

    initial_interval_seconds: int = 0
    backoff_coefficient: float = 2.0
    maximum_interval_seconds: int = 0
    maximum_attempts: int = 0  # 0 == unlimited
    expiration_interval_seconds: int = 0
    non_retriable_error_reasons: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["RetryPolicy"]:
        if d is None:
            return None
        return cls(**d)


@dataclasses.dataclass
class WorkflowExecution:
    workflow_id: str
    run_id: str


@dataclasses.dataclass
class WorkflowType:
    name: str
