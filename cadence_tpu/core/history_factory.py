"""Constructors for every history event type.

The attribute vocabulary here is the framework-wide contract: MutableState
transitions, the tensor packer (ops/pack.py), the active-side
HistoryBuilder, and the test event-graph generator all speak it.

Modeled on the reference's historyBuilder Add*Event constructors
(/root/reference/service/history/historyBuilder.go) and the per-type
*EventAttributes in the IDL.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .enums import EventType, ParentClosePolicy, TimeoutType
from .events import HistoryEvent, RetryPolicy
from .ids import EMPTY_EVENT_TASK_ID


def _ev(
    event_id: int,
    event_type: EventType,
    version: int,
    timestamp: int,
    attributes: Dict[str, Any],
    task_id: int = EMPTY_EVENT_TASK_ID,
) -> HistoryEvent:
    return HistoryEvent(
        event_id=event_id,
        event_type=event_type,
        version=version,
        timestamp=timestamp,
        task_id=task_id,
        attributes={k: v for k, v in attributes.items() if v is not None},
    )


def workflow_execution_started(
    event_id: int, version: int, timestamp: int, *,
    workflow_type: str = "wf",
    task_list: str = "tl",
    execution_start_to_close_timeout_seconds: int = 60,
    task_start_to_close_timeout_seconds: int = 10,
    input: bytes = b"",
    identity: str = "",
    parent_workflow_domain: Optional[str] = None,
    parent_workflow_id: Optional[str] = None,
    parent_run_id: Optional[str] = None,
    parent_initiated_event_id: Optional[int] = None,
    retry_policy: Optional[RetryPolicy] = None,
    attempt: int = 0,
    expiration_timestamp: int = 0,
    cron_schedule: str = "",
    first_decision_task_backoff_seconds: int = 0,
    initiator: int = 0,
    continued_execution_run_id: str = "",
    memo: Optional[Dict[str, bytes]] = None,
    search_attributes: Optional[Dict[str, bytes]] = None,
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionStarted, version, timestamp, {
        "workflow_type": workflow_type,
        "task_list": task_list,
        "execution_start_to_close_timeout_seconds": execution_start_to_close_timeout_seconds,
        "task_start_to_close_timeout_seconds": task_start_to_close_timeout_seconds,
        "input": input,
        "identity": identity,
        "parent_workflow_domain": parent_workflow_domain,
        "parent_workflow_id": parent_workflow_id,
        "parent_run_id": parent_run_id,
        "parent_initiated_event_id": parent_initiated_event_id,
        "retry_policy": retry_policy.to_dict() if retry_policy else None,
        "attempt": attempt,
        "expiration_timestamp": expiration_timestamp,
        "cron_schedule": cron_schedule,
        "first_decision_task_backoff_seconds": first_decision_task_backoff_seconds,
        "initiator": initiator,
        "continued_execution_run_id": continued_execution_run_id,
        "memo": memo,
        "search_attributes": search_attributes,
    })


def decision_task_scheduled(
    event_id: int, version: int, timestamp: int, *,
    task_list: str = "tl",
    start_to_close_timeout_seconds: int = 10,
    attempt: int = 0,
) -> HistoryEvent:
    return _ev(event_id, EventType.DecisionTaskScheduled, version, timestamp, {
        "task_list": task_list,
        "start_to_close_timeout_seconds": start_to_close_timeout_seconds,
        "attempt": attempt,
    })


def decision_task_started(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    identity: str = "",
    request_id: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.DecisionTaskStarted, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "identity": identity,
        "request_id": request_id,
    })


def decision_task_completed(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    started_event_id: int,
    identity: str = "",
    binary_checksum: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.DecisionTaskCompleted, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "started_event_id": started_event_id,
        "identity": identity,
        "binary_checksum": binary_checksum,
    })


def decision_task_timed_out(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    started_event_id: int = 0,
    timeout_type: TimeoutType = TimeoutType.StartToClose,
) -> HistoryEvent:
    return _ev(event_id, EventType.DecisionTaskTimedOut, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "started_event_id": started_event_id,
        "timeout_type": int(timeout_type),
    })


def decision_task_failed(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    started_event_id: int = 0,
    cause: int = 0,
    identity: str = "",
    reason: str = "",
    details: bytes = b"",
    base_run_id: str = "",
    new_run_id: str = "",
    fork_event_version: int = 0,
) -> HistoryEvent:
    return _ev(event_id, EventType.DecisionTaskFailed, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "started_event_id": started_event_id,
        "cause": cause,
        "identity": identity,
        "reason": reason,
        "details": details,
        "base_run_id": base_run_id,
        "new_run_id": new_run_id,
        "fork_event_version": fork_event_version,
    })


def activity_task_scheduled(
    event_id: int, version: int, timestamp: int, *,
    activity_id: str,
    activity_type: str = "act",
    task_list: str = "tl",
    decision_task_completed_event_id: int = 0,
    schedule_to_start_timeout_seconds: int = 10,
    schedule_to_close_timeout_seconds: int = 20,
    start_to_close_timeout_seconds: int = 10,
    heartbeat_timeout_seconds: int = 0,
    input: bytes = b"",
    retry_policy: Optional[RetryPolicy] = None,
) -> HistoryEvent:
    return _ev(event_id, EventType.ActivityTaskScheduled, version, timestamp, {
        "activity_id": activity_id,
        "activity_type": activity_type,
        "task_list": task_list,
        "decision_task_completed_event_id": decision_task_completed_event_id,
        "schedule_to_start_timeout_seconds": schedule_to_start_timeout_seconds,
        "schedule_to_close_timeout_seconds": schedule_to_close_timeout_seconds,
        "start_to_close_timeout_seconds": start_to_close_timeout_seconds,
        "heartbeat_timeout_seconds": heartbeat_timeout_seconds,
        "input": input,
        "retry_policy": retry_policy.to_dict() if retry_policy else None,
    })


def activity_task_started(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    identity: str = "",
    request_id: str = "",
    attempt: int = 0,
) -> HistoryEvent:
    return _ev(event_id, EventType.ActivityTaskStarted, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "identity": identity,
        "request_id": request_id,
        "attempt": attempt,
    })


def activity_task_completed(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    started_event_id: int,
    result: bytes = b"",
    identity: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.ActivityTaskCompleted, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "started_event_id": started_event_id,
        "result": result,
        "identity": identity,
    })


def activity_task_failed(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    started_event_id: int,
    reason: str = "",
    details: bytes = b"",
    identity: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.ActivityTaskFailed, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "started_event_id": started_event_id,
        "reason": reason,
        "details": details,
        "identity": identity,
    })


def activity_task_timed_out(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    started_event_id: int,
    timeout_type: TimeoutType = TimeoutType.StartToClose,
    details: bytes = b"",
) -> HistoryEvent:
    return _ev(event_id, EventType.ActivityTaskTimedOut, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "started_event_id": started_event_id,
        "timeout_type": int(timeout_type),
        "details": details,
    })


def activity_task_cancel_requested(
    event_id: int, version: int, timestamp: int, *,
    activity_id: str,
    decision_task_completed_event_id: int = 0,
) -> HistoryEvent:
    return _ev(event_id, EventType.ActivityTaskCancelRequested, version, timestamp, {
        "activity_id": activity_id,
        "decision_task_completed_event_id": decision_task_completed_event_id,
    })


def request_cancel_activity_task_failed(
    event_id: int, version: int, timestamp: int, *,
    activity_id: str,
    cause: str = "ACTIVITY_ID_UNKNOWN",
    decision_task_completed_event_id: int = 0,
) -> HistoryEvent:
    return _ev(event_id, EventType.RequestCancelActivityTaskFailed, version, timestamp, {
        "activity_id": activity_id,
        "cause": cause,
        "decision_task_completed_event_id": decision_task_completed_event_id,
    })


def activity_task_canceled(
    event_id: int, version: int, timestamp: int, *,
    scheduled_event_id: int,
    started_event_id: int,
    latest_cancel_requested_event_id: int = 0,
    details: bytes = b"",
    identity: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.ActivityTaskCanceled, version, timestamp, {
        "scheduled_event_id": scheduled_event_id,
        "started_event_id": started_event_id,
        "latest_cancel_requested_event_id": latest_cancel_requested_event_id,
        "details": details,
        "identity": identity,
    })


def timer_started(
    event_id: int, version: int, timestamp: int, *,
    timer_id: str,
    start_to_fire_timeout_seconds: int,
    decision_task_completed_event_id: int = 0,
) -> HistoryEvent:
    return _ev(event_id, EventType.TimerStarted, version, timestamp, {
        "timer_id": timer_id,
        "start_to_fire_timeout_seconds": start_to_fire_timeout_seconds,
        "decision_task_completed_event_id": decision_task_completed_event_id,
    })


def timer_fired(
    event_id: int, version: int, timestamp: int, *,
    timer_id: str,
    started_event_id: int,
) -> HistoryEvent:
    return _ev(event_id, EventType.TimerFired, version, timestamp, {
        "timer_id": timer_id,
        "started_event_id": started_event_id,
    })


def cancel_timer_failed(
    event_id: int, version: int, timestamp: int, *,
    timer_id: str,
    cause: str = "TIMER_ID_UNKNOWN",
    decision_task_completed_event_id: int = 0,
    identity: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.CancelTimerFailed, version, timestamp, {
        "timer_id": timer_id,
        "cause": cause,
        "decision_task_completed_event_id": decision_task_completed_event_id,
        "identity": identity,
    })


def timer_canceled(
    event_id: int, version: int, timestamp: int, *,
    timer_id: str,
    started_event_id: int,
    decision_task_completed_event_id: int = 0,
    identity: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.TimerCanceled, version, timestamp, {
        "timer_id": timer_id,
        "started_event_id": started_event_id,
        "decision_task_completed_event_id": decision_task_completed_event_id,
        "identity": identity,
    })


def workflow_execution_cancel_requested(
    event_id: int, version: int, timestamp: int, *,
    cause: str = "",
    identity: str = "",
    cancel_request_id: str = "",
    external_initiated_event_id: Optional[int] = None,
    external_workflow_id: Optional[str] = None,
    external_run_id: Optional[str] = None,
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionCancelRequested, version, timestamp, {
        "cause": cause,
        "identity": identity,
        "cancel_request_id": cancel_request_id,
        "external_initiated_event_id": external_initiated_event_id,
        "external_workflow_id": external_workflow_id,
        "external_run_id": external_run_id,
    })


def workflow_execution_signaled(
    event_id: int, version: int, timestamp: int, *,
    signal_name: str = "signal",
    input: bytes = b"",
    identity: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionSignaled, version, timestamp, {
        "signal_name": signal_name,
        "input": input,
        "identity": identity,
    })


def marker_recorded(
    event_id: int, version: int, timestamp: int, *,
    marker_name: str = "marker",
    details: bytes = b"",
    decision_task_completed_event_id: int = 0,
    identity: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.MarkerRecorded, version, timestamp, {
        "marker_name": marker_name,
        "details": details,
        "decision_task_completed_event_id": decision_task_completed_event_id,
        "identity": identity,
    })


def workflow_execution_completed(
    event_id: int, version: int, timestamp: int, *,
    decision_task_completed_event_id: int = 0,
    result: bytes = b"",
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionCompleted, version, timestamp, {
        "decision_task_completed_event_id": decision_task_completed_event_id,
        "result": result,
    })


def workflow_execution_failed(
    event_id: int, version: int, timestamp: int, *,
    decision_task_completed_event_id: int = 0,
    reason: str = "",
    details: bytes = b"",
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionFailed, version, timestamp, {
        "decision_task_completed_event_id": decision_task_completed_event_id,
        "reason": reason,
        "details": details,
    })


def workflow_execution_timed_out(
    event_id: int, version: int, timestamp: int, *,
    timeout_type: TimeoutType = TimeoutType.StartToClose,
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionTimedOut, version, timestamp, {
        "timeout_type": int(timeout_type),
    })


def workflow_execution_canceled(
    event_id: int, version: int, timestamp: int, *,
    decision_task_completed_event_id: int = 0,
    details: bytes = b"",
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionCanceled, version, timestamp, {
        "decision_task_completed_event_id": decision_task_completed_event_id,
        "details": details,
    })


def workflow_execution_terminated(
    event_id: int, version: int, timestamp: int, *,
    reason: str = "",
    details: bytes = b"",
    identity: str = "",
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionTerminated, version, timestamp, {
        "reason": reason,
        "details": details,
        "identity": identity,
    })


def workflow_execution_continued_as_new(
    event_id: int, version: int, timestamp: int, *,
    new_execution_run_id: str,
    workflow_type: str = "wf",
    task_list: str = "tl",
    decision_task_completed_event_id: int = 0,
    execution_start_to_close_timeout_seconds: int = 60,
    task_start_to_close_timeout_seconds: int = 10,
    input: bytes = b"",
    initiator: int = 0,
    backoff_start_interval_in_seconds: int = 0,
) -> HistoryEvent:
    return _ev(event_id, EventType.WorkflowExecutionContinuedAsNew, version, timestamp, {
        "new_execution_run_id": new_execution_run_id,
        "workflow_type": workflow_type,
        "task_list": task_list,
        "decision_task_completed_event_id": decision_task_completed_event_id,
        "execution_start_to_close_timeout_seconds": execution_start_to_close_timeout_seconds,
        "task_start_to_close_timeout_seconds": task_start_to_close_timeout_seconds,
        "input": input,
        "initiator": initiator,
        "backoff_start_interval_in_seconds": backoff_start_interval_in_seconds,
    })


def request_cancel_external_initiated(
    event_id: int, version: int, timestamp: int, *,
    domain: str,
    workflow_id: str,
    run_id: str = "",
    child_workflow_only: bool = False,
    decision_task_completed_event_id: int = 0,
    control: bytes = b"",
) -> HistoryEvent:
    return _ev(
        event_id, EventType.RequestCancelExternalWorkflowExecutionInitiated,
        version, timestamp, {
            "domain": domain,
            "workflow_id": workflow_id,
            "run_id": run_id,
            "child_workflow_only": child_workflow_only,
            "decision_task_completed_event_id": decision_task_completed_event_id,
            "control": control,
        })


def request_cancel_external_failed(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int,
    domain: str = "",
    workflow_id: str = "",
    run_id: str = "",
    cause: int = 0,
    decision_task_completed_event_id: int = 0,
) -> HistoryEvent:
    return _ev(
        event_id, EventType.RequestCancelExternalWorkflowExecutionFailed,
        version, timestamp, {
            "initiated_event_id": initiated_event_id,
            "domain": domain,
            "workflow_id": workflow_id,
            "run_id": run_id,
            "cause": cause,
            "decision_task_completed_event_id": decision_task_completed_event_id,
        })


def external_workflow_execution_cancel_requested(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int,
    domain: str = "",
    workflow_id: str = "",
    run_id: str = "",
) -> HistoryEvent:
    return _ev(
        event_id, EventType.ExternalWorkflowExecutionCancelRequested,
        version, timestamp, {
            "initiated_event_id": initiated_event_id,
            "domain": domain,
            "workflow_id": workflow_id,
            "run_id": run_id,
        })


def signal_external_initiated(
    event_id: int, version: int, timestamp: int, *,
    domain: str,
    workflow_id: str,
    run_id: str = "",
    signal_name: str = "signal",
    input: bytes = b"",
    child_workflow_only: bool = False,
    decision_task_completed_event_id: int = 0,
    control: bytes = b"",
) -> HistoryEvent:
    return _ev(
        event_id, EventType.SignalExternalWorkflowExecutionInitiated,
        version, timestamp, {
            "domain": domain,
            "workflow_id": workflow_id,
            "run_id": run_id,
            "signal_name": signal_name,
            "input": input,
            "child_workflow_only": child_workflow_only,
            "decision_task_completed_event_id": decision_task_completed_event_id,
            "control": control,
        })


def signal_external_failed(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int,
    domain: str = "",
    workflow_id: str = "",
    run_id: str = "",
    cause: int = 0,
    decision_task_completed_event_id: int = 0,
) -> HistoryEvent:
    return _ev(
        event_id, EventType.SignalExternalWorkflowExecutionFailed,
        version, timestamp, {
            "initiated_event_id": initiated_event_id,
            "domain": domain,
            "workflow_id": workflow_id,
            "run_id": run_id,
            "cause": cause,
            "decision_task_completed_event_id": decision_task_completed_event_id,
        })


def external_workflow_execution_signaled(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int,
    domain: str = "",
    workflow_id: str = "",
    run_id: str = "",
    control: bytes = b"",
) -> HistoryEvent:
    return _ev(
        event_id, EventType.ExternalWorkflowExecutionSignaled,
        version, timestamp, {
            "initiated_event_id": initiated_event_id,
            "domain": domain,
            "workflow_id": workflow_id,
            "run_id": run_id,
            "control": control,
        })


def upsert_workflow_search_attributes(
    event_id: int, version: int, timestamp: int, *,
    search_attributes: Optional[Dict[str, bytes]] = None,
    decision_task_completed_event_id: int = 0,
) -> HistoryEvent:
    return _ev(
        event_id, EventType.UpsertWorkflowSearchAttributes, version, timestamp, {
            "search_attributes": search_attributes or {},
            "decision_task_completed_event_id": decision_task_completed_event_id,
        })


def start_child_initiated(
    event_id: int, version: int, timestamp: int, *,
    domain: str,
    workflow_id: str,
    workflow_type: str = "child_wf",
    task_list: str = "tl",
    decision_task_completed_event_id: int = 0,
    parent_close_policy: ParentClosePolicy = ParentClosePolicy.Terminate,
    input: bytes = b"",
    execution_start_to_close_timeout_seconds: int = 60,
    task_start_to_close_timeout_seconds: int = 10,
) -> HistoryEvent:
    return _ev(
        event_id, EventType.StartChildWorkflowExecutionInitiated,
        version, timestamp, {
            "domain": domain,
            "workflow_id": workflow_id,
            "workflow_type": workflow_type,
            "task_list": task_list,
            "decision_task_completed_event_id": decision_task_completed_event_id,
            "parent_close_policy": int(parent_close_policy),
            "input": input,
            "execution_start_to_close_timeout_seconds": execution_start_to_close_timeout_seconds,
            "task_start_to_close_timeout_seconds": task_start_to_close_timeout_seconds,
        })


def start_child_failed(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int,
    domain: str = "",
    workflow_id: str = "",
    workflow_type: str = "",
    cause: int = 0,
    decision_task_completed_event_id: int = 0,
) -> HistoryEvent:
    return _ev(
        event_id, EventType.StartChildWorkflowExecutionFailed,
        version, timestamp, {
            "initiated_event_id": initiated_event_id,
            "domain": domain,
            "workflow_id": workflow_id,
            "workflow_type": workflow_type,
            "cause": cause,
            "decision_task_completed_event_id": decision_task_completed_event_id,
        })


def child_execution_started(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int,
    domain: str = "",
    workflow_id: str = "",
    run_id: str = "",
    workflow_type: str = "",
) -> HistoryEvent:
    return _ev(
        event_id, EventType.ChildWorkflowExecutionStarted, version, timestamp, {
            "initiated_event_id": initiated_event_id,
            "domain": domain,
            "workflow_id": workflow_id,
            "run_id": run_id,
            "workflow_type": workflow_type,
        })


def _child_closed(
    et: EventType, event_id: int, version: int, timestamp: int,
    initiated_event_id: int, started_event_id: int, extra: Dict[str, Any],
) -> HistoryEvent:
    base = {
        "initiated_event_id": initiated_event_id,
        "started_event_id": started_event_id,
    }
    base.update(extra)
    return _ev(event_id, et, version, timestamp, base)


def child_execution_completed(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int, started_event_id: int, result: bytes = b"",
) -> HistoryEvent:
    return _child_closed(
        EventType.ChildWorkflowExecutionCompleted, event_id, version, timestamp,
        initiated_event_id, started_event_id, {"result": result})


def child_execution_failed(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int, started_event_id: int,
    reason: str = "", details: bytes = b"",
) -> HistoryEvent:
    return _child_closed(
        EventType.ChildWorkflowExecutionFailed, event_id, version, timestamp,
        initiated_event_id, started_event_id, {"reason": reason, "details": details})


def child_execution_canceled(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int, started_event_id: int, details: bytes = b"",
) -> HistoryEvent:
    return _child_closed(
        EventType.ChildWorkflowExecutionCanceled, event_id, version, timestamp,
        initiated_event_id, started_event_id, {"details": details})


def child_execution_timed_out(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int, started_event_id: int,
    timeout_type: TimeoutType = TimeoutType.StartToClose,
) -> HistoryEvent:
    return _child_closed(
        EventType.ChildWorkflowExecutionTimedOut, event_id, version, timestamp,
        initiated_event_id, started_event_id, {"timeout_type": int(timeout_type)})


def child_execution_terminated(
    event_id: int, version: int, timestamp: int, *,
    initiated_event_id: int, started_event_id: int,
) -> HistoryEvent:
    return _child_closed(
        EventType.ChildWorkflowExecutionTerminated, event_id, version, timestamp,
        initiated_event_id, started_event_id, {})
