"""Core workflow FSM: events, mutable state, replay oracle, task generation."""
