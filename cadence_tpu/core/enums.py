"""Workflow event/decision/state enumerations.

Semantics match the reference's Thrift IDL
(/root/reference/idl/github.com/uber/cadence/shared.thrift:152-196 EventType,
:136-150 DecisionType, :119-124 TimeoutType, :239-246 CloseStatus) and the
persistence-level workflow state constants
(/root/reference/common/persistence/dataInterfaces.go WorkflowState*).

Values are dense small ints on purpose: ``EventType`` indexes rows of the
TPU transition table (cadence_tpu/ops/replay.py), so the enum ordering is
part of the on-device ABI.
"""

from __future__ import annotations

import enum


class EventType(enum.IntEnum):
    """History event types; order mirrors the reference IDL enum."""

    WorkflowExecutionStarted = 0
    WorkflowExecutionCompleted = 1
    WorkflowExecutionFailed = 2
    WorkflowExecutionTimedOut = 3
    DecisionTaskScheduled = 4
    DecisionTaskStarted = 5
    DecisionTaskCompleted = 6
    DecisionTaskTimedOut = 7
    DecisionTaskFailed = 8
    ActivityTaskScheduled = 9
    ActivityTaskStarted = 10
    ActivityTaskCompleted = 11
    ActivityTaskFailed = 12
    ActivityTaskTimedOut = 13
    ActivityTaskCancelRequested = 14
    RequestCancelActivityTaskFailed = 15
    ActivityTaskCanceled = 16
    TimerStarted = 17
    TimerFired = 18
    CancelTimerFailed = 19
    TimerCanceled = 20
    WorkflowExecutionCancelRequested = 21
    WorkflowExecutionCanceled = 22
    RequestCancelExternalWorkflowExecutionInitiated = 23
    RequestCancelExternalWorkflowExecutionFailed = 24
    ExternalWorkflowExecutionCancelRequested = 25
    MarkerRecorded = 26
    WorkflowExecutionSignaled = 27
    WorkflowExecutionTerminated = 28
    WorkflowExecutionContinuedAsNew = 29
    StartChildWorkflowExecutionInitiated = 30
    StartChildWorkflowExecutionFailed = 31
    ChildWorkflowExecutionStarted = 32
    ChildWorkflowExecutionCompleted = 33
    ChildWorkflowExecutionFailed = 34
    ChildWorkflowExecutionCanceled = 35
    ChildWorkflowExecutionTimedOut = 36
    ChildWorkflowExecutionTerminated = 37
    SignalExternalWorkflowExecutionInitiated = 38
    SignalExternalWorkflowExecutionFailed = 39
    ExternalWorkflowExecutionSignaled = 40
    UpsertWorkflowSearchAttributes = 41


NUM_EVENT_TYPES = len(EventType)


class DecisionType(enum.IntEnum):
    """Client decision types (the workflow "instruction set")."""

    ScheduleActivityTask = 0
    RequestCancelActivityTask = 1
    StartTimer = 2
    CompleteWorkflowExecution = 3
    FailWorkflowExecution = 4
    CancelTimer = 5
    CancelWorkflowExecution = 6
    RequestCancelExternalWorkflowExecution = 7
    RecordMarker = 8
    ContinueAsNewWorkflowExecution = 9
    StartChildWorkflowExecution = 10
    SignalExternalWorkflowExecution = 11
    UpsertWorkflowSearchAttributes = 12


class ContinueAsNewInitiator(enum.IntEnum):
    """Why a run continued-as-new (reference: shared.thrift
    ContinueAsNewInitiator; stateBuilder treats 2 == CronSchedule)."""

    Decider = 0
    RetryPolicy = 1
    CronSchedule = 2


class TimeoutType(enum.IntEnum):
    StartToClose = 0
    ScheduleToStart = 1
    ScheduleToClose = 2
    Heartbeat = 3


class ParentClosePolicy(enum.IntEnum):
    Abandon = 0
    RequestCancel = 1
    Terminate = 2


class WorkflowState(enum.IntEnum):
    """Lifecycle state of a workflow execution record.

    Mirrors WorkflowStateCreated/Running/Completed/Zombie/Void/Corrupted in
    the reference persistence layer.
    """

    Created = 0
    Running = 1
    Completed = 2
    Zombie = 3
    Void = 4
    Corrupted = 5


class CloseStatus(enum.IntEnum):
    """Close status; ``NONE`` means still open."""

    NONE = 0
    Completed = 1
    Failed = 2
    Canceled = 3
    Terminated = 4
    ContinuedAsNew = 5
    TimedOut = 6


class PendingActivityState(enum.IntEnum):
    Scheduled = 0
    Started = 1
    CancelRequested = 2


class IDReusePolicy(enum.IntEnum):
    AllowDuplicateFailedOnly = 0
    AllowDuplicate = 1
    RejectDuplicate = 2


class QueryResultType(enum.IntEnum):
    Answered = 0
    Failed = 1


class DecisionTaskFailedCause(enum.IntEnum):
    UnhandledDecision = 0
    BadScheduleActivityAttributes = 1
    BadRequestCancelActivityAttributes = 2
    BadStartTimerAttributes = 3
    BadCancelTimerAttributes = 4
    BadRecordMarkerAttributes = 5
    BadCompleteWorkflowExecutionAttributes = 6
    BadFailWorkflowExecutionAttributes = 7
    BadCancelWorkflowExecutionAttributes = 8
    BadRequestCancelExternalAttributes = 9
    BadContinueAsNewAttributes = 10
    StartTimerDuplicateID = 11
    ResetStickyTaskList = 12
    WorkflowWorkerUnhandledFailure = 13
    BadSignalWorkflowExecutionAttributes = 14
    BadStartChildExecutionAttributes = 15
    ForceCloseDecision = 16
    FailoverCloseDecision = 17
    BadSignalInputSize = 18
    ResetWorkflow = 19
    BadBinary = 20
    ScheduleActivityDuplicateID = 21
    BadSearchAttributes = 22


class CancelExternalWorkflowFailedCause(enum.IntEnum):
    """reference: shared.thrift CancelExternalWorkflowExecutionFailedCause."""

    UnknownExternalWorkflowExecution = 0


class SignalExternalWorkflowFailedCause(enum.IntEnum):
    """reference: shared.thrift SignalExternalWorkflowExecutionFailedCause."""

    UnknownExternalWorkflowExecution = 0


class ChildWorkflowFailedCause(enum.IntEnum):
    """reference: shared.thrift ChildWorkflowExecutionFailedCause."""

    WorkflowAlreadyRunning = 0


class TransferTaskType(enum.IntEnum):
    """Transfer-queue task kinds (reference: common/persistence TransferTaskType*)."""

    DecisionTask = 0
    ActivityTask = 1
    CloseExecution = 2
    CancelExecution = 3
    StartChildExecution = 4
    SignalExecution = 5
    RecordWorkflowStarted = 6
    ResetWorkflow = 7
    UpsertWorkflowSearchAttributes = 8


class TimerTaskType(enum.IntEnum):
    """Timer-queue task kinds (reference: TaskTypeDecisionTimeout etc.)."""

    DecisionTimeout = 0
    ActivityTimeout = 1
    UserTimer = 2
    WorkflowTimeout = 3
    DeleteHistoryEvent = 4
    ActivityRetryTimer = 5
    WorkflowBackoffTimer = 6


class WorkflowBackoffType(enum.IntEnum):
    Retry = 0
    Cron = 1


class TaskListType(enum.IntEnum):
    Decision = 0
    Activity = 1


# Workflow close event type -> CloseStatus recorded on X_CLOSE_STATUS:
# the single source of truth every replay kernel (sequential XLA scan,
# Pallas, both associative evaluators in ops/assoc.py) derives its
# close-status arithmetic from, so a new close type lands in all of
# them at once instead of four hand-kept copies.
WORKFLOW_CLOSE_STATUS = (
    (EventType.WorkflowExecutionCompleted, CloseStatus.Completed),
    (EventType.WorkflowExecutionFailed, CloseStatus.Failed),
    (EventType.WorkflowExecutionTimedOut, CloseStatus.TimedOut),
    (EventType.WorkflowExecutionCanceled, CloseStatus.Canceled),
    (EventType.WorkflowExecutionTerminated, CloseStatus.Terminated),
    (EventType.WorkflowExecutionContinuedAsNew, CloseStatus.ContinuedAsNew),
)


def decision_attempt_increment(dfail, dto, a0):
    """Which decision fail/timeout steps bump X_DEC_ATTEMPT — the oracle's
    ``fail_decision`` precondition, shared by every replay kernel: a
    DecisionTaskFailed always increments; a DecisionTaskTimedOut
    increments unless its timeout type (``a0``) is ScheduleToStart.
    Pure ``|``/``&``/``!=`` so numpy and jax bool masks both work."""
    return dfail | (dto & (a0 != int(TimeoutType.ScheduleToStart)))


# Activity timer-task dedup status bitmask, mirrors the reference's
# TimerTaskStatus* bit flags (service/history/mutableStateBuilder.go).
TIMER_TASK_STATUS_NONE = 0
TIMER_TASK_STATUS_CREATED = 1
TIMER_TASK_STATUS_CREATED_START_TO_CLOSE = 1 << 1
TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_START = 1 << 2
TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_CLOSE = 1 << 3
TIMER_TASK_STATUS_CREATED_HEARTBEAT = 1 << 4
