"""TimerSequence: next-timer-task computation with creation dedup.

Twin of the reference's timerBuilder
(/root/reference/service/history/timerBuilder.go — GetUserTimerTaskIfNeeded /
GetActivityTimerTaskIfNeeded): the timer queue only needs a durable task for
the *earliest* pending expiry; per-entry status bits dedup task creation.

Deterministic ordering — (expiry, event_id, timeout_type) — is part of the
replay contract: the TPU kernel computes the same argmin.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .enums import (
    TimeoutType,
    TimerTaskType,
    TIMER_TASK_STATUS_CREATED,
    TIMER_TASK_STATUS_CREATED_HEARTBEAT,
    TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_CLOSE,
    TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_START,
    TIMER_TASK_STATUS_CREATED_START_TO_CLOSE,
)
from .ids import EMPTY_EVENT_ID
from .mutable_state import MutableState, SECOND
from .tasks import TimerTask

_TIMEOUT_BIT = {
    TimeoutType.StartToClose: TIMER_TASK_STATUS_CREATED_START_TO_CLOSE,
    TimeoutType.ScheduleToStart: TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_START,
    TimeoutType.ScheduleToClose: TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_CLOSE,
    TimeoutType.Heartbeat: TIMER_TASK_STATUS_CREATED_HEARTBEAT,
}


class TimerSequence:
    def __init__(self, ms: MutableState) -> None:
        self.ms = ms

    # -- user timers ----------------------------------------------------

    def user_timer_task_if_needed(self) -> Optional[TimerTask]:
        """Durable task for the earliest pending user timer, once."""
        timers = sorted(
            self.ms.pending_timers.values(),
            key=lambda ti: (ti.expiry_time, ti.started_id),
        )
        if not timers:
            return None
        ti = timers[0]
        if ti.task_status & TIMER_TASK_STATUS_CREATED:
            return None
        ti.task_status |= TIMER_TASK_STATUS_CREATED
        return TimerTask(
            task_type=TimerTaskType.UserTimer,
            visibility_timestamp=ti.expiry_time,
            event_id=ti.started_id,
            version=ti.version,
        )

    # -- activity timeouts ----------------------------------------------

    def _activity_timeout_candidates(self) -> List[Tuple[int, int, int, object]]:
        """(expiry, schedule_id, timeout_type, activity) for every armed timeout."""
        out = []
        for ai in self.ms.pending_activities.values():
            if ai.started_id == EMPTY_EVENT_ID:
                if ai.schedule_to_start_timeout > 0:
                    out.append((
                        ai.scheduled_time + ai.schedule_to_start_timeout * SECOND,
                        ai.schedule_id, int(TimeoutType.ScheduleToStart), ai,
                    ))
                if ai.schedule_to_close_timeout > 0:
                    out.append((
                        ai.scheduled_time + ai.schedule_to_close_timeout * SECOND,
                        ai.schedule_id, int(TimeoutType.ScheduleToClose), ai,
                    ))
            else:
                if ai.start_to_close_timeout > 0:
                    out.append((
                        ai.started_time + ai.start_to_close_timeout * SECOND,
                        ai.schedule_id, int(TimeoutType.StartToClose), ai,
                    ))
                if ai.heartbeat_timeout > 0:
                    out.append((
                        ai.last_heartbeat_updated_time + ai.heartbeat_timeout * SECOND,
                        ai.schedule_id, int(TimeoutType.Heartbeat), ai,
                    ))
                if ai.schedule_to_close_timeout > 0:
                    out.append((
                        ai.scheduled_time + ai.schedule_to_close_timeout * SECOND,
                        ai.schedule_id, int(TimeoutType.ScheduleToClose), ai,
                    ))
        return sorted(out, key=lambda c: (c[0], c[1], c[2]))

    def activity_timer_task_if_needed(self) -> Optional[TimerTask]:
        """Durable task for the earliest armed activity timeout, once."""
        candidates = self._activity_timeout_candidates()
        if not candidates:
            return None
        expiry, schedule_id, timeout_type, ai = candidates[0]
        bit = _TIMEOUT_BIT[TimeoutType(timeout_type)]
        if ai.timer_task_status & bit:
            return None
        ai.timer_task_status |= bit
        return TimerTask(
            task_type=TimerTaskType.ActivityTimeout,
            visibility_timestamp=expiry,
            timeout_type=timeout_type,
            event_id=schedule_id,
            schedule_attempt=ai.attempt,
            version=ai.version,
        )
