"""StateBuilder: replay a history-event stream into MutableState + tasks.

Host-side oracle twin of the reference's ``stateBuilderImpl.applyEvents``
(/root/reference/service/history/stateBuilder.go:112-613: the 42-case
event-type switch, the per-event version-history preamble :134-155, and the
task-scheduling helpers :620-800). The TPU kernel
(cadence_tpu/ops/replay.py) vectorizes exactly this function; differential
tests (tests/test_replay_differential.py) assert bit-parity between the two.

This is also the production replayer on paths where a single workflow must
be rebuilt host-side (active-side recovery, resets with host-only state).
"""

from __future__ import annotations

import uuid
from typing import Callable, List, Optional, Tuple

from .enums import EventType, TimeoutType, TimerTaskType, WorkflowBackoffType
from .events import HistoryEvent
from .ids import EMPTY_EVENT_ID
from .mutable_state import DecisionInfo, MutableState, SECOND
from . import tasks as T
from .timer_sequence import TimerSequence


class StateBuilder:
    """Applies event batches to a MutableState, accumulating queue tasks."""

    def __init__(
        self,
        mutable_state: MutableState,
        domain_resolver: Callable[[str], str] = lambda name: name,
        id_generator: Callable[[], str] = lambda: str(uuid.uuid4()),
        retention_days: int = 1,
        preserve_stickiness: bool = False,
    ) -> None:
        self.ms = mutable_state
        self.domain_resolver = domain_resolver
        self.id_generator = id_generator
        self.retention_days = retention_days
        # the reference clears worker stickiness when a REPLICATED
        # batch applies (the remote worker's affinity means nothing
        # here, stateBuilder.go:130); the ACTIVE transaction path runs
        # through this same builder and must NOT wipe the affinity the
        # engine just recorded
        self.preserve_stickiness = preserve_stickiness
        self.transfer_tasks: List[T.TransferTask] = []
        self.timer_tasks: List[T.TimerTask] = []
        self.new_run_transfer_tasks: List[T.TransferTask] = []
        self.new_run_timer_tasks: List[T.TimerTask] = []

    # ------------------------------------------------------------------

    def apply_batches(
        self,
        domain_id: str,
        request_id: str,
        workflow_id: str,
        run_id: str,
        batches: List[List[HistoryEvent]],
    ) -> None:
        """Replay a multi-batch history, one apply_events call per
        transaction batch (the caller-side loop the reference's rebuilder
        runs, nDCStateRebuilder.go:128-137)."""
        for batch in batches:
            self.apply_events(domain_id, request_id, workflow_id, run_id, batch)

    def apply_events(
        self,
        domain_id: str,
        request_id: str,
        workflow_id: str,
        run_id: str,
        history: List[HistoryEvent],
        new_run_history: Optional[List[HistoryEvent]] = None,
    ) -> Tuple[HistoryEvent, Optional[DecisionInfo], Optional[MutableState]]:
        """Apply ONE transaction batch of events.

        Contract: ``history`` is a single persisted transaction batch —
        batch-derived state (scheduled_event_batch_id,
        completion_event_batch_id, transient-decision schedule IDs, and the
        batch-end next_event_id update) all key off ``history[0]``. For a
        multi-batch stream use ``apply_batches``; passing a flat multi-
        transaction list treats it as one giant batch, which is legal but
        yields different batch IDs than per-batch replay.
        """
        if not history:
            raise ValueError("history size is zero")
        first_event = history[0]
        last_event = history[-1]
        last_decision: Optional[DecisionInfo] = None
        new_run_ms: Optional[MutableState] = None
        ms = self.ms

        # workflow turned passive for this apply — reference :130
        if not self.preserve_stickiness:
            ms.clear_stickiness()

        for event in history:
            # version-history preamble — reference :134-155
            if ms.version_histories is not None:
                ms.update_current_version(event.version, force=True)
                vh = ms.version_histories.get_current_version_history()
                vh.add_or_update_item(event.event_id, event.version)
            ms.execution_info.last_event_task_id = event.task_id

            et = event.event_type
            if et == EventType.WorkflowExecutionStarted:
                a = event.attributes
                parent_domain_id = None
                if a.get("parent_workflow_domain"):
                    parent_domain_id = self.domain_resolver(a["parent_workflow_domain"])
                ms.replicate_workflow_execution_started_event(
                    parent_domain_id, workflow_id, run_id, request_id, event
                )
                self.timer_tasks.extend(self._schedule_workflow_timer_tasks(event))
                self.transfer_tasks.append(T.record_workflow_started_task())

            elif et == EventType.DecisionTaskScheduled:
                a = event.attributes
                decision = ms.replicate_decision_task_scheduled_event(
                    event.version,
                    event.event_id,
                    a.get("task_list", ""),
                    a.get("start_to_close_timeout_seconds", 0),
                    a.get("attempt", 0),
                    event.timestamp,
                    event.timestamp,
                )
                self.transfer_tasks.append(
                    T.decision_transfer_task(
                        domain_id, ms.execution_info.task_list, decision.schedule_id
                    )
                )
                if ms.is_sticky_task_list_enabled():
                    # sticky dispatch gets a ScheduleToStart timer so a
                    # dead worker's decision falls back to the normal
                    # list (reference mutableStateTaskGenerator
                    # GenerateDecisionScheduleTasks sticky branch; the
                    # timer queue clears stickiness when it fires)
                    self.timer_tasks.append(
                        T.TimerTask(
                            task_type=TimerTaskType.DecisionTimeout,
                            visibility_timestamp=event.timestamp
                            + ms.execution_info.sticky_schedule_to_start_timeout
                            * SECOND,
                            timeout_type=int(TimeoutType.ScheduleToStart),
                            event_id=decision.schedule_id,
                            schedule_attempt=decision.attempt,
                        )
                    )
                last_decision = decision

            elif et == EventType.DecisionTaskStarted:
                a = event.attributes
                decision = ms.replicate_decision_task_started_event(
                    None,
                    event.version,
                    a.get("scheduled_event_id", EMPTY_EVENT_ID),
                    event.event_id,
                    a.get("request_id", ""),
                    event.timestamp,
                )
                self.timer_tasks.append(
                    T.TimerTask(
                        task_type=TimerTaskType.DecisionTimeout,
                        visibility_timestamp=event.timestamp
                        + decision.decision_timeout * SECOND,
                        timeout_type=int(TimeoutType.StartToClose),
                        event_id=decision.schedule_id,
                        schedule_attempt=decision.attempt,
                    )
                )
                last_decision = decision

            elif et == EventType.DecisionTaskCompleted:
                ms.replicate_decision_task_completed_event(event)

            elif et == EventType.DecisionTaskTimedOut:
                a = event.attributes
                ms.replicate_decision_task_timed_out_event(
                    TimeoutType(a.get("timeout_type", int(TimeoutType.StartToClose))),
                    now=event.timestamp,
                )
                last_decision = self._replicate_transient_decision(domain_id, event, last_decision)

            elif et == EventType.DecisionTaskFailed:
                ms.replicate_decision_task_failed_event(now=event.timestamp)
                last_decision = self._replicate_transient_decision(domain_id, event, last_decision)

            elif et == EventType.ActivityTaskScheduled:
                ai = ms.replicate_activity_task_scheduled_event(
                    first_event.event_id, event
                )
                self.transfer_tasks.append(
                    T.activity_transfer_task(
                        domain_id, ms.execution_info.task_list, ai.schedule_id
                    )
                )
                self._maybe_activity_timer_task()

            elif et == EventType.ActivityTaskStarted:
                ms.replicate_activity_task_started_event(event)
                self._maybe_activity_timer_task()

            elif et == EventType.ActivityTaskCompleted:
                ms.replicate_activity_task_completed_event(event)
                self._maybe_activity_timer_task()

            elif et == EventType.ActivityTaskFailed:
                ms.replicate_activity_task_failed_event(event)
                self._maybe_activity_timer_task()

            elif et == EventType.ActivityTaskTimedOut:
                ms.replicate_activity_task_timed_out_event(event)
                self._maybe_activity_timer_task()

            elif et == EventType.ActivityTaskCancelRequested:
                ms.replicate_activity_task_cancel_requested_event(event)

            elif et == EventType.ActivityTaskCanceled:
                ms.replicate_activity_task_canceled_event(event)
                self._maybe_activity_timer_task()

            elif et == EventType.RequestCancelActivityTaskFailed:
                pass  # no mutable-state action — reference :322

            elif et == EventType.TimerStarted:
                ms.replicate_timer_started_event(event)
                self._maybe_user_timer_task()

            elif et == EventType.TimerFired:
                ms.replicate_timer_fired_event(event)
                self._maybe_user_timer_task()

            elif et == EventType.TimerCanceled:
                ms.replicate_timer_canceled_event(event)
                self._maybe_user_timer_task()

            elif et == EventType.CancelTimerFailed:
                pass  # no mutable-state action — reference :356

            elif et == EventType.StartChildWorkflowExecutionInitiated:
                a = event.attributes
                ci = ms.replicate_start_child_initiated_event(
                    first_event.event_id, event, self.id_generator()
                )
                self.transfer_tasks.append(
                    T.start_child_transfer_task(
                        self.domain_resolver(a.get("domain", "")),
                        a.get("workflow_id", ""),
                        ci.initiated_id,
                    )
                )

            elif et == EventType.StartChildWorkflowExecutionFailed:
                ms.replicate_start_child_failed_event(event)

            elif et == EventType.ChildWorkflowExecutionStarted:
                ms.replicate_child_execution_started_event(event)

            elif et == EventType.ChildWorkflowExecutionCompleted:
                ms.replicate_child_execution_completed_event(event)

            elif et == EventType.ChildWorkflowExecutionFailed:
                ms.replicate_child_execution_failed_event(event)

            elif et == EventType.ChildWorkflowExecutionCanceled:
                ms.replicate_child_execution_canceled_event(event)

            elif et == EventType.ChildWorkflowExecutionTimedOut:
                ms.replicate_child_execution_timed_out_event(event)

            elif et == EventType.ChildWorkflowExecutionTerminated:
                ms.replicate_child_execution_terminated_event(event)

            elif et == EventType.RequestCancelExternalWorkflowExecutionInitiated:
                a = event.attributes
                rci = ms.replicate_request_cancel_external_initiated_event(
                    first_event.event_id, event, self.id_generator()
                )
                rci.target_domain_id = self.domain_resolver(
                    a.get("domain", ""))
                rci.target_workflow_id = a.get("workflow_id", "")
                rci.target_run_id = a.get("run_id", "")
                rci.target_child_workflow_only = a.get(
                    "child_workflow_only", False)
                # task fields come FROM the stored info so the two can
                # never silently diverge
                self.transfer_tasks.append(
                    T.cancel_external_transfer_task(
                        rci.target_domain_id,
                        rci.target_workflow_id,
                        rci.target_run_id,
                        rci.target_child_workflow_only,
                        rci.initiated_id,
                    )
                )

            elif et == EventType.RequestCancelExternalWorkflowExecutionFailed:
                ms.replicate_request_cancel_external_failed_event(event)

            elif et == EventType.ExternalWorkflowExecutionCancelRequested:
                ms.replicate_external_workflow_execution_cancel_requested(event)

            elif et == EventType.SignalExternalWorkflowExecutionInitiated:
                a = event.attributes
                si = ms.replicate_signal_external_initiated_event(
                    first_event.event_id, event, self.id_generator()
                )
                si.target_domain_id = self.domain_resolver(
                    a.get("domain", ""))
                si.target_workflow_id = a.get("workflow_id", "")
                si.target_run_id = a.get("run_id", "")
                si.target_child_workflow_only = a.get(
                    "child_workflow_only", False)
                self.transfer_tasks.append(
                    T.signal_external_transfer_task(
                        si.target_domain_id,
                        si.target_workflow_id,
                        si.target_run_id,
                        si.target_child_workflow_only,
                        si.initiated_id,
                    )
                )

            elif et == EventType.SignalExternalWorkflowExecutionFailed:
                ms.replicate_signal_external_failed_event(event)

            elif et == EventType.ExternalWorkflowExecutionSignaled:
                ms.replicate_external_workflow_execution_signaled(event)

            elif et == EventType.MarkerRecorded:
                pass  # no mutable-state action — reference :494

            elif et == EventType.WorkflowExecutionSignaled:
                ms.replicate_workflow_execution_signaled(event)

            elif et == EventType.WorkflowExecutionCancelRequested:
                ms.replicate_workflow_execution_cancel_requested_event(event)

            elif et == EventType.WorkflowExecutionCompleted:
                ms.replicate_workflow_execution_completed_event(
                    first_event.event_id, event
                )
                self._append_finished_execution_tasks(event)

            elif et == EventType.WorkflowExecutionFailed:
                ms.replicate_workflow_execution_failed_event(
                    first_event.event_id, event
                )
                self._append_finished_execution_tasks(event)

            elif et == EventType.WorkflowExecutionTimedOut:
                ms.replicate_workflow_execution_timedout_event(
                    first_event.event_id, event
                )
                self._append_finished_execution_tasks(event)

            elif et == EventType.WorkflowExecutionCanceled:
                ms.replicate_workflow_execution_canceled_event(
                    first_event.event_id, event
                )
                self._append_finished_execution_tasks(event)

            elif et == EventType.WorkflowExecutionTerminated:
                ms.replicate_workflow_execution_terminated_event(
                    first_event.event_id, event
                )
                self._append_finished_execution_tasks(event)

            elif et == EventType.UpsertWorkflowSearchAttributes:
                ms.replicate_upsert_workflow_search_attributes_event(event)
                self.transfer_tasks.append(T.upsert_search_attributes_task())

            elif et == EventType.WorkflowExecutionContinuedAsNew:
                if not new_run_history:
                    raise ValueError("continued-as-new requires new-run history")
                new_run_ms = MutableState(domain_id=domain_id)
                if ms.version_histories is not None:
                    new_run_ms.version_histories = type(ms.version_histories).new_empty()
                new_run_builder = StateBuilder(
                    new_run_ms, self.domain_resolver, self.id_generator, self.retention_days
                )
                new_run_id = event.attributes.get("new_execution_run_id", "")
                new_run_builder.apply_events(
                    domain_id, self.id_generator(), workflow_id, new_run_id,
                    new_run_history, None,
                )
                self.new_run_transfer_tasks.extend(new_run_builder.transfer_tasks)
                self.new_run_timer_tasks.extend(new_run_builder.timer_tasks)
                ms.replicate_workflow_execution_continued_as_new_event(
                    first_event.event_id, event
                )
                self._append_finished_execution_tasks(event)

            else:
                raise ValueError(f"unknown event type {et}")

        ms.execution_info.last_first_event_id = first_event.event_id
        ms.execution_info.next_event_id = last_event.event_id + 1
        return last_event, last_decision, new_run_ms

    # -- task scheduling helpers ---------------------------------------

    def _replicate_transient_decision(
        self, domain_id: str, event: HistoryEvent, last_decision: Optional[DecisionInfo]
    ) -> Optional[DecisionInfo]:
        # reference: stateBuilder.go:227-258 — after a decision failure or
        # timeout, a transient (attempt>0) decision is scheduled in memory.
        decision = self.ms.replicate_transient_decision_task_scheduled(event.timestamp)
        if decision is not None:
            self.transfer_tasks.append(
                T.decision_transfer_task(
                    domain_id, self.ms.execution_info.task_list, decision.schedule_id
                )
            )
            return decision
        return last_decision

    def _schedule_workflow_timer_tasks(self, event: HistoryEvent) -> List[T.TimerTask]:
        # reference: stateBuilder.go scheduleWorkflowTimerTask (:731-760)
        out: List[T.TimerTask] = []
        now = event.timestamp
        workflow_timeout_ts = now + self.ms.execution_info.workflow_timeout * SECOND
        backoff_s = event.attributes.get("first_decision_task_backoff_seconds", 0)
        if backoff_s:
            workflow_timeout_ts += backoff_s * SECOND
            is_cron = event.attributes.get("initiator", 0) == 2  # CronSchedule
            out.append(
                T.TimerTask(
                    task_type=TimerTaskType.WorkflowBackoffTimer,
                    visibility_timestamp=now + backoff_s * SECOND,
                    timeout_type=int(
                        WorkflowBackoffType.Cron if is_cron else WorkflowBackoffType.Retry
                    ),
                )
            )
        out.append(
            T.TimerTask(
                task_type=TimerTaskType.WorkflowTimeout,
                visibility_timestamp=workflow_timeout_ts,
            )
        )
        return out

    def _maybe_user_timer_task(self) -> None:
        task = TimerSequence(self.ms).user_timer_task_if_needed()
        if task is not None:
            self.timer_tasks.append(task)

    def _maybe_activity_timer_task(self) -> None:
        task = TimerSequence(self.ms).activity_timer_task_if_needed()
        if task is not None:
            self.timer_tasks.append(task)

    def _append_finished_execution_tasks(self, event: HistoryEvent) -> None:
        # reference: stateBuilder.go appendTasksForFinishedExecutions (:779-792)
        self.transfer_tasks.append(T.close_execution_transfer_task())
        self.timer_tasks.append(
            T.TimerTask(
                task_type=TimerTaskType.DeleteHistoryEvent,
                visibility_timestamp=event.timestamp
                + self.retention_days * 24 * 3600 * SECOND,
            )
        )
